"""Full HIGGS-shape (10.5M x 28) on-chip measurement, round 4.

Exact vs batched(K=32) at the reference benchmark's real scale —
the HIGGS-normalized metric is linear in rows, so the ~1 ms/split
latency floor (N-independent) makes the full shape the honest best
configuration. Appends to tools/onchip_r4_results.json.
"""
import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "onchip_r4_results.json")
sys.path.insert(0, os.path.dirname(HERE))   # repo root for lightgbm_tpu


def main():
    import jax
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    n, f = 10_500_000, 28
    r = np.random.RandomState(0)
    X = r.randn(n, f).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float32)
    modes = {
        "exact": {"tree_growth": "exact"},
        "batched_k32": {"tree_growth": "batched", "tree_batch_splits": 32},
    }
    wanted = os.environ.get("FULL_SHAPE_MODES", "exact,batched_k32")
    out = {}
    for name in wanted.split(","):
        extra = modes[name.strip()]
        try:
            cfg = Config({"objective": "binary", "num_leaves": 255,
                          "verbosity": -1, **extra})
            t0 = time.time()
            ds = BinnedDataset.from_matrix(X, cfg, label=y)
            b = create_boosting(cfg, ds, create_objective(cfg), [])
            t_bin = time.time() - t0
            t0 = time.time()
            b.train_many(2)           # compile + warm
            jax.block_until_ready(b.scores)
            t_warm = time.time() - t0
            iters = 10
            t0 = time.time()
            b.train_many(iters)
            jax.block_until_ready(b.scores)
            dt = (time.time() - t0) / iters
            out[name] = {
                "s_per_iter": round(dt, 3),
                "iters_per_sec": round(1.0 / dt, 4),
                "vs_baseline": round((1.0 / dt) / (500.0 / 238.505), 4),
                "bin_s": round(t_bin, 1), "warm_s": round(t_warm, 1)}
            del b, ds
        except Exception as e:  # noqa: BLE001 - record and continue
            out[name] = {"error": repr(e)[:300]}
        print(name, out[name], flush=True)

    results = {}
    if os.path.exists(OUT):
        with open(OUT) as fh:
            results = json.load(fh)
    results["full_shape_r4"] = {"ok": True, "data": out}
    with open(OUT + ".tmp", "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
    os.replace(OUT + ".tmp", OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
