"""Per-phase performance report: spans + wave occupancy + XLA roofline.

Trains a small workload with ``observability=basic``, runs the phase
probe and XLA cost-model extraction (obs/costmodel.py), and renders the
merged picture — per-phase wall times, frontier wave accounting, and
roofline attribution (FLOPs/bytes per call, achieved rates, mfu /
membw_util on accelerators) — as ``report.md`` + ``report.json`` in
``--out-dir``. CI uploads both as artifacts; on a TPU host the same
command reports real utilization against the detected chip's peaks.
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)   # repo root for lightgbm_tpu


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default=os.path.join(HERE, "perf_report"))
    ap.add_argument("--rows", type=int, default=20000)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--num-leaves", type=int, default=31)
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs.costmodel import (roofline_markdown,
                                            roofline_snapshot)
    from lightgbm_tpu.profiling import phase_probe

    rng = np.random.RandomState(0)
    X = rng.randn(args.rows, args.features).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1,
         "num_leaves": args.num_leaves, "tree_growth": "frontier",
         "observability": "basic"},
        lgb.Dataset(X, label=y), num_boost_round=args.iters)
    impl = bst._impl
    impl.models                              # flush pending trees
    phases = phase_probe(impl)               # includes cost extraction
    # join the probe's standalone per-call wave timings into the roofline
    # (spans only cover phases that ran inside real training)
    probe_times = {k: (float(v), 1.0) for k, v in phases.items()
                   if k.startswith("frontier_hist_w")
                   and isinstance(v, (int, float))}
    snap = roofline_snapshot(extra_wall_times=probe_times)

    report = {
        "workload": {"rows": args.rows, "features": args.features,
                     "iters": args.iters, "num_leaves": args.num_leaves},
        "phases": {k: v for k, v in phases.items() if k != "roofline"},
        "roofline": snap,
    }
    json_path = os.path.join(args.out_dir, "report.json")
    with open(json_path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")

    md = ["# lightgbm_tpu perf report", "",
          "Workload: %d rows x %d features, %d iterations, %d leaves "
          "(frontier growth, observability=basic)."
          % (args.rows, args.features, args.iters, args.num_leaves), "",
          "Backend: `%s`, device kind: `%s`."
          % (snap.get("backend", "?"), snap.get("device_kind", "?")), "",
          "## Phase timings (seconds per standalone call)", "",
          "| phase | seconds |", "|---|---|"]
    for k in sorted(report["phases"]):
        v = report["phases"][k]
        if isinstance(v, (int, float)):
            md.append("| %s | %.5f |" % (k, v))
    md += ["", "## Roofline attribution (XLA cost model)", "",
           roofline_markdown(snap)]
    md_path = os.path.join(args.out_dir, "report.md")
    with open(md_path, "w") as fh:
        fh.write("\n".join(md) + "\n")
    print("wrote %s and %s" % (md_path, json_path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
