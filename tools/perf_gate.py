"""Deterministic perf-counter regression gate (tier1 CI).

Measures the semantic performance counters of a small fixed frontier
training workload (lightgbm_tpu/obs/perfgate.py: wave ladder, sweeps per
tree, compiles-after-warmup, per-wave collectives, XLA cost-model FLOPs
and bytes per entry point) and compares them against the committed
baseline ``PERF_COUNTERS.json``. Counters are host-speed independent, so
the gate is meaningful on any CI runner; tolerances live in the baseline
itself (exact for structure, relative for XLA accounting drift).

Exit 0 = every counter within its declared tolerance; 1 = drift, with an
aligned diff table naming each violated counter and both values.
Intentional changes re-baseline with ``--write-baseline`` and commit the
result (docs/Observability.md documents the workflow).

The script re-execs itself once with ``JAX_PLATFORMS=cpu`` and an
8-virtual-device ``XLA_FLAGS`` so the sharded-grower collective counter
can be measured anywhere — both must be set before jax first imports.
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)   # repo root for lightgbm_tpu

_REEXEC_FLAG = "_LGBM_PERF_GATE_CHILD"
_VDEV_FLAG = "--xla_force_host_platform_device_count=8"


def _reexec_with_virtual_devices() -> None:
    """Counters must be platform-pinned and see 8 devices; both env vars
    only take effect before jax's first import, hence the re-exec."""
    if os.environ.get(_REEXEC_FLAG) == "1":
        return
    env = dict(os.environ)
    env[_REEXEC_FLAG] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if _VDEV_FLAG not in flags:
        env["XLA_FLAGS"] = (flags + " " + _VDEV_FLAG).strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main() -> int:
    _reexec_with_virtual_devices()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "PERF_COUNTERS.json"),
                    help="committed baseline to gate against / write")
    ap.add_argument("--write-baseline", action="store_true",
                    help="measure and (re)write the baseline, no gating")
    ap.add_argument("--out", default="",
                    help="also write the measured counters JSON here")
    args = ap.parse_args()

    from lightgbm_tpu.obs import perfgate

    counters, workload = perfgate.measure()
    if args.out:
        with open(args.out, "w") as fh:
            json.dump({"workload": workload, "counters": counters}, fh,
                      indent=2, sort_keys=True)
            fh.write("\n")

    if args.write_baseline:
        baseline = perfgate.make_baseline(counters, workload)
        perfgate.write_baseline(args.baseline, baseline)
        print("wrote %s (%d counters)" % (args.baseline, len(counters)))
        return 0

    if not os.path.exists(args.baseline):
        print("perf_gate: no baseline at %s — run with --write-baseline "
              "and commit it" % args.baseline, file=sys.stderr)
        return 1
    baseline = perfgate.load_baseline(args.baseline)
    violations, table = perfgate.compare(baseline, counters)
    print(table)
    if violations:
        print("perf_gate: %d counter(s) drifted beyond declared "
              "tolerances:" % len(violations), file=sys.stderr)
        for v in violations:
            print("  %(counter)s: baseline=%(baseline)s "
                  "measured=%(measured)s (%(reason)s)" % v,
                  file=sys.stderr)
        return 1
    print("perf_gate: all %d counters within tolerance."
          % len(baseline.get("counters", {})))
    return 0


if __name__ == "__main__":
    sys.exit(main())
