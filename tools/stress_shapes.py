"""Scale-shaped EFB ingest + training stress (docs/Performance.md).

Synthesizes Expo-shaped (one-hot blocks + dense, ~95% sparse) and
Allstate-shaped (4228-column one-hot heavy) matrices — the structured
sparsity of the reference's large benchmarks (Experiments.rst:110-147) —
then ingests through EFB/nbit packing and times a few training
iterations. Run on TPU for the recorded numbers; falls back to CPU.

    python tools/stress_shapes.py [--rows-expo N] [--rows-allstate N]
"""
import argparse
import os
import resource
import sys
import time

import numpy as np
from scipy import sparse

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Backend selection BEFORE any backend init. JAX_PLATFORMS=cpu is forced
# through jax.config (the ambient site hook can reset the env var, verify
# SKILL.md gotcha). Anything else — including the image's globally-set
# JAX_PLATFORMS=axon — goes through bench.py's subprocess probe with a
# hard timeout, because TPU backend init can HANG, not just fail, when
# the tunnel is down; on probe failure we fall back to CPU.
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    import bench
    _info = bench._select_backend()
    print("backend: %s%s" % (_info.get("backend"),
                             " (CPU fallback: %s)" % _info.get("probe_error")
                             if _info.get("fallback") else ""), flush=True)


def onehot_blocks(n, groups, card, seed, extra_dense):
    r = np.random.RandomState(seed)
    parts = []
    for _ in range(groups):
        choice = r.randint(0, card, n)
        parts.append(sparse.csr_matrix(
            (np.ones(n, np.float32), (np.arange(n), choice)),
            shape=(n, card)))
    parts.append(sparse.csr_matrix(r.randn(n, extra_dense)
                                   .astype(np.float32)))
    return sparse.hstack(parts, format="csr")


def run_shape(name, n, groups, card, extra_dense, iters, leaves):
    if n <= 0:
        print("%s: skipped (rows=0)" % name)
        return
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    S = onehot_blocks(n, groups, card, 0, extra_dense)
    sig = np.asarray(S[:, -2].todense()).ravel()
    y = (sig + 0.3 * np.random.RandomState(1).randn(n) > 0) \
        .astype(np.float32)
    print("%s: %d x %d, %.2f%% nnz" % (
        name, S.shape[0], S.shape[1], 100 * S.nnz / (S.shape[0] * S.shape[1])))
    # STRESS_GROWTH overrides. Default batched: at these WIDE shapes the
    # round-4 on-chip comparison favors batched (Expo 0.47 vs exact 0.55
    # s/iter; Allstate 1.52 vs 1.93) — many stored columns make the
    # per-split fused pass expensive, and batching amortizes it; the
    # narrow HIGGS shape favors exact (docs/Performance.md).
    growth = os.environ.get("STRESS_GROWTH", "batched")
    cfg = Config({"objective": "binary", "verbosity": 1,
                  "num_leaves": leaves, "tree_growth": growth,
                  "tree_batch_splits": 16})
    t0 = time.time()
    ds = BinnedDataset.from_matrix(S, cfg, label=y)
    print("%s ingest: %.0fs, %d features -> %d stored cols, "
          "binned %.2f GB, rss %.2f GB" % (
              name, time.time() - t0, S.shape[1], ds.num_columns,
              ds.X_binned.nbytes / 1e9,
              resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6))
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    t0 = time.time()
    b.train_many(iters)
    jax.block_until_ready(b.scores)
    compile_s = time.time() - t0
    t0 = time.time()
    b.train_many(iters)
    jax.block_until_ready(b.scores)
    dt = (time.time() - t0) / iters
    print("%s train (%s, %s L=%d): %.2f s/iter "
          "(compile+%d iters: %.0fs)" % (
              name, jax.default_backend(), growth, leaves, dt, iters,
              compile_s))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-expo", type=int, default=1_100_000)
    ap.add_argument("--rows-allstate", type=int, default=400_000)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--leaves", type=int, default=63)
    args = ap.parse_args()
    run_shape("EXPO-shaped", args.rows_expo, 20, 34, 20, args.iters,
              args.leaves)
    run_shape("ALLSTATE-shaped", args.rows_allstate, 120, 35, 28,
              args.iters, args.leaves)


if __name__ == "__main__":
    main()
