"""Static-analysis gate (tier1 CI): JAX-aware lint + compiled-program
audit (lightgbm_tpu/analysis/).

``--lint`` runs the AST lint (astlint.py rule catalog LGL101-LGL107)
over the package source; any unsuppressed finding fails the gate.
``--audit`` lowers every hot entry point (fused train block, each
wave-width ladder bucket, materialize, the sharded grower under the
8-virtual-device mesh, serving predict buckets) and verifies the
committed ``ANALYSIS_BASELINE.json`` invariants: jaxpr structural
fingerprints, exact collective schedules, zero f64 primitives, zero
host callbacks, and train-block donation effectiveness.  With neither
flag, both run.

Exit 0 = clean; 1 = findings/violations, each naming the file+rule or
entry+invariant.  Intentional program changes re-baseline with
``--write-baseline`` and commit the result (docs/StaticAnalysis.md
documents the workflow; the baseline writer refuses states that break
the hard invariants).

Re-execs itself once with ``JAX_PLATFORMS=cpu`` and an 8-virtual-device
``XLA_FLAGS`` so the sharded-grower collective schedule can be audited
anywhere — both must be set before jax first imports.
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)   # repo root for lightgbm_tpu

_REEXEC_FLAG = "_LGBM_ANALYZE_CHILD"
_VDEV_FLAG = "--xla_force_host_platform_device_count=8"


def _reexec_with_virtual_devices() -> None:
    """The audit must be platform-pinned and see 8 devices; both env
    vars only take effect before jax's first import, hence the re-exec."""
    if os.environ.get(_REEXEC_FLAG) == "1":
        return
    env = dict(os.environ)
    env[_REEXEC_FLAG] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if _VDEV_FLAG not in flags:
        env["XLA_FLAGS"] = (flags + " " + _VDEV_FLAG).strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _run_lint(report: dict) -> int:
    from lightgbm_tpu.analysis import astlint
    findings = astlint.lint_package()
    report["lint"] = {"findings": [vars(f) for f in findings]}
    for f in findings:
        print(f.format())
    if findings:
        print("analyze: %d lint finding(s) — fix or suppress with "
              "`# lgbm-lint: disable=<RULE> <reason>`" % len(findings),
              file=sys.stderr)
        return 1
    print("analyze: lint clean (%d rules)" % len(astlint.LINT_RULES))
    return 0


def _run_audit(report: dict, baseline_path: str,
               write_baseline: bool) -> int:
    from lightgbm_tpu.analysis import auditor
    measured = auditor.collect_audit()
    report["audit"] = {"measured": measured}

    if write_baseline:
        path = auditor.write_baseline(measured, baseline_path)
        print("wrote %s (%d entries)" % (path, len(measured["entries"])))
        return 0

    if not os.path.exists(baseline_path):
        print("analyze: no baseline at %s — run with --write-baseline "
              "and commit it" % baseline_path, file=sys.stderr)
        return 1
    baseline = auditor.load_baseline(baseline_path)
    violations, table = auditor.compare_audit(baseline, measured)
    auditor.publish(measured, violations)
    report["audit"]["violations"] = violations
    print(table)
    if violations:
        print("analyze: %d audit violation(s):" % len(violations),
              file=sys.stderr)
        for v in violations:
            print("  %(entry)s / %(invariant)s: baseline=%(baseline)s "
                  "measured=%(measured)s (%(reason)s)" % v,
                  file=sys.stderr)
        return 1
    print("analyze: all %d audited entries match the baseline."
          % len(measured["entries"]))
    return 0


def main() -> int:
    _reexec_with_virtual_devices()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--lint", action="store_true",
                    help="run the AST lint over the package source")
    ap.add_argument("--audit", action="store_true",
                    help="run the jaxpr/HLO audit against the baseline")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "ANALYSIS_BASELINE.json"),
                    help="committed audit baseline to gate against / write")
    ap.add_argument("--write-baseline", action="store_true",
                    help="measure and (re)write the audit baseline, "
                         "no gating")
    ap.add_argument("--out", default="",
                    help="also write the findings/violations report "
                         "JSON here (CI artifact)")
    args = ap.parse_args()
    do_lint = args.lint or not (args.lint or args.audit)
    do_audit = args.audit or not (args.lint or args.audit)

    report: dict = {}
    rc = 0
    if do_lint:
        rc |= _run_lint(report)
    if do_audit:
        rc |= _run_audit(report, args.baseline, args.write_baseline)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
