"""Merge per-process JSON-lines event streams into one timeline.

Distributed runs write one ``obs_event_file`` per process (each record
stamped with ``process``/``host`` static fields plus a per-stream ``seq``)
and, on a crash, one ``<obs_event_file>.<process>.crash.jsonl`` flight
recorder dump per process.  This tool zips any number of those streams
into a single time-ordered ``timeline.jsonl``:

- **k-way head merge**: streams are consumed through a heap that only
  ever compares the current HEAD of each stream, so records within one
  stream always keep their original order even when that stream's clock
  jumps backwards (NTP step, container migration) — cross-stream order
  is by wall clock, in-stream order is authoritative.
- **monotonic tie-break**: equal timestamps order by the stream's own
  ``seq`` (the EventStream's monotonic per-process counter), then by
  stream name, so the merge is deterministic across runs and platforms.
- every output record gains a ``stream`` field (the source file's
  basename) so a merged timeline still attributes each line.

Usage::

    python tools/merge_events.py out/events.*.jsonl --out timeline.jsonl

Exit 0 on success; malformed lines are counted, reported on stderr and
skipped (a torn final line from a SIGKILL'd process must not sink the
whole post-mortem).
"""
import argparse
import heapq
import json
import os
import sys
from typing import Iterator, List, Optional, TextIO, Tuple


def _records(fh: TextIO, stream: str):
    """Yield parsed records; count (don't raise on) malformed lines."""
    for lineno, line in enumerate(fh, 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            yield None, (stream, lineno)
            continue
        if not isinstance(rec, dict):
            yield None, (stream, lineno)
            continue
        yield rec, None


class _Stream:
    """One input file: exposes head-record sort keys for the heap."""

    def __init__(self, path: str):
        self.name = os.path.basename(path)
        self._fh = open(path)
        self._it = _records(self._fh, self.name)
        self.head: Optional[dict] = None
        self.bad: List[Tuple[str, int]] = []
        self._advance()

    def _advance(self) -> None:
        for rec, err in self._it:
            if err is not None:
                self.bad.append(err)
                continue
            self.head = rec
            return
        self.head = None
        self._fh.close()

    def key(self) -> Tuple[float, int, str]:
        rec = self.head or {}
        try:
            ts = float(rec.get("ts", 0.0))
        except (TypeError, ValueError):
            ts = 0.0
        try:
            seq = int(rec.get("seq", -1))
        except (TypeError, ValueError):
            seq = -1
        return ts, seq, self.name

    def pop(self) -> dict:
        rec = self.head
        self._advance()
        return rec


def merge(paths: List[str]) -> Iterator[dict]:
    """Yield records from ``paths`` time-ordered (see module docstring).
    Each yielded record carries a ``stream`` field."""
    streams = [_Stream(p) for p in paths]
    heap = [(s.key(), i) for i, s in enumerate(streams)
            if s.head is not None]
    heapq.heapify(heap)
    while heap:
        _key, i = heapq.heappop(heap)
        s = streams[i]
        rec = s.pop()
        rec["stream"] = s.name
        yield rec
        if s.head is not None:
            heapq.heappush(heap, (s.key(), i))
    bad = [b for s in streams for b in s.bad]
    if bad:
        for stream, lineno in bad[:10]:
            print("merge_events: skipped malformed line %s:%d"
                  % (stream, lineno), file=sys.stderr)
        if len(bad) > 10:
            print("merge_events: ... and %d more" % (len(bad) - 10),
                  file=sys.stderr)


def build_span_trees(records) -> dict:
    """Reconstruct request-scoped span trees from merged records.

    A reqtrace span record (obs/reqtrace.py) is an ``event == "span"``
    line carrying ``trace``/``span_id``/``parent`` — the ``trace`` field
    distinguishes it from the legacy per-phase Tracer spans, which share
    the event name.  Returns ``{trace_id: {"spans": [...], "roots":
    [...], "orphans": [...]}}`` where each span dict gains a
    ``children`` list of span_ids.  Spans whose ``parent`` is not in the
    trace (a fleet hop whose upstream stream was not merged in, or a
    dropped batch trace) land in ``orphans`` — still listed, never an
    error: a partial post-mortem beats none.
    """
    traces: dict = {}
    for rec in records:
        if rec.get("event") != "span" or "trace" not in rec:
            continue
        traces.setdefault(rec["trace"], []).append(dict(rec))
    out = {}
    for tid, spans in traces.items():
        by_id = {s["span_id"]: s for s in spans}
        roots, orphans = [], []
        for s in spans:
            s.setdefault("children", [])
        for s in spans:
            parent = s.get("parent")
            if parent is None:
                roots.append(s)
            elif parent in by_id:
                by_id[parent]["children"].append(s["span_id"])
            else:
                orphans.append(s)
        out[tid] = {"spans": spans, "roots": roots, "orphans": orphans}
    return out


def main() -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process obs event streams into one "
                    "time-ordered timeline")
    ap.add_argument("inputs", nargs="+",
                    help="JSON-lines event files (streams + crash dumps)")
    ap.add_argument("--out", default="-",
                    help="output path (default: stdout)")
    ap.add_argument("--span-trees", default="",
                    help="also write reconstructed request span trees "
                    "(one JSON object keyed by trace id) to this path")
    args = ap.parse_args()
    for p in args.inputs:
        if not os.path.exists(p):
            print("merge_events: no such file: %s" % p, file=sys.stderr)
            return 2
    out = sys.stdout if args.out == "-" else open(args.out, "w")
    n = 0
    spanbuf = [] if args.span_trees else None
    try:
        for rec in merge(args.inputs):
            out.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
            if spanbuf is not None:
                spanbuf.append(rec)
            n += 1
    finally:
        if out is not sys.stdout:
            out.close()
    if spanbuf is not None:
        trees = build_span_trees(spanbuf)
        with open(args.span_trees, "w") as fh:
            json.dump(trees, fh, sort_keys=True, default=str)
        print("merge_events: %d trace(s) -> %s"
              % (len(trees), args.span_trees), file=sys.stderr)
    print("merge_events: %d record(s) from %d stream(s)%s"
          % (n, len(args.inputs),
             "" if args.out == "-" else " -> %s" % args.out),
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
