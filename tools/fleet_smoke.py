"""Continuous-training fleet end-to-end smoke (tier1 CI).

Runs the whole docs/Fleet.md loop the way an operator's fleet would:
TWO replica serving PROCESSES plus a refit worker, coordinating only
through a shared checkpoint directory and file-KV namespace:

1. train a small model with a checkpoint + training data profile; spawn
   replica processes "a" and "b" (this script re-execed with
   ``--serve-replica``), each booting ``build_app`` with
   ``fleet_kv_dir`` + ``checkpoint_dir`` — the rolling-deploy
   coordinators hot-roll the initial snapshot in sorted order, warm
   every bucket, and announce readiness over the KV namespace;
2. drive continuous DRIFTED traffic at both HTTP front-ends and assert
   both replicas reach ``drift: warn``;
3. the refit worker re-estimates leaf values on the drifted window
   (``Refitter``, structure preserved) and publishes the result with
   ``CheckpointManager.save_refit`` + the window's data profile;
4. the fleet rolls the refit snapshot one replica at a time UNDER the
   live traffic; afterwards assert:
   - zero dropped/errored requests and zero request shed,
   - zero recompiles after warmup in both replica processes (the
     hot-roll prewarmed the refit generation off the request path),
   - served p99 stays under the budget,
   - drift recovers to ``ok`` on the refit window's profile,
   - the served trees are structure-identical to the originals with
     different leaf values,
   - ``/stats/cluster`` + ``/metrics/cluster`` report a converged
     2-replica fleet on the refit snapshot.

Exit code 0 = every assertion holds. The summary JSON goes to ``--out``
(and stdout) for the CI artifact.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu


def _get(base: str, path: str) -> bytes:
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return r.read()


def _post(base: str, path: str, doc) -> dict:
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _wait(pred, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def serve_replica(name: str, workdir: str) -> int:
    """One replica process: build_app over the shared checkpoint + KV
    dirs, roll the initial snapshot, warm up, publish the HTTP base URL
    under ``http/<name>``, then serve until SIGTERM."""
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(workdir, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.fleet import FileKvClient
    from lightgbm_tpu.serving.server import build_app, make_server

    cfg = Config({"objective": "regression", "verbosity": -1,
                  "checkpoint_dir": os.path.join(workdir, "ckpt"),
                  "fleet_kv_dir": os.path.join(workdir, "kv"),
                  "fleet_replica": name,
                  "fleet_announce_period_s": 0.1,
                  "serve_min_bucket": 16, "serve_max_batch": 128,
                  "obs_drift_warn_psi": 0.25, "obs_drift_min_rows": 128})
    app = build_app(cfg)
    if not _wait(lambda: app.watcher._last_id >= 0, timeout_s=60.0):
        print("replica %s: initial snapshot never rolled" % name,
              file=sys.stderr)
        return 1
    app.engine.warmup()            # marks the recompile floor
    server = make_server(app, port=0)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    FileKvClient(cfg.fleet_kv_dir).key_value_set("http/" + name, base)
    signal.signal(signal.SIGTERM, lambda *_: threading.Thread(
        target=server.shutdown, daemon=True).start())
    try:
        server.serve_forever()
    finally:
        server.server_close()
        app.close()
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="fleet_smoke_out",
                    help="checkpoints + KV namespace land here")
    ap.add_argument("--out", default="", help="write the summary JSON here")
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--p99-budget-ms", type=float, default=750.0)
    ap.add_argument("--serve-replica", default="",
                    help=argparse.SUPPRESS)   # internal: replica mode
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    if args.serve_replica:
        return serve_replica(args.serve_replica, args.workdir)
    ckpt_dir = os.path.join(args.workdir, "ckpt")

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import callback, engine
    from lightgbm_tpu.checkpoint.manager import CheckpointManager
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.fleet import FileKvClient, Refitter, ReplicaAnnouncer
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.obs.drift import DataProfile

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg), flush=True)

    # ---- 1. train with a checkpoint + data profile ---------------------
    r = np.random.RandomState(0)
    n, f = 2000, 6
    X = r.randn(n, f).astype(np.float32)

    def label_of(rows):
        return (rows[:, 0] + 0.5 * rows[:, 1]).astype(np.float32)

    y = label_of(X) + 0.2 * r.randn(n).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15,
              "verbosity": -1, "obs_modelstats": True}
    bst = engine.train(params, lgb.Dataset(X, label=y),
                       num_boost_round=args.rounds,
                       callbacks=[callback.checkpoint(ckpt_dir, period=1)])
    base_id = CheckpointManager(ckpt_dir).latest_model()[0]

    # ---- 2. spawn the replica processes --------------------------------
    kv = FileKvClient(os.path.join(args.workdir, "kv"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = {name: subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--serve-replica", name, "--workdir", args.workdir], env=env)
        for name in ("a", "b")}
    summary = {}
    drift_scale, drift_shift = 2.0, 3.0
    stop_traffic = threading.Event()
    lock = threading.Lock()
    counts = {"sent": 0, "errors": 0, "overloaded": 0}

    def traffic(base, seed):
        rs = np.random.RandomState(seed)
        while not stop_traffic.is_set():
            rows = rs.randn(32, f) * drift_scale + drift_shift
            try:
                out = _post(base, "/predict",
                            {"model": "default", "data": rows.tolist()})
                ok = len(out.get("predictions", [])) == 32
            except urllib.error.HTTPError as e:
                with lock:
                    counts["overloaded" if e.code == 503 else "errors"] += 1
                continue
            except Exception:
                with lock:
                    counts["errors"] += 1
                continue
            with lock:
                counts["sent"] += 1
                counts["errors"] += 0 if ok else 1

    threads = []
    try:
        # replicas announce their HTTP base once rolled + warmed
        check(_wait(lambda: all(kv.try_get("http/" + m) for m in procs),
                    timeout_s=180.0),
              "both replica processes came up warmed")
        bases = {m: kv.try_get("http/" + m) for m in procs}
        replicas = sorted(bases.items())

        def announced(field="snap_id"):
            fleet = ReplicaAnnouncer.read_fleet(kv)
            return {m: fleet.get(m, {}).get(field) for m in procs}

        check(all(v == base_id for v in announced().values()),
              "both replicas hot-rolled the initial snapshot %d" % base_id)

        def drift_of(base):
            return json.loads(_get(base, "/healthz")).get("drift")

        # ---- 3. drifted live traffic -> both replicas warn -------------
        threads = [threading.Thread(target=traffic, args=(b, i), daemon=True)
                   for i, (_, b) in enumerate(replicas)]
        for t in threads:
            t.start()
        for name, base in replicas:
            check(_wait(lambda: drift_of(base) == "warn"),
                  "replica %s reached drift: warn on shifted traffic" % name)

        # ---- 4. refit worker: re-estimate leaves on the fresh window ---
        t0 = time.perf_counter()
        rw = np.random.RandomState(7)
        Xw = (rw.randn(n, f) * drift_scale + drift_shift).astype(np.float32)
        yw = label_of(Xw) + 0.2 * rw.randn(n).astype(np.float32)
        refitted = Refitter(bst).refit(Xw, yw, decay_rate=0.0)
        window = BinnedDataset.from_matrix(Xw, Config(dict(params)), label=yw)
        entry = CheckpointManager(ckpt_dir).save_refit(
            refitted, data_profile=DataProfile.from_binned_dataset(window))
        refit_s = time.perf_counter() - t0
        refit_id = int(entry["id"])
        check(refit_id > base_id, "refit snapshot %d published" % refit_id)

        # ---- 5. rolling deploy under live traffic ----------------------
        check(_wait(lambda: all(v == refit_id
                                for v in announced().values()),
                    timeout_s=120.0),
              "both replicas rolled the refit snapshot under traffic")
        for name, base in replicas:
            check(_wait(lambda: drift_of(base) == "ok", timeout_s=30.0),
                  "replica %s drift recovered on the refit profile" % name)
        time.sleep(0.5)              # a little steady-state post-roll
        stop_traffic.set()
        for t in threads:
            t.join(timeout=10.0)

        # ---- 6. fleet invariants ---------------------------------------
        with lock:
            sent, errors = counts["sent"], counts["errors"]
            overloaded = counts["overloaded"]
        check(sent > 50, "drove %d live requests through the fleet" % sent)
        check(errors == 0, "zero dropped/errored requests (got %d)" % errors)
        check(overloaded == 0, "zero shed requests (got %d)" % overloaded)
        stats = {name: json.loads(_get(b, "/stats")) for name, b in replicas}
        for name, _ in replicas:
            snap = stats[name]
            check(snap.get("recompiles_after_warmup", -1) == 0,
                  "replica %s: zero recompiles after warmup (got %s)"
                  % (name, snap.get("recompiles_after_warmup")))
            check(snap.get("errors") == 0 and snap.get("shed") == 0,
                  "replica %s: no server-side errors or shed" % name)
            p99 = snap.get("latency_ms", {}).get("p99_ms", 1e9)
            check(p99 < args.p99_budget_ms,
                  "replica %s: p99 %.1f ms under %.0f ms budget"
                  % (name, p99, args.p99_budget_ms))
            check(snap.get("replica", {}).get("snap_id") == refit_id,
                  "replica %s /stats announces the refit snapshot" % name)

        served = lgb.Booster(
            model_file=CheckpointManager(ckpt_dir).latest_model()[1])
        same_structure = all(
            np.array_equal(s.split_feature, t.split_feature) and
            np.array_equal(s.threshold, t.threshold)
            for s, t in zip(served._impl.models, bst._impl.models))
        changed_leaves = sum(
            not np.array_equal(s.leaf_value, t.leaf_value)
            for s, t in zip(served._impl.models, bst._impl.models))
        check(same_structure, "served trees are structure-identical")
        check(changed_leaves == len(bst._impl.models),
              "every served leaf table was re-estimated (%d/%d)"
              % (changed_leaves, len(bst._impl.models)))

        cluster = json.loads(_get(replicas[0][1], "/stats/cluster"))
        check(cluster["fleet"]["live"] == 2,
              "/stats/cluster sees 2 live replicas")
        check(cluster["fleet"]["snap_id_min"] == refit_id
              and cluster["fleet"]["snap_id_max"] == refit_id
              and not cluster["fleet"]["rolling"],
              "/stats/cluster shows a converged fleet on snapshot %d"
              % refit_id)
        prom = _get(replicas[1][1], "/metrics/cluster").decode()
        check('lgbm_fleet_replica_up{replica="a"} 1' in prom
              and 'lgbm_fleet_replica_up{replica="b"} 1' in prom,
              "/metrics/cluster exports per-replica up gauges")
        check("lgbm_fleet_live_replicas 2" in prom,
              "/metrics/cluster exports the live-replica count")

        summary = {"rounds": args.rounds, "requests": sent,
                   "refit_snapshot": refit_id, "refit_s": round(refit_s, 3),
                   "p99_ms": {name: stats[name]["latency_ms"]["p99_ms"]
                              for name, _ in replicas},
                   "cluster": cluster["fleet"]}
    finally:
        stop_traffic.set()
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()

    summary["failures"] = failures
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
