"""Round-5 start-of-round on-chip validation in ONE command.

Run this THE MOMENT hardware answers, before feature work (the round-4
lesson: every CPU-proxied perf decision inverted on chip, and the
tunnel dies unpredictably — front-load hardware truth). Appends each
result to ``tools/onchip_r5_results.json`` as it lands; rerun resumes.

    python tools/onchip_r5.py [--redo]

Steps:
  probe            backend + matmul sanity (also detects degraded-tunnel
                   states: round 4 saw ~6x all-workload slowdowns and
                   multi-hour hangs — compare against ~0.1-1 ms/matmul)
  kernel_parity    ALL Pallas kernels vs scatter references ON HARDWARE:
                   base digit kernel, slots6, part-tiles, repack
                   partition_tiles (the round-4 refactor shares
                   _digit_contract; this revalidates the compiled forms)
  bench_default    bench.py as the driver runs it (exact growth, packed
                   single-gather, rc auto) — expect ~2.3-2.6 raw on a
                   healthy v5e, ~0.4 in a degraded window
  bench_batched    BENCH_TREE_GROWTH=batched (K=32) comparison point
"""
import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
OUT = os.path.join(HERE, "onchip_r5_results.json")


def load():
    if os.path.exists(OUT):
        with open(OUT) as f:
            return json.load(f)
    return {}


def save(results):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
    os.replace(tmp, OUT)


def run_step(name, code_or_cmd, results, timeout, env=None, redo=False):
    if name in results and not redo and results[name].get("ok"):
        print("[skip] %s (already recorded)" % name, flush=True)
        return True
    print("[run ] %s (timeout %ds)" % (name, timeout), flush=True)
    t0 = time.time()
    cmd = code_or_cmd if isinstance(code_or_cmd, list) \
        else [sys.executable, "-c", code_or_cmd]
    full_env = dict(os.environ, **(env or {}))
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=full_env)
        rec = {"ok": r.returncode == 0, "seconds": round(time.time() - t0, 1)}
        for line in (r.stdout or "").splitlines():
            if line.startswith("RESULT:"):
                rec["data"] = json.loads(line[len("RESULT:"):])
            elif line.startswith("{") and line.rstrip().endswith("}"):
                try:
                    rec["data"] = json.loads(line)
                except ValueError:
                    pass
        if r.returncode != 0:
            rec["error"] = (r.stderr or r.stdout or "")[-800:]
    except subprocess.TimeoutExpired:
        rec = {"ok": False, "seconds": round(time.time() - t0, 1),
               "error": "timeout after %ds" % timeout}
    results[name] = rec
    save(results)
    print("[%s] %s %s" % ("ok  " if rec["ok"] else "FAIL", name,
                          rec.get("data", rec.get("error", ""))), flush=True)
    return rec["ok"]


PROBE = r"""
import json, time
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((4096, 4096), jnp.bfloat16)
t1 = time.time(); y = (x @ x).block_until_ready(); t2 = time.time()
for _ in range(5):
    y = (x @ x).block_until_ready()
t3 = time.time()
print("RESULT:" + json.dumps({
    "platform": d[0].platform, "kind": str(getattr(d[0], "device_kind", "?")),
    "init_s": round(t1 - t0, 1),
    "matmul_ms": round((t3 - t2) / 5 * 1000, 2)}))
"""

KERNEL_PARITY = r"""
import json
import numpy as np
import jax.numpy as jnp
from lightgbm_tpu.core.histogram import build_histogram
from lightgbm_tpu.core.histogram_pallas import (build_histogram_slots6,
                                               build_histogram_part_tiles)
from lightgbm_tpu.core.repack_pallas import partition_tiles
r = np.random.RandomState(7)
n, f, b = 65536, 28, 256
xb = r.randint(0, b, (n, f)).astype(np.uint8)
g = r.randn(n).astype(np.float32)
h = np.abs(r.randn(n)).astype(np.float32)
m = (r.rand(n) > 0.3).astype(np.float32)
out = {}
ref = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                 jnp.asarray(h), jnp.asarray(m),
                                 num_bins=b, impl="scatter"))
pal = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                 jnp.asarray(h), jnp.asarray(m),
                                 num_bins=b, impl="pallas"))
out["base_vs_scatter_max"] = float(np.abs(pal - ref).max())
hi = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                jnp.asarray(h), jnp.asarray(m),
                                num_bins=b, impl="pallas_highest"))
out["highest_vs_scatter_max"] = float(np.abs(hi - ref).max())
# slots6: K parent slots + go-left selector -> both children's channels
K = 8
slot = r.randint(-1, K, n).astype(np.int32)
sel = (r.rand(n) > 0.5).astype(np.float32)
vals = np.stack([g * m, h * m, m])
s6 = np.asarray(build_histogram_slots6(
    jnp.asarray(xb), jnp.asarray(slot), jnp.asarray(sel),
    jnp.asarray(vals), num_bins=b, n_slots=K))
err = 0.0
for s in range(K):
    msk = slot == s
    for ch in range(6):
        w = sel[msk] if ch < 3 else 1 - sel[msk]
        v = vals[ch % 3, msk] * w
        refc = np.zeros((f, b), np.float32)
        for j in range(f):
            np.add.at(refc[j], xb[msk, j], v)
        err = max(err, float(np.abs(s6[s, :, :, ch] - refc).max()))
out["slots6_vs_scatter_max"] = err
# part-tiles: tile-pure segments
tile = 2048
T = n // tile
ts = np.full(T, -1, np.int32); ts[: T // 2] = np.arange(T // 2) % 4
tf = np.zeros(T, np.int32)
for t in range(T // 2):
    tf[t] = 1 if t == 0 or ts[t] != ts[t - 1] else 0
vals_pt = vals.copy(); vals_pt[:, (T // 2) * tile:] = 0.0
pt = np.asarray(build_histogram_part_tiles(
    jnp.asarray(np.ascontiguousarray(xb.T)), jnp.asarray(sel),
    jnp.asarray(vals_pt), jnp.asarray(ts), jnp.asarray(tf),
    num_bins=b, n_slots=4))
err = 0.0
for s in range(4):
    rows = np.concatenate([np.arange(t * tile, (t + 1) * tile)
                           for t in range(T // 2) if ts[t] == s])
    for ch in range(6):
        w = sel[rows] if ch < 3 else 1 - sel[rows]
        v = vals_pt[ch % 3, rows] * w
        refc = np.zeros((f, b), np.float32)
        for j in range(f):
            np.add.at(refc[j], xb[rows, j], v)
        err = max(err, float(np.abs(pt[s, :, :, ch] - refc).max()))
out["part_tiles_vs_scatter_max"] = err
# repack: exact in-tile partition
rows128 = r.randint(0, 256, (8192, 128)).astype(np.uint8)
gl = r.rand(8192) < 0.4
o, cnt = partition_tiles(jnp.asarray(rows128), jnp.asarray(gl),
                         row_tile=512)
o = np.asarray(o)
ok = True
for t in range(16):
    sl = slice(t * 512, (t + 1) * 512)
    gg = gl[sl]
    ok = ok and np.array_equal(
        o[sl], np.concatenate([rows128[sl][gg], rows128[sl][~gg]])) \
        and int(cnt[t]) == int(gg.sum())
out["repack_exact"] = bool(ok)
print("RESULT:" + json.dumps(out))
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--redo", action="store_true")
    args = ap.parse_args()
    results = load()
    redo = args.redo

    if not run_step("probe", PROBE, results, timeout=360, redo=redo):
        print("backend unreachable — stopping (results preserved)")
        return 1
    run_step("kernel_parity", KERNEL_PARITY, results, timeout=900,
             redo=redo)
    bench_env = {"BENCH_BACKEND_TRIES": "1", "BENCH_BACKEND_TIMEOUT": "240"}
    run_step("bench_default", [sys.executable, "bench.py"], results,
             timeout=1800, env=bench_env, redo=redo)
    run_step("bench_batched", [sys.executable, "bench.py"], results,
             timeout=1800,
             env=dict(bench_env, BENCH_TREE_GROWTH="batched"), redo=redo)
    print("\nall recorded in", OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
