"""Serving load test: sustained concurrent traffic across a live hot-roll,
gating the SLO story end to end — the tier1 proof behind docs/Serving.md.

serve_smoke.py proves the single-threaded contract (zero recompiles, exact
parity). This driver proves the production one: N client threads push
randomized batches through a MicroBatchQueue while a CheckpointWatcher
(attached to the engine, so every roll prewarms off the request path)
hot-rolls a NEWER model snapshot into the registry mid-traffic. Asserts:

- zero predictor-cache misses and zero XLA backend compiles after warmup,
  ACROSS the roll — the staged bundle's compiles are credited to the
  warmup floor by ServingEngine.stage_and_prewarm, so any uncredited
  compile on the request path fails the gate;
- the roll actually happened (registry generation bumped) and post-roll
  outputs match the NEW Booster's predictions to 1e-6 (refs for both
  model generations are computed BEFORE warmup, so the reference path's
  own compilations never pollute the post-warmup count);
- client-observed p99 latency (queue wait + device call) stays under
  ``--p99-ms`` over the whole run, roll included.

Prints ONE JSON line with the verdict, per-bucket device-latency
quantiles, and the metrics snapshot. Exit 0 on pass, 1 on any violation.

Usage:
  python tools/load_test.py [--threads 4] [--requests 200] [--p99-ms 250]
CPU-friendly: JAX_PLATFORMS=cpu python tools/load_test.py --requests 50
"""
import argparse
import json
import os
import sys
import threading
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))   # repo root for lightgbm_tpu


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=4,
                    help="concurrent client threads")
    ap.add_argument("--requests", type=int, default=200,
                    help="requests per thread per phase (2 phases: "
                    "before and after the hot-roll)")
    ap.add_argument("--max-batch", type=int, default=256)
    ap.add_argument("--min-bucket", type=int, default=16)
    ap.add_argument("--p99-ms", type=float, default=250.0,
                    help="client-observed p99 latency bound (ms)")
    ap.add_argument("--deadline-ms", type=float, default=2.0,
                    help="micro-batch coalescing deadline")
    ap.add_argument("--roll-timeout", type=float, default=60.0,
                    help="seconds to wait for the watcher to roll")
    ap.add_argument("--parity-sample", type=int, default=16,
                    help="per-phase requests checked against the Booster")
    ap.add_argument("--trace-sample", type=float, default=0.0,
                    help="enable request tracing at this tail-sampling "
                    "rate; every client mints a trace id and propagates "
                    "it (x-lgbm-trace style) into the queue")
    ap.add_argument("--trace-slow-ms", type=float, default=250.0,
                    help="always keep traces at least this slow")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu import callback
    from lightgbm_tpu.checkpoint.manager import CheckpointManager
    from lightgbm_tpu.serving import (MicroBatchQueue, ServingEngine,
                                      install_compile_hook)

    install_compile_hook()   # before any compilation we intend to count
    rng = np.random.RandomState(args.seed)
    serve_dir = tempfile.mkdtemp(prefix="lgbm_load_test_")

    # ---- two model generations, checkpointed where the watcher looks.
    # Generation A trains with a checkpoint callback (snapshots 1..10 land
    # in serve_dir); generation B resumes to 15 rounds WITHOUT the
    # callback — its snapshot is published mid-traffic below, which is
    # the hot-roll under test.
    nf = 10
    Xtr = rng.rand(4000, nf).astype(np.float32)
    ytr = ((Xtr[:, 0] + Xtr[:, 1] * Xtr[:, 2]) > 0.6).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1}
    ds = lgb.Dataset(Xtr, label=ytr)
    bst_a = lgb.train(params, ds, num_boost_round=10,
                      callbacks=[callback.checkpoint(serve_dir, period=1)])
    bst_b = lgb.train(params, ds, num_boost_round=15, resume_from=serve_dir)

    # ---- query pool + parity refs for BOTH generations, pre-warmup
    pool = [rng.rand(int(s), nf).astype(np.float32)
            for s in rng.randint(1, args.max_batch + 1, size=64)]
    refs_a = [bst_a.predict(X) for X in pool]
    refs_b = [bst_b.predict(X) for X in pool]

    # ---- engine + watcher; first poll rolls generation A in, warmup
    # compiles every bucket and marks the floor
    engine = ServingEngine(max_batch=args.max_batch,
                           min_bucket=args.min_bucket)
    watcher = engine.registry.watch_dir("m", serve_dir, poll_interval=0.1,
                                        engine=engine)
    watcher.poll()
    gen0 = engine.registry.generation("m")
    t0 = time.time()
    warmed = engine.warmup()
    t_warm = time.time() - t0
    watcher.start()
    tracer = None
    if args.trace_sample > 0:
        from lightgbm_tpu.obs.reqtrace import RequestTracer, new_trace_id
        tracer = RequestTracer(slow_ms=args.trace_slow_ms,
                               sample=args.trace_sample, seed=args.seed)
    queue = MicroBatchQueue(engine, deadline_ms=args.deadline_ms,
                            tracer=tracer).start()

    latencies: list = []
    failures: list = []
    lat_lock = threading.Lock()

    def fire_phase(refs, tag):
        """args.threads clients x args.requests randomized requests,
        a sample of them parity-checked against ``refs``."""
        def client(tid):
            r = np.random.RandomState(args.seed + 1000 + tid)
            lats = []
            for i in range(args.requests):
                qi = int(r.randint(len(pool)))
                t1 = time.perf_counter()
                # client-minted context, exactly what an HTTP caller
                # sends in x-lgbm-trace: the kept trace's root carries
                # the id WE chose, proving propagation end to end
                ctx = new_trace_id() if tracer is not None else None
                out = queue.predict("m", pool[qi], trace=ctx)
                lats.append((time.perf_counter() - t1) * 1000.0)
                if i < args.parity_sample // max(args.threads, 1) + 1:
                    err = float(np.max(np.abs(out - refs[qi])))
                    if not err <= 1e-6:
                        with lat_lock:
                            failures.append(
                                "%s parity: thread %d query %d maxdiff %.3g"
                                % (tag, tid, qi, err))
            with lat_lock:
                latencies.extend(lats)
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(args.threads)]
        t1 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.time() - t1

    # ---- phase 1: traffic against generation A
    t_phase1 = fire_phase(refs_a, "pre-roll")

    # ---- hot-roll: publish generation B's snapshot, wait for the watcher
    # (traffic keeps flowing in phase 2 the moment the roll lands)
    CheckpointManager(serve_dir).save(bst_b)
    t1 = time.time()
    while engine.registry.generation("m") == gen0 \
            and time.time() - t1 < args.roll_timeout:
        time.sleep(0.05)
    t_roll = time.time() - t1
    rolled = engine.registry.generation("m") > gen0
    if not rolled:
        failures.append("hot-roll did not land within %.0fs"
                        % args.roll_timeout)

    # ---- phase 2: traffic against generation B
    t_phase2 = fire_phase(refs_b if rolled else refs_a, "post-roll")

    queue.stop()
    watcher.stop()

    misses = engine.metrics.cache_misses_after_warmup()
    recompiles = engine.metrics.recompiles_after_warmup()
    if misses != 0:
        failures.append("%d predictor-cache misses after warmup (across "
                        "the hot-roll)" % misses)
    if recompiles != 0:
        failures.append("%d XLA backend compiles after warmup (prewarm "
                        "credit did not cover the roll)" % recompiles)

    lat = np.asarray(latencies, np.float64)
    p50 = float(np.percentile(lat, 50)) if lat.size else 0.0
    p99 = float(np.percentile(lat, 99)) if lat.size else 0.0
    if p99 > args.p99_ms:
        failures.append("client p99 %.1fms exceeds bound %.1fms"
                        % (p99, args.p99_ms))

    snap = engine.metrics.snapshot()
    print(json.dumps({
        "ok": not failures,
        "failures": failures,
        "threads": args.threads,
        "requests": int(lat.size),
        "rolled": rolled,
        "generation": engine.registry.generation("m"),
        "buckets_warmed": warmed,
        "cache_misses_after_warmup": misses,
        "recompiles_after_warmup": recompiles,
        "warmup_seconds": round(t_warm, 3),
        "roll_seconds": round(t_roll, 3),
        "phase_seconds": [round(t_phase1, 3), round(t_phase2, 3)],
        "client_latency_ms": {"p50": round(p50, 3), "p99": round(p99, 3),
                              "bound_p99": args.p99_ms},
        "device_latency_by_bucket": engine.metrics.bucket_latency(),
        "traces_kept": (len(tracer.recent_traces())
                        if tracer is not None else None),
        "metrics": snap,
    }))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
