"""Distributed-training end-to-end smoke (tier1 CI).

A REAL 2-process training run: two OS processes, one CPU device each,
glued by ``jax.distributed`` through ``parallel/network.py`` (which
selects gloo so compiled collectives actually cross process boundaries).
The mesh spans both processes, so every per-wave collective in
``parallel/learners.py`` — the reduce-scatter + best-record election of
``tree_learner=data`` and the PV-Tree vote of ``tree_learner=voting`` —
runs over a genuine multi-controller topology, not the single-process
virtual-device mesh the unit tests use.

Asserted end to end:

- **model agreement**: after training, each rank digests its committed
  trees (structure + leaf values) AND its predictions; digests must be
  identical across ranks for BOTH learner schedules
  (``network.check_model_agreement`` raises on divergence).  Data-parallel
  training is replicated-by-construction, so any mismatch is a real bug.
- **weak scaling**: a 1-process baseline trains half the rows (constant
  rows/device); efficiency = t_base / t_dist is recorded for BENCH and
  sanity-gated only against pathology (collectives serializing the run).
- **straggler skew**: max/min per-rank steady-state seconds, recorded.

Exit code 0 = every assertion holds.  Summary JSON goes to ``--out`` (and
stdout); per-rank results land under ``--workdir`` for artifact upload.
"""
import argparse
import hashlib
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TOTAL_ROWS = 12000       # distributed run: 6000 rows/device on 2 devices
NUM_FEATURES = 12
WARMUP_ITERS = 1         # compile happens here; excluded from timing
TIMED_ITERS = 2          # enough for a scaling row without bloating CI
TOP_K = 3                # voting run: well under F, so the vote matters


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_data(rows: int):
    import numpy as np
    r = np.random.RandomState(7)
    X = r.randn(rows, NUM_FEATURES).astype(np.float32)
    logit = (1.4 * X[:, 0] - 1.1 * X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
             + 0.5 * X[:, 4])
    y = (logit + 0.25 * r.randn(rows) > 0).astype(np.float32)
    return X, y


def _train_timed(X, y, extra):
    """Train WARMUP+TIMED iters; returns (booster, steady-state seconds)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "tree_growth": "frontier"}
    params.update(extra)
    import jax
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(WARMUP_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)     # don't time the warmup's tail
    t0 = time.monotonic()
    for _ in range(TIMED_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)     # dispatch is async; time the work
    return b, time.monotonic() - t0


def _digest(booster, X) -> str:
    """Model digest: committed structure + leaf stats + predictions.
    Replicated training must make this bit-identical on every rank."""
    import numpy as np
    h = hashlib.sha256()
    for t in booster.models:
        nn = t.num_leaves - 1
        h.update(np.asarray(t.split_feature[:nn], np.int32).tobytes())
        h.update(np.asarray(t.threshold_bin[:nn], np.int32).tobytes())
        h.update(np.asarray(t.leaf_value[:t.num_leaves],
                            np.float64).tobytes())
        h.update(np.asarray(t.leaf_count[:t.num_leaves],
                            np.float64).tobytes())
    h.update(np.asarray(booster.predict(X[:512], raw_score=True),
                        np.float64).tobytes())
    return h.hexdigest()


# --------------------------------------------------------------- workers
def _worker_train(rank: int, args) -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.parallel import network
    # rank 0's entry doubles as the jax.distributed coordinator address;
    # network.init also flips the CPU backend to gloo collectives
    network.init(machines="127.0.0.1:%d,127.0.0.1:0" % args.port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()

    X, y = _make_data(TOTAL_ROWS)
    res = {"rank": rank}
    for mode, extra in (
            ("data", {"tree_learner": "data", "num_machines": 2,
                      "mesh_shape": [2]}),
            ("voting", {"tree_learner": "voting", "num_machines": 2,
                        "mesh_shape": [2], "top_k": TOP_K})):
        b, secs = _train_timed(X, y, extra)
        d = _digest(b, X)
        # raises LightGBMError on divergence — the worker exits nonzero
        # and the launcher surfaces its stderr
        network.check_model_agreement(
            d, namespace="lgbm_train_smoke_%s" % mode)
        res["digest_%s" % mode] = d
        res["seconds_%s" % mode] = secs
        res["trees_%s" % mode] = len(b.models)
    with open(os.path.join(args.workdir, "train.rank%d.json" % rank),
              "w") as fh:
        json.dump(res, fh, sort_keys=True)
    # barrier before exit so neither rank tears the coordinator down
    # while the other is still mid-allgather
    from lightgbm_tpu.parallel.network import KvHostComm
    KvHostComm(namespace="lgbm_train_smoke_done").allgather({"rank": rank})
    return 0


def _worker_base(args) -> int:
    """1-process weak-scaling baseline: half the rows on one device —
    rows/device match the distributed run, so t_base/t_dist is the
    weak-scaling efficiency (1.0 = collectives cost nothing)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    X, y = _make_data(TOTAL_ROWS // 2)
    _, secs = _train_timed(X, y, {})
    with open(os.path.join(args.workdir, "base.json"), "w") as fh:
        json.dump({"seconds": secs, "rows": TOTAL_ROWS // 2}, fh)
    return 0


# -------------------------------------------------------------- launcher
def _spawn_pair(port: int, workdir: str):
    procs = []
    for rank in range(2):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "",            # one device per process
               "LIGHTGBM_TPU_RANK": str(rank),
               "PYTHONPATH": REPO}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(rank), "--phase", "train",
             "--port", str(port), "--workdir", workdir],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    return procs


def _drain(procs, timeout: float):
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            so, se = p.communicate()
        outs.append((p.returncode, so, se))
    return outs


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="dist_train_out")
    ap.add_argument("--out", default="", help="summary JSON path")
    ap.add_argument("--worker", type=int, default=-1,
                    help="(internal) run as rank N instead of launching")
    ap.add_argument("--phase", default="train", choices=["train", "base"])
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)

    if args.worker >= 0:
        if args.phase == "base":
            return _worker_base(args)
        return _worker_train(args.worker, args)

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    # ---- 2-process distributed training --------------------------------
    outs = _drain(_spawn_pair(_free_port(), args.workdir), timeout=420)
    for rank, (rc, so, se) in enumerate(outs):
        check(rc == 0, "train rank %d exited 0 (rc=%s)" % (rank, rc))
        if rc != 0:
            print("--- rank %d stdout ---\n%s\n--- rank %d stderr ---\n%s"
                  % (rank, so[-1500:], rank, se[-3000:]))
    results = {}
    for rank in range(2):
        path = os.path.join(args.workdir, "train.rank%d.json" % rank)
        if os.path.exists(path):
            with open(path) as fh:
                results[rank] = json.load(fh)
    check(len(results) == 2, "both train ranks reported")

    # ---- cross-process model agreement (launcher-side re-check) --------
    agreement = {}
    for mode in ("data", "voting"):
        ds = [results[r].get("digest_%s" % mode) for r in sorted(results)]
        ok = len(ds) == 2 and ds[0] is not None and ds[0] == ds[1]
        check(ok, "%s-parallel model identical across processes" % mode)
        agreement[mode] = ds[0] if ok else ds
        trees = {results[r].get("trees_%s" % mode) for r in results}
        check(trees == {WARMUP_ITERS + TIMED_ITERS},
              "%s-parallel committed %d trees on every rank (got %s)"
              % (mode, WARMUP_ITERS + TIMED_ITERS, sorted(trees)))

    # ---- weak-scaling baseline (1 process, rows/device held constant) --
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
           "PYTHONPATH": REPO}
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--worker", "0",
         "--phase", "base", "--workdir", args.workdir],
        env=env, cwd=REPO, timeout=420)
    check(rc == 0, "weak-scaling baseline exited 0 (rc=%s)" % rc)
    base = {}
    base_path = os.path.join(args.workdir, "base.json")
    if os.path.exists(base_path):
        with open(base_path) as fh:
            base = json.load(fh)

    weak = {}
    skew = None
    if len(results) == 2 and base.get("seconds"):
        t_ranks = [results[r].get("seconds_data", 0.0)
                   for r in sorted(results)]
        t_dist = max(t_ranks)          # the run is as slow as its slowest
        t_base = float(base["seconds"])
        eff = t_base / t_dist if t_dist > 0 else 0.0
        skew = (max(t_ranks) / min(t_ranks)) if min(t_ranks) > 0 else None
        weak = {"rows_per_device": TOTAL_ROWS // 2,
                "timed_iters": TIMED_ITERS,
                "t_base_1p_s": round(t_base, 3),
                "t_dist_2p_s": round(t_dist, 3),
                "efficiency": round(eff, 3),
                "straggler_skew": round(skew, 3) if skew else None}
        # sanity floor only — the measured number is the BENCH artifact,
        # the gate just catches a wedged/livelocked collective, and only
        # on machines that can genuinely host both ranks: with <4 cores
        # the two processes time-slice the same cores and gloo's
        # rendezvous spin makes the ratio meaningless (a 1-core box
        # measures 0.003 with a perfectly healthy schedule)
        cores = os.cpu_count() or 1
        weak["cores"] = cores
        if cores >= 4:
            check(eff > 0.005, "weak-scaling efficiency %.3f above "
                               "pathology floor 0.005" % eff)
        else:
            print("note weak-scaling efficiency %.3f recorded only "
                  "(%d cores cannot host 2 ranks fairly)" % (eff, cores))
        check(skew is not None and skew < 10.0,
              "straggler skew %.2fx within 10x sanity bound"
              % (skew or float("inf")))

    summary = {"failures": failures,
               "agreement": agreement,
               "ranks": results,
               "weak_scaling": weak}
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
