"""Distributed-training end-to-end smoke (tier1 CI).

A REAL 2-process training run: two OS processes, one CPU device each,
glued by ``jax.distributed`` through ``parallel/network.py`` (which
selects gloo so compiled collectives actually cross process boundaries).
The mesh spans both processes, so every per-wave collective in
``parallel/learners.py`` — the reduce-scatter + best-record election of
``tree_learner=data`` and the PV-Tree vote of ``tree_learner=voting`` —
runs over a genuine multi-controller topology, not the single-process
virtual-device mesh the unit tests use.

Asserted end to end:

- **model agreement**: after training, each rank digests its committed
  trees (structure + leaf values) AND its predictions; digests must be
  identical across ranks for BOTH learner schedules
  (``network.check_model_agreement`` raises on divergence).  Data-parallel
  training is replicated-by-construction, so any mismatch is a real bug.
- **weak scaling**: a 1-process baseline trains half the rows (constant
  rows/device); efficiency = t_base / t_dist is recorded for BENCH and
  sanity-gated only against pathology (collectives serializing the run).
- **straggler skew**: max/min per-rank steady-state seconds, recorded.

Exit code 0 = every assertion holds.  Summary JSON goes to ``--out`` (and
stdout); per-rank results land under ``--workdir`` for artifact upload.
"""
import argparse
import hashlib
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TOTAL_ROWS = 12000       # distributed run: 6000 rows/device on 2 devices
NUM_FEATURES = 12
WARMUP_ITERS = 1         # compile happens here; excluded from timing
TIMED_ITERS = 2          # enough for a scaling row without bloating CI
TOP_K = 3                # voting run: well under F, so the vote matters

# chunks x chips (stream phase): each process streams ONLY its row shard
# in fixed-size chunks — 2400 rows/shard at chunk_rows=1200 means no
# process ever holds more than half its shard on device, i.e. the global
# dataset exceeds any single process's chunk budget by construction
STREAM_ROWS = 4800       # 2400 rows/shard on 2 processes
STREAM_SRC_CHUNK = 640   # raw source granularity (!= device chunk_rows)
STREAM_CHUNK2 = 1200     # 2 device chunks per shard
STREAM_CHUNK4 = 600      # 4 device chunks per shard (same padded length)
STREAM_TOP_K = 4         # voting leg nomination width


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _make_data(rows: int):
    import numpy as np
    r = np.random.RandomState(7)
    X = r.randn(rows, NUM_FEATURES).astype(np.float32)
    logit = (1.4 * X[:, 0] - 1.1 * X[:, 1] + 0.8 * X[:, 2] * X[:, 3]
             + 0.5 * X[:, 4])
    y = (logit + 0.25 * r.randn(rows) > 0).astype(np.float32)
    return X, y


def _train_timed(X, y, extra):
    """Train WARMUP+TIMED iters; returns (booster, steady-state seconds)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "tree_growth": "frontier"}
    params.update(extra)
    import jax
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    for _ in range(WARMUP_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)     # don't time the warmup's tail
    t0 = time.monotonic()
    for _ in range(TIMED_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)     # dispatch is async; time the work
    return b, time.monotonic() - t0


def _digest(booster, X) -> str:
    """Model digest: committed structure + leaf stats + predictions.
    Replicated training must make this bit-identical on every rank."""
    import numpy as np
    h = hashlib.sha256()
    for t in booster.models:
        nn = t.num_leaves - 1
        h.update(np.asarray(t.split_feature[:nn], np.int32).tobytes())
        h.update(np.asarray(t.threshold_bin[:nn], np.int32).tobytes())
        h.update(np.asarray(t.leaf_value[:t.num_leaves],
                            np.float64).tobytes())
        h.update(np.asarray(t.leaf_count[:t.num_leaves],
                            np.float64).tobytes())
    h.update(np.asarray(booster.predict(X[:512], raw_score=True),
                        np.float64).tobytes())
    return h.hexdigest()


# --------------------------------------------------------------- workers
def _worker_train(rank: int, args) -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.parallel import network
    # rank 0's entry doubles as the jax.distributed coordinator address;
    # network.init also flips the CPU backend to gloo collectives
    network.init(machines="127.0.0.1:%d,127.0.0.1:0" % args.port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()

    X, y = _make_data(TOTAL_ROWS)
    res = {"rank": rank}
    for mode, extra in (
            ("data", {"tree_learner": "data", "num_machines": 2,
                      "mesh_shape": [2]}),
            ("voting", {"tree_learner": "voting", "num_machines": 2,
                        "mesh_shape": [2], "top_k": TOP_K})):
        b, secs = _train_timed(X, y, extra)
        d = _digest(b, X)
        # raises LightGBMError on divergence — the worker exits nonzero
        # and the launcher surfaces its stderr
        network.check_model_agreement(
            d, namespace="lgbm_train_smoke_%s" % mode)
        res["digest_%s" % mode] = d
        res["seconds_%s" % mode] = secs
        res["trees_%s" % mode] = len(b.models)
    with open(os.path.join(args.workdir, "train.rank%d.json" % rank),
              "w") as fh:
        json.dump(res, fh, sort_keys=True)
    # barrier before exit so neither rank tears the coordinator down
    # while the other is still mid-allgather
    from lightgbm_tpu.parallel.network import KvHostComm
    KvHostComm(namespace="lgbm_train_smoke_done").allgather({"rank": rank})
    return 0


def _worker_base(args) -> int:
    """1-process weak-scaling baseline: half the rows on one device —
    rows/device match the distributed run, so t_base/t_dist is the
    weak-scaling efficiency (1.0 = collectives cost nothing)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    X, y = _make_data(TOTAL_ROWS // 2)
    _, secs = _train_timed(X, y, {})
    with open(os.path.join(args.workdir, "base.json"), "w") as fh:
        json.dump({"seconds": secs, "rows": TOTAL_ROWS // 2}, fh)
    return 0


def _structure_digest(models) -> str:
    """Tree STRUCTURE only (splits + routing + row counts, no leaf
    values): the cross-topology identity contract — chunked == single-
    shot and sharded == serial hold structurally, while f32 leaf-value
    accumulation order may differ across chunk boundaries."""
    import numpy as np
    h = hashlib.sha256()
    for t in models:
        nn = t.num_leaves - 1
        h.update(np.asarray(t.split_feature[:nn], np.int32).tobytes())
        h.update(np.asarray(t.threshold_bin[:nn], np.int32).tobytes())
        h.update(np.asarray(t.left_child[:nn], np.int32).tobytes())
        h.update(np.asarray(t.right_child[:nn], np.int32).tobytes())
        h.update(np.asarray(t.leaf_count[:t.num_leaves],
                            np.float64).tobytes())
    return h.hexdigest()


def _stream_base() -> dict:
    return {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "tree_growth": "frontier", "deterministic": True,
            "min_data_in_leaf": 5,
            # exact-parity hook: sample == full data, so the allgathered
            # reservoir reproduces serial bin boundaries bit-for-bit
            "bin_construct_sample_cnt": 2 * STREAM_ROWS}


def _worker_stream(rank: int, args) -> int:
    """Rank body of the chunks-x-chips smoke: sharded ingest + streamed
    training over the 2-process mesh, for both learner schedules, at 2
    and 4 chunks per shard, plus kill-and-resume byte-identity."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.parallel import network
    network.init(machines="127.0.0.1:%d,127.0.0.1:0" % args.port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import callback, engine
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.profiling import (backend_compile_count,
                                        install_compile_hook)
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.stream.source import ArraySource, ShardedSource

    install_compile_hook()
    X, y = _make_data(STREAM_ROWS)
    res = {"rank": rank}

    def sharded_ds(cfg):
        # each rank streams ONLY its contiguous row block; ingest merges
        # the reservoir samples + labels over one host allgather
        return ingest(ShardedSource(
            ArraySource(X, label=y, chunk_rows=STREAM_SRC_CHUNK),
            rank, 2), cfg)

    def fit(extra, sd=None, iters=WARMUP_ITERS + TIMED_ITERS,
            timed=False):
        p = dict(_stream_base(), num_machines=2, mesh_shape=[2],
                 tree_learner="data")
        p.update(extra)
        cfg = Config(p)
        if sd is None:
            sd = sharded_ds(cfg)
        c0 = backend_compile_count()
        b = create_boosting(cfg, sd, create_objective(cfg), [])
        secs = 0.0
        if timed:
            for _ in range(WARMUP_ITERS):
                b.train_one_iter()
            jax.block_until_ready(b.scores)
            t0 = time.monotonic()
            for _ in range(iters - WARMUP_ITERS):
                b.train_one_iter()
            jax.block_until_ready(b.scores)
            secs = time.monotonic() - t0
        else:
            for _ in range(iters):
                b.train_one_iter()
            jax.block_until_ready(b.scores)
        return b, sd, secs, float(backend_compile_count() - c0)

    # throwaway single-chunk run absorbs every once-per-process compile
    # (shared jitted helpers), so the measured runs see only their own
    # program sets — same discipline as the perf gate's stream counters
    fit({"data_stream_chunk_rows": 2400}, iters=1)

    # ---- data learner, 2 chunks/shard (the timed leg) ----------------
    b2, sd2, secs, c2 = fit({"data_stream_chunk_rows": STREAM_CHUNK2},
                            timed=True)
    d2 = _digest(b2, X)
    network.check_model_agreement(d2, namespace="lgbm_stream_smoke_data2")
    res.update(digest_data2=d2, seconds_data2=secs,
               trees_data2=len(b2.models),
               structure_data2=_structure_digest(b2.models),
               compiles_data2=c2,
               chunks2=int(b2._stream.num_chunks),
               rows_per_shard=int(b2._stream.rows_per_sweep))

    # warm booster trains more: ZERO new programs
    c0 = backend_compile_count()
    b2.train_one_iter()
    res["compiles_after_warmup"] = float(backend_compile_count() - c0)

    # ---- data learner, 4 chunks/shard: structure-identical, and the
    # fresh-booster program set is the same SIZE (chunk-count invariance
    # under the mesh — chunk count only changes how often each fixed-
    # shape kernel runs)
    b4, _, _, c4 = fit({"data_stream_chunk_rows": STREAM_CHUNK4}, sd=sd2)
    d4 = _digest(b4, X)
    network.check_model_agreement(d4, namespace="lgbm_stream_smoke_data4")
    res.update(digest_data4=d4, trees_data4=len(b4.models),
               structure_data4=_structure_digest(b4.models),
               compile_chunk_invariance=float(c4 - c2),
               chunks4=int(b4._stream.num_chunks))

    # ---- voting learner over the same sharded stream -----------------
    bv, _, _, _ = fit({"tree_learner": "voting", "top_k": STREAM_TOP_K,
                       "data_stream_chunk_rows": STREAM_CHUNK2}, sd=sd2)
    dv = _digest(bv, X)
    network.check_model_agreement(dv, namespace="lgbm_stream_smoke_vote")
    res.update(digest_voting=dv, trees_voting=len(bv.models),
               structure_voting=_structure_digest(bv.models))

    # ---- single-process streamed baseline (no mesh, full data, run
    # identically on both ranks): the sharded run must reproduce its
    # tree structure exactly
    ps = dict(_stream_base(), data_stream_chunk_rows=STREAM_CHUNK4)
    cfgs = Config(ps)
    sds = ingest(ArraySource(X, label=y, chunk_rows=STREAM_SRC_CHUNK),
                 cfgs)
    bs = create_boosting(cfgs, sds, create_objective(cfgs), [])
    for _ in range(WARMUP_ITERS + TIMED_ITERS):
        bs.train_one_iter()
    res["structure_serial"] = _structure_digest(bs.models)

    # ---- kill-and-resume byte-identity under the 2-process mesh ------
    pr = dict(_stream_base(), num_machines=2, mesh_shape=[2],
              tree_learner="data", data_stream_chunk_rows=STREAM_CHUNK2)

    def run_ck(ckpt, rounds, resume=False):
        d = lgb.Dataset(np.zeros((2, NUM_FEATURES)))
        d._binned = sharded_ds(Config(pr))
        return engine.train(
            dict(pr), d, num_boost_round=rounds,
            callbacks=[callback.checkpoint(ckpt, period=1)],
            resume_from=(ckpt if resume else None), verbose_eval=False)

    gdir = os.path.join(args.workdir, "ck_golden_r%d" % rank)
    idir = os.path.join(args.workdir, "ck_interrupt_r%d" % rank)
    golden = run_ck(gdir, 4)
    run_ck(idir, 2)                       # "killed" after 2 rounds
    resumed = run_ck(idir, 4, resume=True)
    gtxt, rtxt = golden.model_to_string(), resumed.model_to_string()
    res["resume_byte_identical"] = bool(gtxt == rtxt)
    dr = hashlib.sha256(rtxt.encode()).hexdigest()
    network.check_model_agreement(dr, namespace="lgbm_stream_smoke_ck")
    res["digest_resumed"] = dr

    with open(os.path.join(args.workdir, "stream.rank%d.json" % rank),
              "w") as fh:
        json.dump(res, fh, sort_keys=True)
    from lightgbm_tpu.parallel.network import KvHostComm
    KvHostComm(namespace="lgbm_stream_smoke_done").allgather(
        {"rank": rank})
    return 0


def _worker_stream_base(args) -> int:
    """1-process weak-scaling baseline for the stream phase: half the
    rows, same chunks/shard (constant rows/device AND chunks/device)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import time as _time
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.stream.sampler import ingest
    from lightgbm_tpu.stream.source import ArraySource

    X, y = _make_data(STREAM_ROWS // 2)
    cfg = Config(dict(_stream_base(),
                      data_stream_chunk_rows=STREAM_CHUNK2))
    sd = ingest(ArraySource(X, label=y, chunk_rows=STREAM_SRC_CHUNK), cfg)
    b = create_boosting(cfg, sd, create_objective(cfg), [])
    for _ in range(WARMUP_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)
    t0 = _time.monotonic()
    for _ in range(TIMED_ITERS):
        b.train_one_iter()
    jax.block_until_ready(b.scores)
    secs = _time.monotonic() - t0
    with open(os.path.join(args.workdir, "stream_base.json"), "w") as fh:
        json.dump({"seconds": secs, "rows": STREAM_ROWS // 2}, fh)
    return 0


# -------------------------------------------------------------- launcher
def _spawn_pair(port: int, workdir: str, phase: str = "train"):
    procs = []
    for rank in range(2):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "",            # one device per process
               "LIGHTGBM_TPU_RANK": str(rank),
               "PYTHONPATH": REPO}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(rank), "--phase", phase,
             "--port", str(port), "--workdir", workdir],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    return procs


def _drain(procs, timeout: float):
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            so, se = p.communicate()
        outs.append((p.returncode, so, se))
    return outs


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="dist_train_out")
    ap.add_argument("--out", default="", help="summary JSON path")
    ap.add_argument("--worker", type=int, default=-1,
                    help="(internal) run as rank N instead of launching")
    ap.add_argument("--phase", default="train",
                    choices=["train", "base", "stream", "stream_base"])
    ap.add_argument("--only", default="all",
                    choices=["all", "train", "stream"],
                    help="which phases the launcher runs")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)

    if args.worker >= 0:
        if args.phase == "base":
            return _worker_base(args)
        if args.phase == "stream":
            return _worker_stream(args.worker, args)
        if args.phase == "stream_base":
            return _worker_stream_base(args)
        return _worker_train(args.worker, args)

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    summary = {"failures": failures}
    if args.only in ("all", "train"):
        summary.update(_run_train_phase(args, check))
    if args.only in ("all", "stream"):
        summary["stream"] = _run_stream_phase(args, check)

    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


def _run_train_phase(args, check) -> dict:
    # ---- 2-process distributed training --------------------------------
    outs = _drain(_spawn_pair(_free_port(), args.workdir), timeout=420)
    for rank, (rc, so, se) in enumerate(outs):
        check(rc == 0, "train rank %d exited 0 (rc=%s)" % (rank, rc))
        if rc != 0:
            print("--- rank %d stdout ---\n%s\n--- rank %d stderr ---\n%s"
                  % (rank, so[-1500:], rank, se[-3000:]))
    results = {}
    for rank in range(2):
        path = os.path.join(args.workdir, "train.rank%d.json" % rank)
        if os.path.exists(path):
            with open(path) as fh:
                results[rank] = json.load(fh)
    check(len(results) == 2, "both train ranks reported")

    # ---- cross-process model agreement (launcher-side re-check) --------
    agreement = {}
    for mode in ("data", "voting"):
        ds = [results[r].get("digest_%s" % mode) for r in sorted(results)]
        ok = len(ds) == 2 and ds[0] is not None and ds[0] == ds[1]
        check(ok, "%s-parallel model identical across processes" % mode)
        agreement[mode] = ds[0] if ok else ds
        trees = {results[r].get("trees_%s" % mode) for r in results}
        check(trees == {WARMUP_ITERS + TIMED_ITERS},
              "%s-parallel committed %d trees on every rank (got %s)"
              % (mode, WARMUP_ITERS + TIMED_ITERS, sorted(trees)))

    # ---- weak-scaling baseline (1 process, rows/device held constant) --
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
           "PYTHONPATH": REPO}
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--worker", "0",
         "--phase", "base", "--workdir", args.workdir],
        env=env, cwd=REPO, timeout=420)
    check(rc == 0, "weak-scaling baseline exited 0 (rc=%s)" % rc)
    base = {}
    base_path = os.path.join(args.workdir, "base.json")
    if os.path.exists(base_path):
        with open(base_path) as fh:
            base = json.load(fh)

    weak = {}
    skew = None
    if len(results) == 2 and base.get("seconds"):
        t_ranks = [results[r].get("seconds_data", 0.0)
                   for r in sorted(results)]
        t_dist = max(t_ranks)          # the run is as slow as its slowest
        t_base = float(base["seconds"])
        eff = t_base / t_dist if t_dist > 0 else 0.0
        skew = (max(t_ranks) / min(t_ranks)) if min(t_ranks) > 0 else None
        weak = {"rows_per_device": TOTAL_ROWS // 2,
                "timed_iters": TIMED_ITERS,
                "t_base_1p_s": round(t_base, 3),
                "t_dist_2p_s": round(t_dist, 3),
                "efficiency": round(eff, 3),
                "straggler_skew": round(skew, 3) if skew else None}
        # sanity floor only — the measured number is the BENCH artifact,
        # the gate just catches a wedged/livelocked collective, and only
        # on machines that can genuinely host both ranks: with <4 cores
        # the two processes time-slice the same cores and gloo's
        # rendezvous spin makes the ratio meaningless (a 1-core box
        # measures 0.003 with a perfectly healthy schedule)
        cores = os.cpu_count() or 1
        weak["cores"] = cores
        if cores >= 4:
            check(eff > 0.005, "weak-scaling efficiency %.3f above "
                               "pathology floor 0.005" % eff)
        else:
            print("note weak-scaling efficiency %.3f recorded only "
                  "(%d cores cannot host 2 ranks fairly)" % (eff, cores))
        check(skew is not None and skew < 10.0,
              "straggler skew %.2fx within 10x sanity bound"
              % (skew or float("inf")))

    return {"agreement": agreement, "ranks": results,
            "weak_scaling": weak}


def _run_stream_phase(args, check) -> dict:
    """Chunks x chips: 2-process sharded-stream training + its
    1-process weak-scaling baseline, assembled into the BENCH_r15 row."""
    outs = _drain(_spawn_pair(_free_port(), args.workdir, phase="stream"),
                  timeout=480)
    for rank, (rc, so, se) in enumerate(outs):
        check(rc == 0, "stream rank %d exited 0 (rc=%s)" % (rank, rc))
        if rc != 0:
            print("--- rank %d stdout ---\n%s\n--- rank %d stderr ---\n%s"
                  % (rank, so[-1500:], rank, se[-3000:]))
    results = {}
    for rank in range(2):
        path = os.path.join(args.workdir, "stream.rank%d.json" % rank)
        if os.path.exists(path):
            with open(path) as fh:
                results[rank] = json.load(fh)
    check(len(results) == 2, "both stream ranks reported")
    if len(results) != 2:
        return {"ranks": results}
    r0, r1 = results[0], results[1]

    # cross-process digest agreement (launcher-side re-check; the
    # workers already ran check_model_agreement per leg)
    for leg in ("data2", "data4", "voting", "resumed"):
        check(r0.get("digest_" + leg) == r1.get("digest_" + leg)
              and r0.get("digest_" + leg) is not None,
              "stream %s model identical across processes" % leg)

    # structure identity: sharded == serial streamed, and chunk-count
    # invariant (2 vs 4 chunks per shard)
    check(r0.get("structure_data2") == r0.get("structure_serial"),
          "sharded streamed trees structure-identical to 1-process "
          "streamed")
    check(r0.get("structure_data4") == r0.get("structure_data2"),
          "streamed-sharded structure invariant in chunk count (2 vs 4)")

    # compiled-program contracts, per rank
    for rank, r in sorted(results.items()):
        check(r.get("compile_chunk_invariance") == 0.0,
              "rank %d: fresh-booster program count invariant 2->4 "
              "chunks (diff=%s)"
              % (rank, r.get("compile_chunk_invariance")))
        check(r.get("compiles_after_warmup") == 0.0,
              "rank %d: zero compiles after warmup (got %s)"
              % (rank, r.get("compiles_after_warmup")))
        check(bool(r.get("resume_byte_identical")),
              "rank %d: kill-and-resume byte-identical model" % rank)
    trees = {r.get("trees_data2") for r in results.values()}
    check(trees == {WARMUP_ITERS + TIMED_ITERS},
          "stream data leg committed %d trees on every rank (got %s)"
          % (WARMUP_ITERS + TIMED_ITERS, sorted(trees)))
    check(int(r0.get("chunks2", 0)) == 2 and int(r0.get("chunks4", 0)) == 4,
          "chunk schedule as declared (2 and 4 chunks/shard, got %s/%s)"
          % (r0.get("chunks2"), r0.get("chunks4")))

    # ---- 1-process weak-scaling baseline (constant rows/device) --------
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
           "PYTHONPATH": REPO}
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "--worker", "0",
         "--phase", "stream_base", "--workdir", args.workdir],
        env=env, cwd=REPO, timeout=420)
    check(rc == 0, "stream weak-scaling baseline exited 0 (rc=%s)" % rc)
    base = {}
    base_path = os.path.join(args.workdir, "stream_base.json")
    if os.path.exists(base_path):
        with open(base_path) as fh:
            base = json.load(fh)

    weak = {}
    if base.get("seconds"):
        t_ranks = [results[r].get("seconds_data2", 0.0)
                   for r in sorted(results)]
        t_dist = max(t_ranks)
        t_base = float(base["seconds"])
        rows_base = float(base["rows"]) * TIMED_ITERS
        rows_dist = float(STREAM_ROWS) * TIMED_ITERS
        weak = {"rows_per_shard": STREAM_ROWS // 2,
                "chunks_per_shard": 2,
                "chunk_rows": STREAM_CHUNK2,
                "timed_iters": TIMED_ITERS,
                "t_base_1p_s": round(t_base, 3),
                "t_dist_2p_s": round(t_dist, 3),
                "rows_per_sec_1p": round(rows_base / t_base, 1)
                if t_base > 0 else None,
                "rows_per_sec_2p": round(rows_dist / t_dist, 1)
                if t_dist > 0 else None,
                "efficiency": round(t_base / t_dist, 3)
                if t_dist > 0 else None,
                "cores": os.cpu_count() or 1}
        if weak["cores"] >= 4:
            check((weak["efficiency"] or 0) > 0.005,
                  "stream weak-scaling efficiency %s above pathology "
                  "floor 0.005" % weak["efficiency"])
        else:
            print("note stream weak-scaling efficiency %s recorded only "
                  "(%d cores cannot host 2 ranks fairly)"
                  % (weak["efficiency"], weak["cores"]))

    return {"ranks": results, "weak_scaling": weak,
            "agreement": {leg: r0.get("digest_" + leg)
                          for leg in ("data2", "data4", "voting",
                                      "resumed")}}


if __name__ == "__main__":
    sys.exit(main())
