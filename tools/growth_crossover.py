"""Find the exact-vs-batched crossover in stored-column count (round 4).

HIGGS-narrow (28 cols) favors exact growth on chip; Expo/Allstate-wide
favor batched. This sweeps dense shapes between them to locate the
crossover that backs tree_growth=auto's policy. Appends results to
tools/onchip_r4_results.json under "growth_crossover".
"""
import json
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
OUT = os.path.join(HERE, "onchip_r4_results.json")


def main():
    import jax
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    n = 500_000
    r = np.random.RandomState(0)
    out = {}
    for f in (28, 64, 128, 256):
        X = r.randn(n, f).astype(np.float32)
        y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float32)
        row = {}
        for name, extra in (("exact", {"tree_growth": "exact"}),
                            ("batched", {"tree_growth": "batched",
                                         "tree_batch_splits": 32})):
            cfg = Config({"objective": "binary", "num_leaves": 255,
                          "verbosity": -1, **extra})
            ds = BinnedDataset.from_matrix(X, cfg, label=y)
            b = create_boosting(cfg, ds, create_objective(cfg), [])
            b.train_many(3)
            jax.block_until_ready(b.scores)
            t0 = time.time()
            b.train_many(6)
            jax.block_until_ready(b.scores)
            row[name] = round((time.time() - t0) / 6, 3)
            del b, ds
        row["winner"] = min(("exact", "batched"), key=row.get)
        out["cols_%d" % f] = row
        print(f, row, flush=True)

    res = json.load(open(OUT))
    res["growth_crossover"] = {"ok": True, "data": out,
                               "shape": "500k rows, L=255, dense"}
    with open(OUT + ".tmp", "w") as fh:
        json.dump(res, fh, indent=1, sort_keys=True)
    os.replace(OUT + ".tmp", OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
