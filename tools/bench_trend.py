"""Cross-round bench trend: every BENCH_r*.json in one table.

Each PR lands a ``BENCH_rNN.json`` (bench.py output, shape drifting as
the harness grew: early rounds nest everything under ``parsed``, later
rounds add subsystem blocks like ``streaming`` / ``distributed`` /
``packed_bins``).  This tool reads them ALL, extracts a tolerant set of
headline metrics per round, and emits:

- a markdown trend table (metric x {first seen, best ever, latest,
  delta}) with a ``REGRESSION?`` flag when the latest value is worse
  than the best-ever by more than ``--tolerance`` (relative); payload /
  collective pins use zero tolerance — those are exact invariants, any
  growth is real;
- ``--json`` with the full per-round series for dashboards.

Numbers across rounds come from DIFFERENT hosts and backends (CI is
CPU, some rounds ran accelerator probes), so the flag is a prompt to
look, not a gate — the perf gate proper is tools/perf_gate.py over
deterministic counters.  Exit 0 always unless ``--strict``, which turns
flagged regressions into exit 1.

Usage::

    python tools/bench_trend.py [--dir .] [--json trend.json]
    python tools/bench_trend.py --markdown trend.md --strict
"""
import argparse
import glob
import json
import os
import re
import sys

# (metric, candidate paths tried in order — each also retried under
# "parsed" —, direction: +1 higher-is-better / -1 lower-is-better,
# pin: exact invariant => zero tolerance)
METRICS = [
    ("train_5_iters_s", ["phase_seconds.train_5_iters"], -1, False),
    ("predict_rows_per_sec", ["predict_rows_per_sec"], +1, False),
    ("train_auc", ["train_auc"], +1, False),
    ("mfu_estimate", ["mfu_estimate"], +1, False),
    ("obs_basic_overhead_frac", ["obs_basic_overhead_frac"], -1, False),
    ("obs_trace_overhead_frac", ["obs_trace_overhead_frac"], -1, False),
    ("traversal_speedup_vs_replay",
     ["traversal_speedup_vs_replay"], +1, False),
    ("stream_overlap_efficiency",
     ["streaming.overlap_efficiency"], +1, False),
    ("stream_ingest_rows_per_sec",
     ["streaming.ingest_rows_per_sec"], +1, False),
    ("payload_frac_data_rs",
     ["distributed.payload_vs_serial.data_rs"], -1, True),
    ("payload_frac_voting",
     ["distributed.payload_vs_serial.voting"], -1, True),
    ("wave_payload_f32_data",
     ["distributed_streaming.per_wave_collectives_8dev_F16_B16"
      ".data.payload_f32_per_wave",
      "distributed.per_wave_collectives_8dev_F16_B16"
      ".data.payload_f32_per_wave"], -1, True),
    ("wave_payload_f32_voting",
     ["distributed_streaming.per_wave_collectives_8dev_F16_B16"
      ".voting.payload_f32_per_wave",
      "distributed.per_wave_collectives_8dev_F16_B16"
      ".voting.payload_f32_per_wave"], -1, True),
    ("packing_bytes_ratio_w1", ["packed_bins.w1.bytes_ratio"], +1, True),
    ("packing_bytes_ratio_w8", ["packed_bins.w8.bytes_ratio"], +1, True),
    ("serve_recompiles_after_warmup",
     ["serve_recompiles_after_warmup"], -1, True),
]


def _dig(doc, dotted):
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def extract(doc, paths):
    """First numeric hit across ``paths``, each tried at top level and
    under the legacy ``parsed`` nesting."""
    for p in paths:
        for root in (doc, doc.get("parsed") or {}):
            v = _dig(root, p)
            if isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                return float(v)
    return None


def load_rounds(bench_dir):
    """``[(round_number, doc)]`` sorted by round."""
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except ValueError:
            print("bench_trend: skipping unreadable %s" % path,
                  file=sys.stderr)
            continue
        if isinstance(doc, dict):
            rounds.append((int(m.group(1)), doc))
    return sorted(rounds)


def build_trend(rounds, tolerance):
    """Per-metric series + best/latest/flag summary."""
    out = {"rounds": [r for r, _ in rounds], "metrics": {}}
    for name, paths, direction, pin in METRICS:
        series = {}
        for rnum, doc in rounds:
            v = extract(doc, paths)
            if v is not None:
                series[rnum] = v
        if not series:
            continue
        ordered = sorted(series.items())
        latest_r, latest = ordered[-1]
        best_r, best = max(ordered, key=lambda kv: direction * kv[1])
        first_r, first = ordered[0]
        tol = 0.0 if pin else tolerance
        scale = max(abs(best), 1e-12)
        worse_frac = (best - latest) * direction / scale
        out["metrics"][name] = {
            "direction": "higher" if direction > 0 else "lower",
            "pin": pin,
            "series": {str(k): v for k, v in ordered},
            "first": {"round": first_r, "value": first},
            "best": {"round": best_r, "value": best},
            "latest": {"round": latest_r, "value": latest},
            "worse_than_best_frac": round(worse_frac, 4),
            "regression": bool(worse_frac > tol),
        }
    return out


def _fmt(v):
    if v is None:
        return "-"
    if abs(v) >= 1000:
        return "%.0f" % v
    return ("%.4f" % v).rstrip("0").rstrip(".")


def to_markdown(trend):
    lines = [
        "# Bench trend (%d rounds: r%s..r%s)"
        % (len(trend["rounds"]), min(trend["rounds"] or [0]),
           max(trend["rounds"] or [0])),
        "",
        "| metric | dir | first | best (round) | latest (round) "
        "| vs best | flag |",
        "|---|---|---|---|---|---|---|",
    ]
    for name, m in sorted(trend["metrics"].items()):
        flag = ""
        if m["regression"]:
            flag = "**REGRESSION?**" if not m["pin"] else "**PIN BROKEN**"
        lines.append(
            "| %s | %s%s | %s | %s (r%d) | %s (r%d) | %+.1f%% | %s |"
            % (name, m["direction"], " pin" if m["pin"] else "",
               _fmt(m["first"]["value"]),
               _fmt(m["best"]["value"]), m["best"]["round"],
               _fmt(m["latest"]["value"]), m["latest"]["round"],
               -100.0 * m["worse_than_best_frac"], flag))
    lines += [
        "",
        "`vs best` is the latest value relative to the best-ever "
        "(sign-adjusted; negative = worse). Cross-round numbers come "
        "from different hosts — flags prompt a look, the real gate is "
        "tools/perf_gate.py.",
    ]
    return "\n".join(lines) + "\n"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="relative slack before flagging a non-pin "
                    "metric (default 0.25: CI hosts are noisy)")
    ap.add_argument("--json", default="",
                    help="write the full trend JSON here")
    ap.add_argument("--markdown", default="",
                    help="write the markdown table here (also printed)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any metric is flagged")
    args = ap.parse_args()

    rounds = load_rounds(args.dir)
    if not rounds:
        print("bench_trend: no BENCH_r*.json under %s" % args.dir,
              file=sys.stderr)
        return 2
    trend = build_trend(rounds, args.tolerance)
    md = to_markdown(trend)
    print(md, end="")
    if args.markdown:
        with open(args.markdown, "w") as fh:
            fh.write(md)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(trend, fh, indent=2, sort_keys=True)
    flagged = [n for n, m in trend["metrics"].items() if m["regression"]]
    if flagged:
        print("bench_trend: flagged: %s" % ", ".join(sorted(flagged)),
              file=sys.stderr)
    return 1 if (args.strict and flagged) else 0


if __name__ == "__main__":
    sys.exit(main())
