"""Out-of-core streaming end-to-end smoke (tier1 CI).

Builds a dataset 4x larger than the configured chunk cap, writes it to a
``.npy`` file, and trains it through the full out-of-core path — mmap
chunk source, two-round sample binning, double-buffered host->device
pipeline, cross-chunk frontier growth — then verifies from the outside:

- the streamed model is STRUCTURE-IDENTICAL to a single-shot in-memory
  run on the same rows (same splits/thresholds/children/counts; value
  lines are allowed last-ulp float drift from chunked f32 summation);
- predictions agree with the single-shot run to fp32 tolerance;
- the dataset really was chunked (>= 4 chunks) and the bin matrix was
  never materialized whole (``X_binned is None``);
- the pipeline's overlap accounting is sane and reported: sweeps,
  rows transferred, overlap_efficiency in [0, 1], ingest rows/sec;
- host chunks are word-packed exactly when ``--bin-packing`` says so
  (auto resolves to byte for streaming), and every wave runs in
  chunks+1 dispatches (the last chunk's sweep fused with the commit).

Exit code 0 = every assertion holds. The summary JSON goes to ``--out``
(and stdout) so CI uploads it as an artifact; the numbers feed the
BENCH_r12 streaming section.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu

# model-text lines that define tree STRUCTURE (value lines carry
# float-accumulation noise between chunked and single-shot runs)
_STRUCT_KEYS = ("split_feature=", "threshold=", "left_child=",
                "right_child=", "leaf_count=", "internal_count=",
                "num_leaves=", "decision_type=", "cat_boundaries=",
                "cat_threshold=", "num_cat=")


def _struct(model_str):
    return [l for l in model_str.splitlines() if l.startswith(_STRUCT_KEYS)]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="stream_smoke_out",
                    help="the .npy dataset and model dumps land here")
    ap.add_argument("--out", default="", help="write the summary JSON here")
    ap.add_argument("--rows", type=int, default=8000)
    ap.add_argument("--chunk-rows", type=int, default=2000,
                    help="rows per chunk (dataset is rows/chunk-rows chunks)")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--bin-packing", default="auto",
                    choices=("auto", "none", "nibble", "byte"),
                    help="tpu_bin_packing for the STREAMED run (auto "
                    "resolves to byte: word-packed host chunks)")
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)

    import numpy as np
    import lightgbm_tpu as lgb

    r = np.random.RandomState(0)
    n, f = args.rows, 10
    X = r.randn(n, f)
    X[:, 3] = r.randint(0, 8, n)          # a low-cardinality column
    y = (2 * X[:, 0] + np.sin(X[:, 1]) + 0.7 * X[:, 2]
         + 0.3 * r.randn(n) > 0).astype(np.float64)
    npy = os.path.join(args.workdir, "train.npy")
    np.save(npy, X)

    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "tree_growth": "frontier", "deterministic": True,
              "min_data_in_leaf": 20,
              # sample >= n so streamed and in-memory binning see the
              # same boundaries and structure parity is exact
              "bin_construct_sample_cnt": max(200000, n)}

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    # ---- single-shot baseline (in-memory) ------------------------------
    base = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=args.iters)

    # ---- streamed run from the .npy mmap source ------------------------
    sp = dict(params, data_stream_chunk_rows=args.chunk_rows,
              data_stream_prefetch=2, tpu_bin_packing=args.bin_packing)
    ds = lgb.Dataset(npy, label=y, params=sp)
    bst = lgb.train(dict(sp), ds, num_boost_round=args.iters)

    binned = ds.construct()._binned
    check(getattr(binned, "is_streamed", False),
          "dataset took the streamed path")
    check(binned.X_binned is None, "bin matrix never materialized whole")
    nchunks = len(binned.chunks)
    check(nchunks >= 4, ">= 4 host chunks (got %d)" % nchunks)

    # ---- structure parity ----------------------------------------------
    s_base = _struct(base.model_to_string())
    s_stream = _struct(bst.model_to_string())
    check(s_base == s_stream,
          "streamed model structure identical to single-shot "
          "(%d structural lines)" % len(s_base))
    pred_b = base.predict(X[:512])
    pred_s = bst.predict(X[:512])
    max_dp = float(np.max(np.abs(pred_b - pred_s)))
    check(max_dp < 1e-4, "predictions match single-shot "
          "(max |dp| = %.3g)" % max_dp)
    with open(os.path.join(args.workdir, "model_streamed.txt"), "w") as fh:
        fh.write(bst.model_to_string())

    # ---- pipeline accounting -------------------------------------------
    pipe = bst._impl._stream
    check(pipe is not None, "trainer holds a ChunkPipeline")
    stats = pipe.stats() if pipe is not None else {}
    packed = bool(pipe is not None and pipe.packed)
    if pipe is not None:
        want_packed = args.bin_packing != "none"
        check(packed == want_packed,
              "host chunks %s word-packed (tpu_bin_packing=%s)"
              % ("are" if want_packed else "are NOT", args.bin_packing))
        grower = bst._impl._stream_grower
        if grower is not None and grower.waves:
            per_wave = grower.wave_dispatches / grower.waves
            check(per_wave == pipe.num_chunks + 1,
                  "chunks+1 dispatches per wave — last chunk's sweep "
                  "fused with the commit (%.2f vs %d chunks)"
                  % (per_wave, pipe.num_chunks))
        check(stats["num_chunks"] == nchunks,
              "pipeline sweeps all %d chunks" % nchunks)
        check(stats["sweeps"] >= args.iters,
              "at least one sweep per iteration (%d sweeps / %d iters)"
              % (stats["sweeps"], args.iters))
        check(stats["rows_transferred"] == stats["sweeps"] * n,
              "every sweep transfers all %d rows" % n)
        eff = stats["overlap_efficiency"]
        check(0.0 <= eff <= 1.0,
              "overlap_efficiency in [0, 1] (got %.3f)" % eff)
        print("overlap_efficiency: %.3f" % eff)
        print("ingest_rows_per_sec: %.0f" % (stats["ingest_rows_per_sec"]
                                             or 0.0))

    summary = {"rows": n, "chunk_rows": args.chunk_rows,
               "num_chunks": nchunks, "iterations": args.iters,
               "structure_identical": s_base == s_stream,
               "max_pred_delta": max_dp, "bin_packing": args.bin_packing,
               "chunks_word_packed": packed,
               "pipeline": stats, "failures": failures}
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
