#!/usr/bin/env python3
"""Generate docs/Parameters.md from the config table.

The reference generates src/io/config_auto.cpp FROM docs/Parameters.rst
(doc-is-source-of-truth); here the direction is inverted — config.py's
typed table is the source of truth and the doc is derived, so the two can
never drift. Run: python tools/gen_params_doc.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from lightgbm_tpu.config import _PARAMS  # noqa: E402

HEADER = """# Parameters

Generated from `lightgbm_tpu/config.py` by `tools/gen_params_doc.py` —
do not edit by hand. Keys and aliases follow the reference's parameter
table (include/LightGBM/config.h); values are parsed from Python dicts,
CLI `key=value` pairs, and `#`-commented config files alike.

| Parameter | Type | Default | Aliases |
|---|---|---|---|
"""


def main() -> None:
    rows = []
    for name, typ, default, aliases in _PARAMS:
        tname = getattr(typ, "__name__", str(typ))
        dflt = repr(default) if default != "" else "`\"\"`"
        rows.append("| `%s` | %s | %s | %s |" % (
            name, tname, dflt,
            ", ".join("`%s`" % a for a in aliases) if aliases else "—"))
    out = HEADER + "\n".join(rows) + "\n"
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "Parameters.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write(out)
    print("wrote %s (%d parameters)" % (os.path.normpath(path), len(rows)))


if __name__ == "__main__":
    main()
