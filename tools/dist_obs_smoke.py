"""Distributed-observability end-to-end smoke (tier1 CI).

A REAL 2-process run: two OS processes, one CPU device each, glued by
``jax.distributed`` through ``parallel/network.py`` — then the whole
distributed telemetry surface (obs/distributed.py) is exercised from the
outside, in three phases:

- **federation**: both ranks train the same small model with
  ``observability=basic``; rank 1's feature sampling is artificially
  delayed so it becomes a genuine straggler.  Each rank then asserts its
  OWN ``/stats/cluster`` + ``/metrics/cluster`` routes (served from the
  once-per-block allgather cache): both processes present, the skew gauge
  fired on the slow rank, the straggler report routed through the
  HealthMonitor, and the merged Prometheus text carries both
  ``process="0"`` and ``process="1"`` series.
- **crash**: a second 2-process run idles mid-training; the launcher
  SIGTERMs both ranks and asserts each one died BY the signal yet left a
  complete ``events.<rank>.jsonl.<rank>.crash.jsonl`` flight-recorder
  dump (header reason ``sigterm``, ring entries attached).
- **merge**: ``tools/merge_events.py`` zips the per-rank streams + crash
  dumps into one ``timeline.jsonl`` artifact and the launcher asserts the
  merge is complete and time-ordered.

Exit code 0 = every assertion holds.  Summary JSON goes to ``--out`` (and
stdout); per-rank event streams, crash dumps and the merged timeline land
under ``--workdir`` for CI artifact upload.
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WARN_SKEW = 1.2          # fed phase: assert skew >= this (config'd too)
SAMPLE_DELAY_S = 0.25    # rank 1's per-iteration feature-sampling delay
BLOCK = 4                # iterations per train_many call
BLOCKS = 3               # allgather rounds (>= 2: gauges lag one block)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrape(port: int, path: str) -> bytes:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
        return r.read()


# --------------------------------------------------------------- worker
def _init_cluster(port: int):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.parallel import network
    # rank 0's entry doubles as the jax.distributed coordinator address
    network.init(machines="127.0.0.1:%d,127.0.0.1:0" % port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()


def _build_booster(rank: int, workdir: str, extra=None):
    import numpy as np
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.boosting import create_boosting

    r = np.random.RandomState(0)
    X = r.randn(800, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "observability": "basic", "health_monitor": "warn",
              "obs_event_file":
                  os.path.join(workdir, "events.%d.jsonl" % rank),
              "obs_straggler_warn_skew": WARN_SKEW}
    params.update(extra or {})
    cfg = Config(params)
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    return create_boosting(cfg, ds, create_objective(cfg), [])


def _delay_sampling(delay_s: float) -> None:
    """Make THIS rank a straggler: feature-mask sampling happens inside
    the per-block host window (gbdt.py opens t0 before it), so a sleep
    here lands squarely in busy_s."""
    from lightgbm_tpu.boosting.gbdt import GBDT
    orig = GBDT._sample_feature_mask

    def slow(self):
        time.sleep(delay_s)
        return orig(self)

    GBDT._sample_feature_mask = slow


def _worker_federation(rank: int, args) -> int:
    _init_cluster(args.port)
    if rank == 1:
        _delay_sampling(SAMPLE_DELAY_S)
    b = _build_booster(rank, args.workdir, extra={"obs_stats_port": 0})
    for _ in range(BLOCKS):
        b.train_many(BLOCK)

    obs = b.obs
    doc = obs.dist.cluster_stats()
    prom = obs.dist.cluster_prometheus()
    straggler_reports = [r for r in (obs.monitor.reports if obs.monitor
                                     else []) if r.kind == "straggler_wave"]
    res = {"rank": rank,
           "processes": sorted((doc.get("processes") or {}).keys()),
           "skew": (doc.get("straggler") or {}).get("skew"),
           "straggler_process":
               (doc.get("straggler") or {}).get("process"),
           "prom_has_p0": 'process="0"' in prom,
           "prom_has_p1": 'process="1"' in prom,
           "straggler_reports": len(straggler_reports)}
    # the HTTP routes must serve the same cache set_cluster wired up
    if obs.stats is not None:
        hdoc = json.loads(_scrape(obs.stats.port, "/stats/cluster"))
        res["http_processes"] = sorted((hdoc.get("processes") or {}).keys())
        hprom = _scrape(obs.stats.port, "/metrics/cluster").decode()
        res["http_prom_both"] = ('process="0"' in hprom
                                 and 'process="1"' in hprom)
    with open(os.path.join(args.workdir, "fed.rank%d.json" % rank),
              "w") as fh:
        json.dump(res, fh, sort_keys=True)
    # barrier before exit so neither rank tears the coordinator down
    # while the other is still mid-allgather
    from lightgbm_tpu.parallel.network import KvHostComm
    KvHostComm(namespace="lgbm_smoke_done").allgather({"rank": rank})
    return 0


def _worker_crash(rank: int, args) -> int:
    _init_cluster(args.port)
    b = _build_booster(rank, args.workdir, extra={"obs_stats_port": -1})
    b.train_many(BLOCK)     # populate the event stream + flight ring
    assert b.obs.flight is not None and len(b.obs.flight) > 0
    with open(os.path.join(args.workdir,
                           "ready.%d" % rank), "w") as fh:
        fh.write("ok\n")
    while True:             # idle until the launcher SIGTERMs us
        time.sleep(0.05)


# -------------------------------------------------------------- launcher
def _spawn(phase: str, port: int, workdir: str):
    procs = []
    for rank in range(2):
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "",            # one device per process
               "LIGHTGBM_TPU_RANK": str(rank),
               "PYTHONPATH": REPO}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(rank), "--phase", phase,
             "--port", str(port), "--workdir", workdir],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    return procs


def _drain(procs, timeout: float):
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            so, se = p.communicate()
        outs.append((p.returncode, so, se))
    return outs


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="dist_obs_out")
    ap.add_argument("--out", default="", help="summary JSON path")
    ap.add_argument("--worker", type=int, default=-1,
                    help="(internal) run as rank N instead of launching")
    ap.add_argument("--phase", default="fed", choices=["fed", "crash"])
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)

    if args.worker >= 0:
        if args.phase == "fed":
            return _worker_federation(args.worker, args)
        return _worker_crash(args.worker, args)

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    # ---- phase 1: federation + straggler detection ---------------------
    fed_dir = os.path.join(args.workdir, "fed")
    os.makedirs(fed_dir, exist_ok=True)
    outs = _drain(_spawn("fed", _free_port(), fed_dir), timeout=420)
    for rank, (rc, so, se) in enumerate(outs):
        check(rc == 0, "fed rank %d exited 0 (rc=%s)" % (rank, rc))
        if rc != 0:
            print("--- rank %d stdout ---\n%s\n--- rank %d stderr ---\n%s"
                  % (rank, so[-1500:], rank, se[-3000:]))
    results = {}
    for rank in range(2):
        path = os.path.join(fed_dir, "fed.rank%d.json" % rank)
        if os.path.exists(path):
            with open(path) as fh:
                results[rank] = json.load(fh)
    check(len(results) == 2, "both fed ranks reported")
    for rank, res in sorted(results.items()):
        check(res.get("processes") == ["0", "1"],
              "rank %d cluster doc has both processes (got %s)"
              % (rank, res.get("processes")))
        check((res.get("skew") or 0) >= WARN_SKEW,
              "rank %d skew %.3fx >= %.2fx"
              % (rank, res.get("skew") or 0, WARN_SKEW))
        check(res.get("straggler_process") == 1,
              "rank %d identifies rank 1 as the straggler (got %s)"
              % (rank, res.get("straggler_process")))
        check(res.get("prom_has_p0") and res.get("prom_has_p1"),
              "rank %d merged exposition carries both process series"
              % rank)
        check(res.get("straggler_reports", 0) >= 1,
              "rank %d routed >=1 straggler report through HealthMonitor"
              % rank)
        check(res.get("http_processes") == ["0", "1"],
              "rank %d /stats/cluster serves the federated cache" % rank)
        check(res.get("http_prom_both") is True,
              "rank %d /metrics/cluster carries both process series"
              % rank)

    # ---- phase 2: SIGTERM -> flight recorder crash dumps ---------------
    crash_dir = os.path.join(args.workdir, "crash")
    os.makedirs(crash_dir, exist_ok=True)
    procs = _spawn("crash", _free_port(), crash_dir)
    deadline = time.time() + 420
    ready = [os.path.join(crash_dir, "ready.%d" % r) for r in range(2)]
    while time.time() < deadline:
        if all(os.path.exists(p) for p in ready):
            break
        if any(p.poll() is not None for p in procs):
            break               # a worker died early; fall through
        time.sleep(0.2)
    ready_ok = all(os.path.exists(p) for p in ready)
    check(ready_ok, "both crash ranks reached the idle point")
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    outs = _drain(procs, timeout=60)
    for rank, (rc, so, se) in enumerate(outs):
        check(rc in (-signal.SIGTERM, 128 + signal.SIGTERM),
              "crash rank %d died by SIGTERM (rc=%s)" % (rank, rc))
        if rc not in (-signal.SIGTERM, 128 + signal.SIGTERM):
            print("--- rank %d stderr ---\n%s" % (rank, se[-3000:]))
        dump = os.path.join(crash_dir,
                            "events.%d.jsonl.%d.crash.jsonl"
                            % (rank, rank))
        exists = os.path.exists(dump)
        check(exists, "crash rank %d flight dump exists" % rank)
        if exists:
            with open(dump) as fh:
                lines = [json.loads(ln) for ln in fh if ln.strip()]
            hdr = lines[0] if lines else {}
            check(hdr.get("event") == "flight_recorder_dump"
                  and hdr.get("reason") == "sigterm"
                  and hdr.get("process") == rank,
                  "crash rank %d dump header (got %s)" % (rank, hdr))
            check(hdr.get("entries", 0) > 0 and len(lines) == 1
                  + hdr.get("entries", 0),
                  "crash rank %d dump carries its ring (%d entries)"
                  % (rank, hdr.get("entries", 0)))

    # ---- phase 3: merged timeline --------------------------------------
    streams = sorted(
        os.path.join(crash_dir, f) for f in os.listdir(crash_dir)
        if f.endswith(".jsonl"))
    timeline = os.path.join(args.workdir, "timeline.jsonl")
    merged, in_lines = [], 0
    if streams:
        rc = subprocess.call(
            [sys.executable, os.path.join(REPO, "tools",
                                          "merge_events.py")]
            + streams + ["--out", timeline], cwd=REPO)
        check(rc == 0, "merge_events exits 0 over %d streams"
              % len(streams))
        for p in streams:
            with open(p) as fh:
                in_lines += sum(1 for ln in fh if ln.strip())
        if os.path.exists(timeline):
            with open(timeline) as fh:
                merged = [json.loads(ln) for ln in fh if ln.strip()]
        check(len(merged) == in_lines,
              "timeline complete (%d/%d records)"
              % (len(merged), in_lines))
        # crash dumps are internally non-monotonic by design (the header
        # is stamped at dump time, the ring records keep their original
        # ts) and the merge keeps in-stream order authoritative, so the
        # cross-stream ts assertion covers the live streams only
        ts = [float(r.get("ts", 0)) for r in merged
              if not r["stream"].endswith(".crash.jsonl")]
        check(ts == sorted(ts), "timeline live streams are time-ordered")
        check(all("stream" in r for r in merged),
              "every timeline record attributes its stream")
        procs_seen = {r.get("process") for r in merged
                      if "process" in r}
        check({0, 1} <= procs_seen,
              "timeline carries events from both processes (got %s)"
              % sorted(procs_seen))
    else:
        check(False, "crash phase produced event streams to merge")

    summary = {"failures": failures,
               "federation": results,
               "timeline_records": len(merged),
               "streams_merged": len(streams)}
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
