"""Observability end-to-end smoke (tier1 CI).

Trains a small binary model for a few iterations with the full telemetry
stack on — ``observability=full``, health monitor warning, the JSON-lines
event stream, the in-process stats HTTP endpoint, and a 1-iteration
Perfetto capture window — then verifies the whole pipe from the outside:

- scrapes ``/metrics`` (Prometheus text), ``/stats`` (JSON snapshot) and
  ``/healthz`` over HTTP and asserts the iteration counter matches;
- asserts ZERO health anomalies on the healthy run (warn mode must stay
  silent when nothing is wrong);
- asserts the event stream carries one event per iteration plus the
  ``train_done`` record;
- reports (but does not require) the Perfetto trace artifacts — the
  capture helper degrades gracefully where the profiler is unavailable.

Exit code 0 = every assertion holds. The summary JSON goes to ``--out``
(and stdout); the event stream and any trace land under ``--workdir`` so
CI can upload them as artifacts.
"""
import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu


def _scrape(port: int, path: str) -> bytes:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
        return r.read()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="obs_smoke_out",
                    help="event stream + perfetto artifacts land here")
    ap.add_argument("--out", default="", help="write the summary JSON here")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    event_file = os.path.join(args.workdir, "events.jsonl")
    trace_dir = os.path.join(args.workdir, "perfetto")

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine

    r = np.random.RandomState(0)
    n, f = 3000, 8
    X = r.randn(n, f).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * r.randn(n)) > 0) \
        .astype(np.float32)

    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "tree_growth": "frontier",
              "observability": "full",
              "health_monitor": "warn",
              "obs_event_file": event_file,
              "obs_stats_port": 0,            # ephemeral; read back below
              "obs_perfetto_dir": trace_dir,
              "obs_perfetto_start": 1,
              "obs_perfetto_iters": 1}
    bst = engine.train(params, lgb.Dataset(X, label=y),
                       num_boost_round=args.iters)

    obs = bst._impl.obs
    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    # ---- health: a clean run must report zero anomalies ----------------
    mon = obs.monitor
    check(mon is not None and mon.action == "warn",
          "health monitor armed in warn mode")
    anomalies = mon.anomaly_count() if mon is not None else -1
    check(anomalies == 0, "zero health anomalies (got %d)" % anomalies)

    # ---- event stream --------------------------------------------------
    with open(event_file) as fh:
        events = [json.loads(line) for line in fh if line.strip()]
    kinds = [e.get("event") for e in events]
    iters = [e for e in events if e.get("event") == "iteration"]
    check(len(iters) >= args.iters,
          ">= %d iteration events (got %d)" % (args.iters, len(iters)))
    done = [e for e in events if e.get("event") == "train_done"]
    check(len(done) == 1 and done[0].get("iterations") == args.iters,
          "train_done event with iterations=%d" % args.iters)
    check(not done or done[0].get("anomalies") == 0,
          "train_done reports zero anomalies")

    # ---- HTTP scrape (the stats server outlives training) --------------
    check(obs.stats is not None, "stats endpoint bound")
    scraped = {}
    if obs.stats is not None:
        port = obs.stats.port
        prom = _scrape(port, "/metrics").decode()
        check("lgbm_train_iterations_total %d" % args.iters in prom,
              "/metrics exposes lgbm_train_iterations_total")
        check("lgbm_train_iteration_seconds" in prom,
              "/metrics exposes the iteration-time summary")
        snap = json.loads(_scrape(port, "/stats"))
        check("metrics" in snap and "ts" in snap, "/stats snapshot parses")
        hz = json.loads(_scrape(port, "/healthz"))
        check(hz.get("status") == "ok" and hz.get("anomalies") == 0,
              "/healthz reports ok with zero anomalies")
        # ---- single-process degenerate case (obs/distributed.py) -------
        # the cluster routes must serve exactly the local view, with no
        # DistributedObs constructed and no host allgather ever issued
        check(obs.dist is None,
              "no DistributedObs constructed single-process (auto mode)")
        prom_local = _scrape(port, "/metrics")
        prom_cluster = _scrape(port, "/metrics/cluster")
        check(prom_cluster == prom_local,
              "/metrics/cluster byte-equal to /metrics single-process")
        snap_cluster = json.loads(_scrape(port, "/stats/cluster"))
        check(snap_cluster.get("metrics") == snap.get("metrics"),
              "/stats/cluster metrics map identical to /stats")
        check("lgbm_dist_allgathers_total" not in snap.get("metrics", {}),
              "no allgather counter registered (none issued)")
        check('process="' not in prom_local.decode(),
              "no process= federation label single-process")
        scraped = {"port": port, "healthz": hz,
                   "prom_lines": len(prom.splitlines())}
        obs.stats.stop()

    # Perfetto artifacts are best-effort: report what landed
    trace_files = []
    for root, _dirs, files in os.walk(trace_dir):
        trace_files += [os.path.relpath(os.path.join(root, fn), trace_dir)
                        for fn in files]
    print("perfetto artifacts: %d file(s)" % len(trace_files))

    summary = {"iterations": args.iters, "anomalies": anomalies,
               "event_kinds": sorted(set(k for k in kinds if k)),
               "events": len(events), "scrape": scraped,
               "perfetto_files": len(trace_files),
               "failures": failures}
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
