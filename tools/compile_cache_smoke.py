"""Cold-vs-warm persistent-compile-cache smoke (tier1 CI).

Runs a tiny 2-iteration frontier training probe with ``compile_cache_dir``
pointed at a shared directory and emits one JSON object describing the
compile accounting. The CI workflow runs it TWICE with the same directory:

- run 1 (cold): populates the cache; asserts the in-process invariant that
  a second ``train_many`` window after warmup performs ZERO backend
  compiles (all wave-width buckets compiled up front);
- run 2 (``--expect-warm``): additionally asserts every compile request was
  served from the persistent cache (zero misses), i.e. a restarted process
  recompiles nothing — the cross-process half of "zero recompiles after
  warmup".

Exit code 0 = all assertions hold; 1 = a compile invariant broke. The JSON
goes to ``--out`` (and stdout) so CI can upload it as an artifact.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cache-dir", required=True,
                    help="shared persistent compile cache directory")
    ap.add_argument("--out", default="", help="write the probe JSON here")
    ap.add_argument("--expect-warm", action="store_true",
                    help="assert zero persistent-cache misses (run 2)")
    ap.add_argument("--iters", type=int, default=2)
    args = ap.parse_args()

    # cache + counters BEFORE any compile (binning jits too), so the
    # persistent cache covers the whole probe, not just training
    from lightgbm_tpu.profiling import (backend_compile_count,
                                        compile_cache_stats,
                                        enable_compile_cache,
                                        install_compile_hook)
    install_compile_hook()
    enable_compile_cache(args.cache_dir)

    import jax
    import numpy as np
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.objectives import create_objective

    r = np.random.RandomState(0)
    n, f = 5000, 10
    X = r.randn(n, f).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * r.randn(n)) > 0) \
        .astype(np.float32)

    cfg = Config({"objective": "binary", "num_leaves": 31, "verbosity": -1,
                  "tree_growth": "frontier",
                  "compile_cache_dir": args.cache_dir})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])

    t0 = time.time()
    b.train_many(args.iters)          # compiles + pre-warms the ladder
    jax.block_until_ready(b.scores)
    warmup_s = time.time() - t0
    floor = backend_compile_count()

    t0 = time.time()
    b.train_many(args.iters)          # must reuse every executable
    jax.block_until_ready(b.scores)
    train_s = time.time() - t0

    recompiles = backend_compile_count() - floor
    stats = compile_cache_stats()
    ladder = getattr(b, "_ladder_warmup", None) or {}
    result = {
        "iters": args.iters,
        "expect_warm": bool(args.expect_warm),
        "warmup_s": round(warmup_s, 3),
        "train_s": round(train_s, 3),
        "backend_compiles_total": stats["backend_compiles"],
        "recompiles_after_warmup": recompiles,
        "compile_cache_hits": stats["persistent_cache_hits"],
        "compile_cache_misses": stats["persistent_cache_misses"],
        "frontier_wave_ladder": list(ladder.get("widths", [])),
        "frontier_ladder_compiles": {
            str(w): c for w, c in
            ladder.get("per_bucket_compiles", {}).items()},
    }
    errors = []
    if recompiles != 0:
        errors.append("%d XLA compiles after warmup (expected 0)"
                      % recompiles)
    if args.expect_warm and stats["persistent_cache_misses"] != 0:
        errors.append("%d persistent-cache misses on a warm cache "
                      "(expected 0)" % stats["persistent_cache_misses"])
    if errors:
        result["errors"] = errors
    line = json.dumps(result, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
