"""SLO burn-rate + request-tracing end-to-end smoke (tier1 CI).

Boots a 2-replica serving fleet (this script re-execed with
``--serve-replica``, same process pattern as fleet_smoke.py) with

- declarative SLOs armed: ``serve_slo_p99_ms`` (latency) and
  ``serve_slo_availability``, judged over deliberately short burn
  windows so CI sees a full fast-window cycle in seconds;
- request tracing on (``obs_trace``) with a per-replica event file; and
- an injected ``serve_delay`` fault that sleeps every dispatched
  predict past the latency threshold.

Then drives mixed traffic at both HTTP front-ends (some requests carry a
client-minted ``x-lgbm-trace`` header) and asserts the whole
observability story:

1. the latency SLO flips to *burning* on both replicas within ONE fast
   window of the first request — the multi-window clamp makes a
   sustained breach responsive even in a young process;
2. ``/slo`` agrees across replicas (same specs, same verdicts:
   ``serve_p99`` burning, ``serve_availability`` quiet) and the
   ``lgbm_slo_burning`` gauge rides the Prometheus exposition;
3. a kept slow trace's span tree names the stage that ate the latency:
   the batch's ``predict`` span holds the delay as SELF time (its
   ``device_*`` children stay fast), and the client-minted trace id
   survives the HTTP hop into the kept trace;
4. the span events landed in each replica's event file and
   ``tools/merge_events.py`` reconstructs parent/child trees from the
   merged streams;
5. tracing + SLO judging cost no correctness: zero recompiles after
   warmup, zero server-side errors, zero shed.

Exit 0 = every assertion holds. Summary JSON to ``--out`` + stdout.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu

DELAY_MS = 150.0          # injected per-dispatch sleep
P99_THRESHOLD_MS = 50.0   # latency SLO threshold (every request breaches)
FAST_WINDOW_S = 3.0
SLOW_WINDOW_S = 6.0
TICK_S = 0.25


def _get(base: str, path: str) -> bytes:
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return r.read()


def _post(base: str, path: str, doc, headers=None) -> dict:
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(), headers=hdrs)
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _wait(pred, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def serve_replica(name: str, workdir: str) -> int:
    """One replica: build_app with SLOs + tracing + the delay fault,
    roll the initial snapshot, warm up, publish the base URL."""
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(workdir, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.fleet import FileKvClient
    from lightgbm_tpu.serving.server import build_app, make_server

    cfg = Config({
        "objective": "regression", "verbosity": -1,
        "checkpoint_dir": os.path.join(workdir, "ckpt"),
        "fleet_kv_dir": os.path.join(workdir, "kv"),
        "fleet_replica": name,
        "fleet_announce_period_s": 0.1,
        "serve_min_bucket": 16, "serve_max_batch": 128,
        # --- the fault under test: every dispatched predict sleeps
        "fault_inject": "serve_delay@request:*:%d" % int(DELAY_MS),
        # --- request tracing: the delay (>= slow_ms) keeps every trace
        "obs_trace": True,
        "obs_trace_slow_ms": 100.0,
        "obs_trace_sample": 0.05,
        "obs_event_file": os.path.join(workdir, "events.%s.jsonl" % name),
        # --- SLOs with CI-short windows
        "serve_slo_p99_ms": P99_THRESHOLD_MS,
        "serve_slo_target": 0.99,
        "serve_slo_availability": 0.999,
        "slo_fast_window_s": FAST_WINDOW_S,
        "slo_slow_window_s": SLOW_WINDOW_S,
        "slo_burn_warn": 2.0,
        "slo_tick_s": TICK_S,
    })
    app = build_app(cfg)
    if not _wait(lambda: app.watcher._last_id >= 0, timeout_s=60.0):
        print("replica %s: initial snapshot never rolled" % name,
              file=sys.stderr)
        return 1
    app.engine.warmup()            # marks the recompile floor
    server = make_server(app, port=0)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    FileKvClient(cfg.fleet_kv_dir).key_value_set("http/" + name, base)
    signal.signal(signal.SIGTERM, lambda *_: threading.Thread(
        target=server.shutdown, daemon=True).start())
    try:
        server.serve_forever()
    finally:
        server.server_close()
        app.close()
    return 0


def _self_times(records):
    """``[(name, self_ms)]`` per span: duration minus direct children —
    the stage-attribution view of one trace's flat records."""
    child_sum = {}
    for r in records:
        p = r.get("parent")
        if p is not None:
            child_sum[p] = child_sum.get(p, 0.0) + float(r["dur_ms"])
    return [(r["name"],
             float(r["dur_ms"]) - child_sum.get(r["span_id"], 0.0))
            for r in records]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="slo_smoke_out")
    ap.add_argument("--out", default="", help="write the summary JSON here")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--serve-replica", default="",
                    help=argparse.SUPPRESS)   # internal: replica mode
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    if args.serve_replica:
        return serve_replica(args.serve_replica, args.workdir)
    ckpt_dir = os.path.join(args.workdir, "ckpt")

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import callback, engine
    from lightgbm_tpu.fleet import FileKvClient

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg), flush=True)

    # ---- 1. train a small model the replicas will roll -----------------
    r = np.random.RandomState(0)
    n, f = 1500, 6
    X = r.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1]).astype(np.float32)
    engine.train({"objective": "regression", "num_leaves": 15,
                  "verbosity": -1}, lgb.Dataset(X, label=y),
                 num_boost_round=args.rounds,
                 callbacks=[callback.checkpoint(ckpt_dir, period=1)])

    # ---- 2. spawn the replicas -----------------------------------------
    kv = FileKvClient(os.path.join(args.workdir, "kv"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = {name: subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--serve-replica", name, "--workdir", args.workdir], env=env)
        for name in ("a", "b")}
    summary = {}
    stop_traffic = threading.Event()
    lock = threading.Lock()
    counts = {"sent": 0, "errors": 0, "overloaded": 0}
    minted = "c0ffee%010d"   # client trace ids (hex) by thread index

    def traffic(base, idx):
        rs = np.random.RandomState(100 + idx)
        first = True
        while not stop_traffic.is_set():
            rows = rs.randn(16, f)
            # the first request of each thread carries a client-minted
            # trace id; the rest let the server mint
            hdrs = {"x-lgbm-trace": minted % idx} if first else None
            first = False
            try:
                out = _post(base, "/predict",
                            {"model": "default", "data": rows.tolist()},
                            headers=hdrs)
                ok = len(out.get("predictions", [])) == 16
            except urllib.error.HTTPError as e:
                with lock:
                    counts["overloaded" if e.code == 503 else "errors"] += 1
                continue
            except Exception:
                with lock:
                    counts["errors"] += 1
                continue
            with lock:
                counts["sent"] += 1
                counts["errors"] += 0 if ok else 1

    threads = []
    try:
        check(_wait(lambda: all(kv.try_get("http/" + m) for m in procs),
                    timeout_s=180.0),
              "both replica processes came up warmed")
        replicas = sorted((m, kv.try_get("http/" + m)) for m in procs)

        def slo_doc(base):
            return json.loads(_get(base, "/slo"))

        for name, base in replicas:
            doc = slo_doc(base)
            check(sorted(doc.get("slos", {})) ==
                  ["serve_availability", "serve_p99"],
                  "replica %s declares both SLOs on /slo" % name)
            check(not doc["slos"]["serve_p99"]["burning"],
                  "replica %s: p99 SLO quiet before traffic" % name)

        # ---- 3. delayed traffic -> burn within one fast window ---------
        t_traffic = time.monotonic()
        threads = [threading.Thread(target=traffic, args=(b, i),
                                    daemon=True)
                   for i, (_, b) in enumerate(replicas)]
        for t in threads:
            t.start()

        flips = {}

        def burning(name, base):
            doc = slo_doc(base)["slos"]["serve_p99"]
            if doc["burning"] and name not in flips:
                flips[name] = time.monotonic() - t_traffic
            return doc["burning"]

        for name, base in replicas:
            ok = _wait(lambda: burning(name, base),
                       timeout_s=FAST_WINDOW_S + 5.0, interval_s=0.1)
            check(ok, "replica %s: p99 SLO flipped to burning" % name)
            if ok:
                check(flips[name] <= FAST_WINDOW_S,
                      "replica %s: flip in %.2fs <= one fast window "
                      "(%.0fs)" % (name, flips[name], FAST_WINDOW_S))

        # ---- 4. /slo agrees across replicas ----------------------------
        docs = {name: slo_doc(base) for name, base in replicas}
        for name in docs:
            p99 = docs[name]["slos"]["serve_p99"]
            avail = docs[name]["slos"]["serve_availability"]
            check(p99["burning"] and p99["fast_burn"] >= 2.0,
                  "replica %s: p99 burning (fast burn %.1fx)"
                  % (name, p99["fast_burn"]))
            check(not avail["burning"],
                  "replica %s: availability SLO stays quiet" % name)
        check(docs["a"]["slos"]["serve_p99"]["burning"] ==
              docs["b"]["slos"]["serve_p99"]["burning"],
              "/slo verdicts agree across replicas")
        for name, base in replicas:
            prom = _get(base, "/metrics/prometheus").decode()
            check('lgbm_slo_burning{slo="serve_p99"} 1' in prom,
                  "replica %s exports lgbm_slo_burning=1" % name)

        # a little steady-state so the verdicts rest on real volume (the
        # flip itself lands after a couple of 150ms requests)
        time.sleep(2.5)
        stop_traffic.set()
        for t in threads:
            t.join(timeout=10.0)

        # ---- 5. the kept slow trace names the guilty stage -------------
        slow_self_ms = {}
        for name, base in replicas:
            traces = json.loads(_get(base, "/traces"))["traces"]
            slow = [t for t in traces if t["reason"] == "slow"]
            check(len(slow) > 0,
                  "replica %s kept slow traces (%d)" % (name, len(slow)))
            if not slow:
                continue
            tr = slow[-1]
            names = {r["name"] for r in tr["records"]}
            check({"request", "queue_wait", "batch", "predict"} <= names,
                  "replica %s: slow trace has the full span tree (%s)"
                  % (name, sorted(names)))
            worst = max(_self_times(tr["records"]), key=lambda kv: kv[1])
            slow_self_ms[name] = {"stage": worst[0],
                                  "self_ms": round(worst[1], 1)}
            check(worst[0] == "predict" and worst[1] >= DELAY_MS * 0.8,
                  "replica %s: 'predict' ate the latency (%.0fms self "
                  "time)" % (name, worst[1]))
            check(any(t["trace"].startswith("c0ffee") for t in traces),
                  "replica %s kept a client-minted trace id" % name)

        # ---- 6. event files + merge reconstruct the trees --------------
        ev_files = [os.path.join(args.workdir, "events.%s.jsonl" % m)
                    for m in procs]
        check(all(os.path.exists(p) for p in ev_files),
              "both replicas wrote span event files")
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import merge_events
        merged = list(merge_events.merge([p for p in ev_files
                                          if os.path.exists(p)]))
        trees = merge_events.build_span_trees(merged)
        check(len(trees) > 0, "merged streams yield %d span tree(s)"
              % len(trees))
        rooted = [t for t in trees.values() if t["roots"]]
        check(len(rooted) > 0 and all(
            not t["orphans"] for t in rooted),
              "reconstructed trees are parent-linked (no orphans)")

        # ---- 7. tracing + SLOs cost nothing ----------------------------
        with lock:
            sent, errors = counts["sent"], counts["errors"]
            overloaded = counts["overloaded"]
        check(sent > 20, "drove %d live requests through the fleet" % sent)
        check(errors == 0, "zero client-observed errors (got %d)" % errors)
        check(overloaded == 0, "zero shed requests (got %d)" % overloaded)
        stats = {name: json.loads(_get(b, "/stats"))
                 for name, b in replicas}
        for name, _ in replicas:
            snap = stats[name]
            check(snap.get("recompiles_after_warmup", -1) == 0,
                  "replica %s: zero recompiles after warmup (got %s) "
                  "with tracing on" % (name,
                                       snap.get("recompiles_after_warmup")))
            check(snap.get("errors") == 0 and snap.get("shed") == 0,
                  "replica %s: no server-side errors or shed" % name)

        summary = {
            "requests": sent,
            "burn_flip_s": {k: round(v, 3) for k, v in flips.items()},
            "fast_window_s": FAST_WINDOW_S,
            "slow_trace_attribution": slow_self_ms,
            "span_trees_merged": len(trees),
            "p99_ms": {name: stats[name]["latency_ms"]["p99_ms"]
                       for name, _ in replicas},
        }
    finally:
        stop_traffic.set()
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()

    summary["failures"] = failures
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
