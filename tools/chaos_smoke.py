"""Chaos end-to-end smoke (tier1 CI): fault-injected failure drills.

Every resilience contract in docs/Resilience.md, exercised from the
OUTSIDE with real processes and the shipped fault-injection plans:

- **kill**: a trainer child is SIGKILLed mid-run by its own armed
  ``kill@iter:3`` fault; the :class:`ProcessSupervisor` restarts it
  (``LGBM_SUPERVISOR_ATTEMPT`` gates the fault to attempt 0), the rerun
  auto-resumes from the checkpoint directory, and the final model's
  trees are byte-identical to an uninterrupted golden run.
- **exhaust**: an in-process supervised run whose ``crash@iter:*`` fault
  never stops firing burns its restart budget; the terminal error names
  the last flight-recorder dump and that dump exists on disk (CI
  artifact).
- **kv**: a REAL 2-process ``jax.distributed`` cluster. Round 0 proves
  retry: rank 0 arms ``kv_error@round:0`` and the allgather still
  completes through the transient. Round 1 proves surfacing: rank 1
  abstains, rank 0's bounded wait fails with namespace / round / rank /
  peer / key / elapsed-ms context.
- **overload**: a serving queue with ``serve_max_queue_rows`` bounded
  admission under a request burst (an injected ``serve_delay`` makes the
  engine slow): queued rows never exceed the bound, excess requests shed
  fast with OverloadedError + retry-after, admitted requests all answer,
  and drain-stop completes cleanly.
- **hotroll**: a staged all-NaN model is REFUSED by canary validation
  (``lgbm_serving_rollbacks_total`` ticks) while the prior generation
  keeps serving finite predictions.

Exit code 0 = every assertion holds. Summary JSON goes to ``--out`` (and
stdout); models, checkpoints, and flight dumps land under ``--workdir``
for CI artifact upload.
"""
import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KILL_AT = 3          # kill@iter:KILL_AT in the child trainer
ROUNDS = 8           # total boosting rounds per training scenario
QUEUE_ROWS = 8       # serve_max_queue_rows for the overload burst
BURST = 12           # concurrent 2-row requests thrown at the queue


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _train_data():
    import numpy as np
    r = np.random.RandomState(11)
    X = r.randn(240, 5)
    y = (X[:, 0] + 2.0 * X[:, 1] + 0.2 * r.randn(240) > 0)
    return X, y.astype(np.float64)


def _trees_only(model_text: str) -> str:
    """Model text minus the parameters echo (which legitimately differs:
    checkpoint paths, the fault plan itself)."""
    return model_text.split("\nparameters:", 1)[0]


# --------------------------------------------------------------- workers
def _worker_train(args) -> int:
    """One training attempt: checkpoint every iteration, arm the fault
    plan on supervisor attempt 0 only, save the final model."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine
    from lightgbm_tpu.resilience.supervisor import ATTEMPT_ENV

    attempt = int(os.environ.get(ATTEMPT_ENV, "0"))
    X, y = _train_data()
    params = dict(objective="binary", num_leaves=5, min_data_in_leaf=5,
                  verbosity=-1, checkpoint_dir=args.ckpt,
                  checkpoint_period=1)
    if args.fault and attempt == 0:
        params["fault_inject"] = args.fault
    ds = lgb.Dataset(X, label=y, params=dict(params))
    bst = engine.train(dict(params), ds, num_boost_round=ROUNDS,
                       verbose_eval=False)
    bst.save_model(args.model_out)
    return 0


def _init_cluster(port: int) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from lightgbm_tpu.parallel import network
    network.init(machines="127.0.0.1:%d,127.0.0.1:0" % port,
                 num_machines=2, time_out=60)
    assert jax.process_count() == 2, jax.process_count()


def _worker_kv(rank: int, args) -> int:
    """Round 0: allgather through an injected transient error (retry).
    Round 1: rank 1 abstains so rank 0's bounded wait surfaces a
    context-rich timeout error."""
    _init_cluster(args.port)
    from lightgbm_tpu.log import LightGBMError
    from lightgbm_tpu.parallel.network import KvHostComm
    from lightgbm_tpu.resilience import faults

    res = {"rank": rank}
    if rank == 0:
        faults.install_plan("kv_error@round:0")
    comm = KvHostComm(namespace="lgbm_chaos_kv",
                      timeout_ms=4000 if rank == 0 else 60000,
                      retries=2, retry_backoff_s=0.05)
    out = comm.allgather({"rank": rank})
    res["round0_peers"] = sorted(o["rank"] for o in out)
    if rank == 0:
        plan = faults.active_plan()
        res["fault_fired"] = bool(plan and plan.faults[0].fires == 1)
        err = ""
        try:
            comm.allgather({"rank": rank})    # peer 1 never publishes
        except LightGBMError as e:
            err = str(e)
        res["round1_error"] = err
    with open(os.path.join(args.workdir, "kv.rank%d.json" % rank),
              "w") as fh:
        json.dump(res, fh, sort_keys=True)
    if rank == 0:
        with open(os.path.join(args.workdir, "kv_done"), "w") as fh:
            fh.write("ok\n")
    else:
        # keep the cluster healthy while rank 0 waits out its timeout;
        # abstaining from the allgather is the failure being injected
        deadline = time.time() + 120
        done = os.path.join(args.workdir, "kv_done")
        while time.time() < deadline and not os.path.exists(done):
            time.sleep(0.2)
    return 0


# -------------------------------------------------------- scenario: kill
def _scenario_kill(args, check) -> dict:
    from lightgbm_tpu.resilience.supervisor import ProcessSupervisor

    def spawn_args(ckpt, model_out, fault):
        return [sys.executable, os.path.abspath(__file__),
                "--worker", "train", "--workdir", args.workdir,
                "--ckpt", ckpt, "--model-out", model_out,
                "--fault", fault]

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    golden_model = os.path.join(args.workdir, "golden.txt")
    rc = subprocess.call(
        spawn_args(os.path.join(args.workdir, "ckpt_g"), golden_model, ""),
        env=env, cwd=REPO)
    check(rc == 0, "kill: golden trainer exited 0 (rc=%s)" % rc)

    victim_model = os.path.join(args.workdir, "victim.txt")
    sup = ProcessSupervisor(
        spawn_args(os.path.join(args.workdir, "ckpt_v"), victim_model,
                   "kill@iter:%d" % KILL_AT),
        max_restarts=2, backoff_s=0.2, backoff_max_s=1.0, env=env, cwd=REPO)
    rc = sup.run()
    check(rc == 0, "kill: supervised trainer converged (rc=%s)" % rc)
    check(sup.restarts >= 1 and sup.attempts[0] != 0,
          "kill: attempt 0 died by the armed fault (attempts=%s)"
          % sup.attempts)
    identical = False
    if os.path.exists(golden_model) and os.path.exists(victim_model):
        identical = (_trees_only(open(golden_model).read())
                     == _trees_only(open(victim_model).read()))
    check(identical, "kill: resumed model trees byte-identical to golden")
    return {"attempts": sup.attempts, "restarts": sup.restarts,
            "identical": identical}


# ----------------------------------------------------- scenario: exhaust
def _scenario_exhaust(args, check) -> dict:
    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine
    from lightgbm_tpu.log import LightGBMError
    from lightgbm_tpu.resilience import faults

    X, y = _train_data()
    params = dict(objective="binary", num_leaves=5, min_data_in_leaf=5,
                  verbosity=-1,
                  checkpoint_dir=os.path.join(args.workdir, "ckpt_x"),
                  checkpoint_period=1, fault_inject="crash@iter:*",
                  supervise=True, supervise_max_restarts=1,
                  supervise_backoff_s=0.05, supervise_backoff_max_s=0.1,
                  observability="basic",
                  obs_event_file=os.path.join(args.workdir,
                                              "train_events.jsonl"))
    ds = lgb.Dataset(X, label=y, params=dict(params))
    msg, dump = "", ""
    try:
        engine.train(dict(params), ds, num_boost_round=4,
                     verbose_eval=False)
    except LightGBMError as e:
        msg = str(e)
    finally:
        faults.clear_plan()
    check("after 1 restart" in msg,
          "exhaust: budget exhaustion surfaced (got %r)" % msg[:120])
    check("last flight dump:" in msg,
          "exhaust: terminal error names the flight dump")
    if "last flight dump:" in msg:
        dump = msg.rsplit("last flight dump:", 1)[1].strip().rstrip(")")
        check(os.path.exists(dump),
              "exhaust: flight dump exists at %s" % dump)
    return {"error": msg[:300], "flight_dump": dump}


# ---------------------------------------------------------- scenario: kv
def _scenario_kv(args, check) -> dict:
    port = _free_port()
    procs = []
    for rank in range(2):
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
               "LIGHTGBM_TPU_RANK": str(rank), "PYTHONPATH": REPO}
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", "kv", "--rank", str(rank),
             "--port", str(port), "--workdir", args.workdir],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    for rank, p in enumerate(procs):
        try:
            so, se = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            so, se = p.communicate()
        check(p.returncode == 0,
              "kv: rank %d exited 0 (rc=%s)" % (rank, p.returncode))
        if p.returncode != 0:
            print("--- kv rank %d stderr ---\n%s" % (rank, se[-3000:]))
    results = {}
    for rank in range(2):
        path = os.path.join(args.workdir, "kv.rank%d.json" % rank)
        if os.path.exists(path):
            with open(path) as fh:
                results[rank] = json.load(fh)
    check(all(r.get("round0_peers") == [0, 1] for r in results.values())
          and len(results) == 2,
          "kv: round-0 allgather completed on both ranks")
    r0 = results.get(0, {})
    check(r0.get("fault_fired") is True,
          "kv: the injected transient error fired (and was retried)")
    err = r0.get("round1_error", "")
    for needle in ("lgbm_chaos_kv", "rank=0", "peer=1", "key=",
                   "elapsed=", "attempts="):
        check(needle in err,
              "kv: timeout error carries %r (got %r)" % (needle, err[:160]))
    return {"round1_error": err[:300]}


# ---------------------------------------------------- scenario: overload
def _scenario_overload(args, check) -> dict:
    import numpy as np
    from lightgbm_tpu.log import OverloadedError
    from lightgbm_tpu.resilience import faults
    from lightgbm_tpu.serving import MicroBatchQueue, ServingEngine
    from lightgbm_tpu.serving.registry import ModelBundle

    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine as train_engine

    X, y = _train_data()
    params = dict(objective="binary", num_leaves=5, min_data_in_leaf=5,
                  verbosity=-1)
    ds = lgb.Dataset(X, label=y, params=dict(params))
    bst = train_engine.train(dict(params), ds, num_boost_round=3,
                             verbose_eval=False)
    eng = ServingEngine(max_batch=16, min_bucket=16)
    eng.registry.register(ModelBundle.from_booster("m", bst))
    eng.warmup()

    # a slow engine is what makes the queue fill: 60 ms per dispatch
    faults.install_plan("serve_delay@req:*:60")
    q = MicroBatchQueue(eng, max_rows=2, deadline_ms=5.0,
                        max_queue_rows=QUEUE_ROWS).start()
    outcomes, rows_seen = [], []
    lock = threading.Lock()

    def one(i):
        try:
            fut = q.submit("m", np.zeros((2, 5), np.float32))
            with lock:
                rows_seen.append(eng.metrics.queue_rows)
            outcomes.append(("ok", fut.result(timeout=30)))
        except OverloadedError as e:
            outcomes.append(("shed", e))
        time.sleep(0.001 * i)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(BURST)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    q.stop()                       # graceful drain
    faults.clear_plan()

    served = [o for o in outcomes if o[0] == "ok"]
    sheds = [o for o in outcomes if o[0] == "shed"]
    check(len(served) + len(sheds) == BURST,
          "overload: every request resolved (%d ok + %d shed)"
          % (len(served), len(sheds)))
    check(len(sheds) >= 1, "overload: bounded admission shed load")
    check(all(o[1].shape == (2,) for o in served),
          "overload: admitted requests all answered")
    check(all(getattr(o[1], "retry_after_s", 0) > 0 for o in sheds),
          "overload: shed errors carry a retry-after hint")
    check(max(rows_seen or [0]) <= QUEUE_ROWS,
          "overload: queued rows stayed <= serve_max_queue_rows=%d "
          "(max seen %d)" % (QUEUE_ROWS, max(rows_seen or [0])))
    check(eng.metrics.shed == len(sheds),
          "overload: lgbm_serving_shed_total == observed sheds")
    return {"served": len(served), "shed": len(sheds),
            "max_queue_rows_seen": max(rows_seen or [0])}


# ----------------------------------------------------- scenario: hotroll
def _scenario_hotroll(args, check) -> dict:
    import re
    import numpy as np
    from lightgbm_tpu.log import LightGBMError
    from lightgbm_tpu.serving import ServingEngine

    import lightgbm_tpu as lgb
    from lightgbm_tpu import engine as train_engine

    X, y = _train_data()
    params = dict(objective="binary", num_leaves=5, min_data_in_leaf=5,
                  verbosity=-1)
    ds = lgb.Dataset(X, label=y, params=dict(params))
    bst = train_engine.train(dict(params), ds, num_boost_round=3,
                             verbose_eval=False)
    good = os.path.join(args.workdir, "roll_good.txt")
    bad = os.path.join(args.workdir, "roll_bad.txt")
    bst.save_model(good)
    text = open(good).read()
    poisoned = re.sub(
        r"leaf_value=([^\n]+)",
        lambda m: "leaf_value=" + " ".join(
            ["nan"] * len(m.group(1).split())), text)
    open(bad, "w").write(poisoned)

    eng = ServingEngine(max_batch=16, min_bucket=16)
    eng.registry.register(eng.stage_and_prewarm("m", good), replace=True)
    ref = eng.predict("m", X[:4])
    refused = ""
    try:
        eng.stage_and_prewarm("m", bad)
    except LightGBMError as e:
        refused = str(e)
    check("canary" in refused,
          "hotroll: NaN model refused by canary validation (got %r)"
          % refused[:120])
    check(eng.metrics.rollbacks == 1,
          "hotroll: lgbm_serving_rollbacks_total ticked")
    out = eng.predict("m", X[:4])
    check(np.isfinite(out).all() and np.array_equal(out, ref),
          "hotroll: prior generation still serves identical finite output")
    return {"refused": refused[:200], "rollbacks": eng.metrics.rollbacks}


# -------------------------------------------------------------- launcher
def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="chaos_out")
    ap.add_argument("--out", default="", help="summary JSON path")
    ap.add_argument("--worker", default="",
                    help="(internal) run as a worker: train | kv")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--ckpt", default="")
    ap.add_argument("--model-out", dest="model_out", default="")
    ap.add_argument("--fault", default="")
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)

    if args.worker == "train":
        return _worker_train(args)
    if args.worker == "kv":
        return _worker_kv(args.rank, args)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    summary = {"failures": failures}
    scenarios = [("kill", _scenario_kill), ("exhaust", _scenario_exhaust),
                 ("kv", _scenario_kv), ("overload", _scenario_overload),
                 ("hotroll", _scenario_hotroll)]
    for name, fn in scenarios:
        print("=== scenario: %s ===" % name)
        try:
            summary[name] = fn(args, check)
        except Exception as e:  # noqa: BLE001 - verdict, not traceback
            check(False, "%s: scenario crashed: %s: %s"
                  % (name, type(e).__name__, e))

    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
