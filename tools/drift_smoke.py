"""Train/serve drift end-to-end smoke (tier1 CI).

Exercises the whole model-observability pipe from docs/Observability.md
("Model statistics & drift") the way an operator would hit it:

1. train a small model with ``obs_modelstats`` on and a checkpoint
   directory — the training data profile is captured at Dataset
   construction and persisted into the snapshot's ``meta.json``;
2. hot-roll the snapshot into a ServingEngine via ``watch_dir`` (the
   staged bundle recovers the profile from the sibling meta file) and
   bind the serving HTTP front-end;
3. serve same-distribution traffic and assert the drift status stays
   ``ok``, then serve SHIFTED traffic and assert, within a bounded
   number of batches:
   - the ``lgbm_drift_psi`` / ``lgbm_drift_psi_max`` gauges cross the
     warn threshold (scraped over ``/metrics/prometheus``),
   - ``/healthz`` reports ``drift: warn`` while staying HTTP 200 (drift
     is advisory — it must never shed traffic),
   - ``/drift`` carries the per-feature PSI detail,
   - the ``on_drift`` refit hook fired exactly once (edge-triggered);
4. verify the training-side surfaces: ``feature_importance`` parity
   against the streamed accumulator and the ``lgbm_model_*`` gauges.

Exit code 0 = every assertion holds. The summary JSON goes to ``--out``
(and stdout) for the CI artifact.
"""
import argparse
import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # repo root for lightgbm_tpu


def _get(base: str, path: str) -> bytes:
    with urllib.request.urlopen(base + path, timeout=10) as r:
        return r.read()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="drift_smoke_out",
                    help="checkpoints land here")
    ap.add_argument("--out", default="", help="write the summary JSON here")
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--warn-psi", type=float, default=0.25)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    ckpt_dir = os.path.join(args.workdir, "ckpt")

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu import callback, engine
    from lightgbm_tpu.serving.predictor import ServingEngine
    from lightgbm_tpu.serving.registry import ModelRegistry
    from lightgbm_tpu.serving.server import ServingApp, make_server

    failures = []

    def check(cond, msg):
        (failures.append(msg) if not cond else None)
        print("%s %s" % ("ok  " if cond else "FAIL", msg))

    # ---- 1. train with modelstats + checkpointing ----------------------
    r = np.random.RandomState(0)
    n, f = 2000, 8
    X = r.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.2 * r.randn(n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "tree_growth": "frontier", "obs_modelstats": True,
              "obs_drift_warn_psi": args.warn_psi}
    bst = engine.train(params, lgb.Dataset(X, label=y),
                       num_boost_round=args.iters,
                       callbacks=[callback.checkpoint(ckpt_dir, period=1)])

    ms = bst._impl._modelstats
    check(ms is not None and ms.trees == args.iters,
          "modelstats tracked %d trees" % args.iters)
    imp_stream = ms.importance("split")
    imp_host = bst.feature_importance("split").astype(np.float64)
    check(np.array_equal(imp_stream, imp_host),
          "streaming split importance == host recomputation")
    check(np.allclose(ms.importance("gain"), bst.feature_importance("gain"),
                      rtol=1e-3, atol=1e-2),
          "streaming gain importance ~ host recomputation")

    # ---- 2. hot-roll the snapshot into a serving engine ----------------
    reg = ModelRegistry()
    eng = ServingEngine(registry=reg, min_bucket=16, max_batch=128,
                        drift_warn_psi=args.warn_psi, drift_min_rows=128)
    watcher = reg.watch_dir("m", ckpt_dir, engine=eng)   # arms drift hook
    check(watcher.poll() is True, "snapshot hot-rolled into the registry")
    bundle = reg.get("m")
    check(bundle.profile is not None and len(bundle.profile) == f,
          "staged bundle recovered the %d-feature training profile" % f)

    refits = []
    eng.add_drift_hook(refits.append)

    app = ServingApp(eng)
    server = make_server(app, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = "http://127.0.0.1:%d" % server.server_address[1]
    summary = {}
    try:
        # ---- 3a. same-distribution traffic stays ok --------------------
        for _ in range(4):
            eng.predict("m", r.randn(64, f).astype(np.float32))
        hz = json.loads(_get(base, "/healthz"))
        check(hz.get("drift") == "ok",
              "/healthz drift=ok on same-distribution traffic (got %r)"
              % hz.get("drift"))

        # ---- 3b. shifted traffic warns within bounded batches ----------
        batches = 0
        for batches in range(1, 13):
            eng.predict("m",
                        (r.randn(64, f) * 3 + 6).astype(np.float32))
            if eng.drift_status()["status"] == "warn":
                break
        check(eng.drift_status()["status"] == "warn",
              "drift warn within %d shifted batches" % batches)

        hz = json.loads(_get(base, "/healthz"))
        check(hz.get("drift") == "warn", "/healthz reports drift: warn")
        check(hz.get("status") == "ok",
              "drift is advisory: /healthz stays HTTP-200 ok")

        drift = json.loads(_get(base, "/drift"))
        mstat = drift.get("models", {}).get("m", {})
        check(drift.get("status") == "warn" and
              mstat.get("max_psi", 0) >= args.warn_psi,
              "/drift carries max_psi >= %.2f" % args.warn_psi)
        check(any(v.get("psi", 0) >= args.warn_psi
                  for v in mstat.get("features", {}).values()),
              "/drift carries per-feature PSI detail")

        prom = _get(base, "/metrics/prometheus").decode()
        psi_lines = [l for l in prom.splitlines()
                     if l.startswith("lgbm_drift_psi_max{")]
        check(psi_lines and max(float(l.rsplit(" ", 1)[1])
                                for l in psi_lines) >= args.warn_psi,
              "lgbm_drift_psi_max gauge crossed the threshold")
        check("lgbm_drift_psi{" in prom,
              "per-feature lgbm_drift_psi gauges exported")
        check("lgbm_model_trees" in prom,
              "training-side lgbm_model_* gauges share the registry")
        check(len(refits) == 1,
              "on_drift refit hook fired exactly once (got %d)"
              % len(refits))
        check("lgbm_drift_reports_total 1" in prom,
              "drift report routed through the health monitor")

        summary = {"iterations": args.iters,
                   "shifted_batches_to_warn": batches,
                   "max_psi": mstat.get("max_psi"),
                   "healthz": hz,
                   "refit_hook_fires": len(refits),
                   "split_importance": [int(v) for v in imp_host]}
    finally:
        server.shutdown()
        server.server_close()
        app.close()

    summary["failures"] = failures
    blob = json.dumps(summary, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(blob + "\n")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
