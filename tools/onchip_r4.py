"""Round-4 on-chip measurement protocol (VERDICT r3 #1) in ONE command.

The axon TPU tunnel has died mid-round in every previous round, so this
runs the full measurement list as independent subprocess steps with hard
timeouts and APPENDS each result to ``tools/onchip_r4_results.json`` as
soon as it lands — a tunnel death halfway through still leaves every
completed measurement on disk.

    python tools/onchip_r4.py [--quick]

Steps (each skippable by prior completion, rerun with --redo):
  probe          backend probe (device kind, cheap matmul)
  kernel_parity  slot kernel + hist_tile_vals vs scatter ON HARDWARE
  bench_default  bench.py as the driver runs it (batched growth)
  bench_exact    BENCH_TREE_GROWTH=exact comparison point
  bench_k{4,8,16,32}  batched-growth K sweep
  bench_pack     tpu_batched_pack=true at the best K so far
  full_shape     HIGGS-shaped 10.5M x 28 iters/s (batched + exact)
  stress         Expo/Allstate shapes (tools/stress_shapes.py)
  multiclass     vmap-vs-sequential class batching timing
"""
import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
OUT = os.path.join(HERE, "onchip_r4_results.json")


def load():
    if os.path.exists(OUT):
        with open(OUT) as f:
            return json.load(f)
    return {}


def save(results):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
    os.replace(tmp, OUT)


def run_step(name, code_or_cmd, results, timeout, env=None, redo=False):
    if name in results and not redo and results[name].get("ok"):
        print("[skip] %s (already recorded)" % name, flush=True)
        return True
    print("[run ] %s (timeout %ds)" % (name, timeout), flush=True)
    t0 = time.time()
    cmd = code_or_cmd if isinstance(code_or_cmd, list) \
        else [sys.executable, "-c", code_or_cmd]
    full_env = dict(os.environ, **(env or {}))
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=full_env)
        rec = {"ok": r.returncode == 0, "seconds": round(time.time() - t0, 1)}
        # steps print one JSON payload line: either prefixed RESULT:
        # (the inline steps) or a bare {...} line (bench.py)
        for line in (r.stdout or "").splitlines():
            if line.startswith("RESULT:"):
                rec["data"] = json.loads(line[len("RESULT:"):])
            elif line.startswith("{") and line.rstrip().endswith("}"):
                try:
                    rec["data"] = json.loads(line)
                except ValueError:
                    pass
        if r.returncode != 0:
            rec["error"] = (r.stderr or r.stdout or "")[-800:]
    except subprocess.TimeoutExpired:
        rec = {"ok": False, "seconds": round(time.time() - t0, 1),
               "error": "timeout after %ds" % timeout}
    results[name] = rec
    save(results)
    print("[%s] %s %s" % ("ok  " if rec["ok"] else "FAIL", name,
                          rec.get("data", rec.get("error", ""))), flush=True)
    return rec["ok"]


PROBE = r"""
import json, time
t0 = time.time()
import jax, jax.numpy as jnp
d = jax.devices()
x = jnp.ones((4096, 4096), jnp.bfloat16)
t1 = time.time(); y = (x @ x).block_until_ready(); t2 = time.time()
for _ in range(3):
    y = (x @ x).block_until_ready()
t3 = time.time()
print("RESULT:" + json.dumps({
    "platform": d[0].platform, "kind": str(getattr(d[0], "device_kind", "?")),
    "n_devices": len(d), "init_s": round(t1 - t0, 1),
    "matmul_tflops": round(3 * 2 * 4096**3 / max(t3 - t2, 1e-9) / 1e12, 1)}))
"""

KERNEL_PARITY = r"""
import json
import numpy as np
import jax.numpy as jnp
from lightgbm_tpu.core.histogram import build_histogram, hist_tile_vals
from lightgbm_tpu.core.histogram_pallas import build_histogram_slots
r = np.random.RandomState(7)
n, f, b, s = 65536, 28, 256, 8
xb = r.randint(0, b, (n, f)).astype(np.uint8)
g = r.randn(n).astype(np.float32)
h = np.abs(r.randn(n)).astype(np.float32)
m = (r.rand(n) > 0.3).astype(np.float32)
slot = r.randint(0, s, (n,)).astype(np.int32)
out = {}
ref = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                 jnp.asarray(h), jnp.asarray(m),
                                 num_bins=b, impl="scatter"))
pal = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                 jnp.asarray(h), jnp.asarray(m),
                                 num_bins=b, impl="pallas"))
out["pallas_vs_scatter_max"] = float(np.abs(pal - ref).max())
hi = np.asarray(build_histogram(jnp.asarray(xb), jnp.asarray(g),
                                jnp.asarray(h), jnp.asarray(m),
                                num_bins=b, impl="pallas_highest"))
out["pallas_highest_vs_scatter_max"] = float(np.abs(hi - ref).max())
# 6-channel tile (the fused partition path shape)
v6 = r.randn(4096, 6).astype(np.float32)
ref6 = np.asarray(hist_tile_vals(jnp.asarray(xb[:4096]), jnp.asarray(v6),
                                 b, "scatter"))
p6 = np.asarray(hist_tile_vals(jnp.asarray(xb[:4096]), jnp.asarray(v6),
                               b, "pallas"))
out["tile6_vs_scatter_max"] = float(np.abs(p6 - ref6).max())
# slot kernel (batched growth): per-slot scatter reference
vals = np.stack([g * m, h * m, m])           # [3, N] channels
sl = np.asarray(build_histogram_slots(jnp.asarray(xb), jnp.asarray(slot),
                                      jnp.asarray(vals), num_bins=b,
                                      n_slots=s))      # [s, F, B, 3]
refs = np.stack([np.asarray(build_histogram(
    jnp.asarray(xb), jnp.asarray(g), jnp.asarray(h),
    jnp.asarray(m * (slot == i)), num_bins=b, impl="scatter"))
    for i in range(s)])
out["slot_kernel_vs_scatter_max"] = float(np.abs(sl - refs).max())
print("RESULT:" + json.dumps(out))
"""

FULL_SHAPE = r"""
import json, os, time
import numpy as np
import jax
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting
n, f = 10_500_000, 28
r = np.random.RandomState(0)
X = r.randn(n, f).astype(np.float32)
y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float32)
out = {}
for growth in (os.environ.get("FULL_SHAPE_MODES", "batched,exact")
               .split(",")):
    cfg = Config({"objective": "binary", "num_leaves": 255,
                  "verbosity": -1, "tree_growth": growth})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    b.train_one_iter()   # compile + first iter
    jax.block_until_ready(b.scores)
    t0 = time.time()
    iters = 10
    b.train_many(iters)
    jax.block_until_ready(b.scores)
    dt = (time.time() - t0) / iters
    out[growth] = {"s_per_iter": round(dt, 3),
                   "iters_per_sec": round(1.0 / dt, 4)}
print("RESULT:" + json.dumps(out))
"""

MULTICLASS = r"""
import json, time
import numpy as np
import jax
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.boosting import create_boosting
n, f, k = 500_000, 28, 5
r = np.random.RandomState(0)
X = r.randn(n, f).astype(np.float32)
y = (np.abs(X[:, 0] * 2 + r.randn(n)) % k).astype(int).astype(np.float32)
out = {}
for slots, name in ((0, "vmap"), (4, "sequential_capped")):
    cfg = Config({"objective": "multiclass", "num_class": k,
                  "num_leaves": 63, "verbosity": -1,
                  **({"histogram_pool_size": 1e-4} if slots else {})})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    b = create_boosting(cfg, ds, create_objective(cfg), [])
    b.train_one_iter()
    jax.block_until_ready(b.scores)
    t0 = time.time()
    for _ in range(3):
        b.train_one_iter()
    jax.block_until_ready(b.scores)
    out[name] = {"s_per_iter": round((time.time() - t0) / 3, 3),
                 "vmapped": bool(b.grow_params.vmapped_classes)}
print("RESULT:" + json.dumps(out))
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="probe + kernel parity + default bench only")
    ap.add_argument("--redo", action="store_true",
                    help="rerun steps that already have results")
    args = ap.parse_args()
    results = load()
    redo = args.redo

    if not run_step("probe", PROBE, results, timeout=360, redo=redo):
        print("backend unreachable — stopping (results preserved)")
        return 1
    run_step("kernel_parity", KERNEL_PARITY, results, timeout=600,
             redo=redo)

    bench_env = {"BENCH_BACKEND_TRIES": "1", "BENCH_BACKEND_TIMEOUT": "240"}
    run_step("bench_default", [sys.executable, "bench.py"], results,
             timeout=1800, env=bench_env, redo=redo)
    if args.quick:
        return 0
    run_step("bench_exact", [sys.executable, "bench.py"], results,
             timeout=1800, env=dict(bench_env, BENCH_TREE_GROWTH="exact"),
             redo=redo)
    for k in (4, 8, 16, 32):
        run_step("bench_k%d" % k, [sys.executable, "bench.py"], results,
                 timeout=1800,
                 env=dict(bench_env, BENCH_BATCH_SPLITS=str(k)), redo=redo)
    # best K so far, with the packed tile-skip variant
    best_k, best_v = 16, -1.0
    for k in (4, 8, 16, 32):
        d = results.get("bench_k%d" % k, {}).get("data") or {}
        if d.get("value", -1) > best_v:
            best_k, best_v = k, d["value"]
    run_step("bench_pack", [sys.executable, "bench.py"], results,
             timeout=1800,
             env=dict(bench_env, BENCH_BATCH_SPLITS=str(best_k),
                      BENCH_EXTRA_PARAMS="tpu_batched_pack=true"),
             redo=redo)
    run_step("full_shape", FULL_SHAPE, results, timeout=3600, redo=redo)
    run_step("stress", [sys.executable, "tools/stress_shapes.py"], results,
             timeout=3600, redo=redo)
    run_step("multiclass", MULTICLASS, results, timeout=1800, redo=redo)
    print("\nall recorded in", OUT)
    return 0


if __name__ == "__main__":
    sys.exit(main())
