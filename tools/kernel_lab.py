"""On-chip histogram-kernel experiments (round 4).

Findings this script established (see docs/Performance.md):
- the per-feature digit kernel is BANDWIDTH-bound when fed feature-major
  input directly (~0.2-0.5 ms per 1M x 28 x 256 pass) — the 29 ms
  production number was the un-hoisted [N, F] -> [F, N] uint8 transpose
  plus dispatch, not the matmuls;
- a data-dependent (scalar-prefetch) OUTPUT BlockSpec index defeats the
  output pipeliner (per-cell fetch+writeback, ~14 ms per pass); keeping
  the whole per-slot accumulator as ONE constant-index block restores
  full speed;
- the joint slot one-hot's S-factor is real MXU work: measured cost vs
  n_slots quantifies what tile-pure partitioning saves.

Usage: python tools/kernel_lab.py
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


N, F, B = 1_048_576, 28, 256
FP = 32
HI = 16


def timed(run, args_list, n_iter=20):
    out = run(*args_list[0])
    jax.block_until_ready(out)
    t0 = time.time()
    for i in range(n_iter):
        out = run(*args_list[i % len(args_list)])
    jax.block_until_ready(out)
    return (time.time() - t0) / n_iter * 1000, out


def v0_kernel(xb_ref, vals_ref, out_ref):
    r = pl.program_id(1)
    xb = xb_ref[...].astype(jnp.int32)
    vals = vals_ref[...]
    ft, c = xb.shape
    k = vals.shape[0]

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HI, c), 0)
    for j in range(ft):
        x = xb[j:j + 1, :]
        hi_eq = iota_hi == (x >> 4)
        lo_eq = iota_lo == (x & 15)
        a = jnp.where(hi_eq[None], vals[:, None, :], 0.0).reshape(k * HI, c)
        a_top = a.astype(jnp.bfloat16)
        a_rem = (a - a_top.astype(jnp.float32)).astype(jnp.bfloat16)
        eqlo = jnp.where(lo_eq, 1.0, 0.0).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            a_top, eqlo, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        part += jax.lax.dot_general(
            a_rem, eqlo, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[:, j, :, :] += part.reshape(k, HI, 16)


def mk_v0(row_tile, feature_tile=8, k=3):
    @jax.jit
    def run(xb_t, vals):
        return pl.pallas_call(
            v0_kernel,
            grid=(FP // feature_tile, N // row_tile),
            in_specs=[
                pl.BlockSpec((feature_tile, row_tile), lambda i, r: (i, r)),
                pl.BlockSpec((k, row_tile), lambda i, r: (0, r)),
            ],
            out_specs=pl.BlockSpec((k, feature_tile, HI, 16),
                                   lambda i, r: (0, i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((k, FP, HI, 16), jnp.float32),
        )(xb_t, vals)
    return run


def slot_scratch_kernel(tile_slot_ref, xb_ref, sel_ref, vals_ref, out_ref,
                        *, n_slots):
    """Partitioned-tile kernel, VMEM-resident accumulator: out is ONE
    constant-index block [S, 6, ft, Hi, 16]; the prefetched tile slot
    only selects the accumulator SLICE (dynamic leading index), so the
    output pipeliner sees a resident block for the whole row sweep."""
    r = pl.program_id(1)
    slot = tile_slot_ref[r]

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(slot >= 0)
    def _body():
        xb = xb_ref[...].astype(jnp.int32)
        sel = sel_ref[...]
        v3 = vals_ref[...]
        ft, c = xb.shape
        v6 = jnp.concatenate([v3 * sel, v3 * (1.0 - sel)], axis=0)
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HI, c), 0)
        for j in range(ft):
            x = xb[j:j + 1, :]
            hi_eq = iota_hi == (x >> 4)
            lo_eq = iota_lo == (x & 15)
            a = jnp.where(hi_eq[None], v6[:, None, :], 0.0) \
                .reshape(6 * HI, c)
            a_top = a.astype(jnp.bfloat16)
            a_rem = (a - a_top.astype(jnp.float32)).astype(jnp.bfloat16)
            eqlo = jnp.where(lo_eq, 1.0, 0.0).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                a_top, eqlo, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            part += jax.lax.dot_general(
                a_rem, eqlo, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            out_ref[slot, :, j, :, :] += part.reshape(6, HI, 16)


def mk_slot_scratch(n_slots, row_tile=2048, feature_tile=8):
    from jax.experimental.pallas import tpu as pltpu
    kernel = functools.partial(slot_scratch_kernel, n_slots=n_slots)

    @jax.jit
    def run(xb_t, sel, vals, tile_slot):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(FP // feature_tile, N // row_tile),
            in_specs=[
                pl.BlockSpec((feature_tile, row_tile),
                             lambda i, r, *_: (i, r)),
                pl.BlockSpec((1, row_tile), lambda i, r, *_: (0, r)),
                pl.BlockSpec((3, row_tile), lambda i, r, *_: (0, r)),
            ],
            out_specs=pl.BlockSpec(
                (n_slots, 6, feature_tile, HI, 16),
                lambda i, r, *_: (0, 0, i, 0, 0)),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n_slots, 6, FP, HI, 16),
                                           jnp.float32),
        )(tile_slot.astype(jnp.int32), xb_t, sel[None, :], vals)
    return run


def joint_kernel(xb_ref, slot_ref, vals_ref, out_ref, *, n_slots):
    """Existing joint (slot x lo) design: RHS width n_slots*16."""
    r = pl.program_id(1)
    slot = slot_ref[...].astype(jnp.int32)
    vals = vals_ref[...]
    k = vals.shape[0]
    xb = xb_ref[...].astype(jnp.int32)
    ft, c = xb.shape

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HI, c), 0)
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (n_slots, c), 0)
    s_eq = iota_s == slot
    for j in range(ft):
        x = xb[j:j + 1, :]
        hi_eq = iota_hi == (x >> 4)
        lo_eq = iota_lo == (x & 15)
        a = jnp.where(hi_eq[None], vals[:, None, :], 0.0).reshape(k * HI, c)
        eqj = jnp.where(s_eq[:, None, :] & lo_eq[None], 1.0, 0.0) \
            .reshape(n_slots * 16, c)
        a_top = a.astype(jnp.bfloat16)
        a_rem = (a - a_top.astype(jnp.float32)).astype(jnp.bfloat16)
        eqb = eqj.astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            a_top, eqb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        part += jax.lax.dot_general(
            a_rem, eqb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[:, j, :, :] += part.reshape(k, HI, n_slots * 16)


def mk_joint(n_slots, row_tile=2048, feature_tile=8):
    kernel = functools.partial(joint_kernel, n_slots=n_slots)

    @jax.jit
    def run(xb_t, slot, vals):
        return pl.pallas_call(
            kernel,
            grid=(FP // feature_tile, N // row_tile),
            in_specs=[
                pl.BlockSpec((feature_tile, row_tile), lambda i, r: (i, r)),
                pl.BlockSpec((1, row_tile), lambda i, r: (0, r)),
                pl.BlockSpec((3, row_tile), lambda i, r: (0, r)),
            ],
            out_specs=pl.BlockSpec((3, feature_tile, HI, n_slots * 16),
                                   lambda i, r: (0, i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((3, FP, HI, n_slots * 16),
                                           jnp.float32),
        )(xb_t, slot[None, :], vals)
    return run


def pertile_kernel(act_ref, xb_ref, sel_ref, vals_ref, out_ref):
    """Per-TILE histogram output, STATIC index maps only: cell (i, r)
    writes its tile's [6, ft, Hi, 16] block to out[r]; the caller
    reduces tiles -> slots with one [S, T] one-hot matmul (inactive
    tiles carry one-hot weight 0). act_ref gates compute: inactive
    tiles just zero their block (garbage x 0 would still poison via
    NaN, so the zero matters)."""
    r = pl.program_id(1)
    act = act_ref[r]

    @pl.when(act == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(act != 0)
    def _body():
        xb = xb_ref[...].astype(jnp.int32)
        sel = sel_ref[...]
        v3 = vals_ref[...]
        ft, c = xb.shape
        v6 = jnp.concatenate([v3 * sel, v3 * (1.0 - sel)], axis=0)
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (16, c), 0)
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (HI, c), 0)
        for j in range(ft):
            x = xb[j:j + 1, :]
            hi_eq = iota_hi == (x >> 4)
            lo_eq = iota_lo == (x & 15)
            a = jnp.where(hi_eq[None], v6[:, None, :], 0.0) \
                .reshape(6 * HI, c)
            a_top = a.astype(jnp.bfloat16)
            a_rem = (a - a_top.astype(jnp.float32)).astype(jnp.bfloat16)
            eqlo = jnp.where(lo_eq, 1.0, 0.0).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                a_top, eqlo, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            part += jax.lax.dot_general(
                a_rem, eqlo, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            out_ref[0, :, j, :, :] = part.reshape(6, HI, 16)


def mk_pertile(n_slots, row_tile=2048, feature_tile=8):
    from jax.experimental.pallas import tpu as pltpu
    t = N // row_tile

    @jax.jit
    def run(xb_t, sel, vals, tile_slot):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(FP // feature_tile, t),
            in_specs=[
                pl.BlockSpec((feature_tile, row_tile),
                             lambda i, r, *_: (i, r)),
                pl.BlockSpec((1, row_tile), lambda i, r, *_: (0, r)),
                pl.BlockSpec((3, row_tile), lambda i, r, *_: (0, r)),
            ],
            out_specs=pl.BlockSpec(
                (1, 6, feature_tile, HI, 16),
                lambda i, r, *_: (r, 0, i, 0, 0)),
        )
        act = (tile_slot >= 0).astype(jnp.int32)
        tiles = pl.pallas_call(
            pertile_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((t, 6, FP, HI, 16),
                                           jnp.float32),
        )(act, xb_t, sel[None, :], vals)
        seg = (tile_slot[None, :]
               == jnp.arange(n_slots, dtype=jnp.int32)[:, None]) \
            .astype(jnp.float32)                        # [S, T]
        return jnp.einsum("st,tcfhl->scfhl", seg, tiles)
    return run


def main():
    r = np.random.RandomState(0)
    xb_np = r.randint(0, B, (F, N)).astype(np.uint8)
    xb_t = jnp.asarray(np.concatenate(
        [xb_np, np.zeros((FP - F, N), np.uint8)], axis=0))
    xb_rm = jnp.asarray(np.ascontiguousarray(xb_np.T))   # [N, F] row-major
    vals_sets = [jnp.asarray(r.randn(3, N).astype(np.float32))
                 for _ in range(4)]
    sel = jnp.asarray((r.rand(N) > 0.5).astype(np.float32))

    # 0) methodology guard: exact numpy reference for the LAST input set
    run = mk_v0(2048)
    ms, out = timed(run, [(xb_t, v) for v in vals_sets])
    ref = np.zeros((3, F, B), np.float32)
    v_last = np.asarray(vals_sets[(20 - 1) % 4])
    for ch in range(3):
        for f in range(F):
            np.add.at(ref[ch, f], xb_np[f], v_last[ch])
    got = np.asarray(out).reshape(3, FP, B)[:, :F]
    print("v0 rt=2048 (varied inputs)   : %6.2f ms  err=%.1e"
          % (ms, np.abs(got - ref).max()), flush=True)

    # 1) transpose cost (what build_histogram pays when not hoisted)
    tr = jax.jit(lambda x: jnp.pad(x.T, ((0, FP - F), (0, 0))))
    ms, _ = timed(tr, [(xb_rm,)])
    print("uint8 [N,F]->[F,N] transpose : %6.2f ms" % ms, flush=True)

    # 2) per-tile + segment-matmul (partition-pure tiles, static index)
    for s, frac in ((16, 2), (16, 1), (32, 1)):
        ts = np.full(N // 2048, -1, np.int32)
        nact = N // (2048 * frac)
        ts[:nact] = np.arange(nact) % s
        args = [(xb_t, sel, v, jnp.asarray(ts)) for v in vals_sets]
        try:
            ms, out = timed(mk_pertile(s), args)
            # spot parity on slot 0 of the last set
            sel_np = np.asarray(sel)
            refs = np.zeros((F, B, 6), np.float32)
            rows = np.concatenate([np.arange(t * 2048, (t + 1) * 2048)
                                   for t in range(nact)
                                   if ts[t] == 0])
            for ch in range(6):
                w = sel_np[rows] if ch < 3 else 1 - sel_np[rows]
                v = v_last[ch % 3, rows] * w
                for f in range(F):
                    np.add.at(refs[f, :, ch], xb_np[f, rows], v)
            got = np.transpose(np.asarray(out[0]).reshape(6, FP, B),
                               (1, 2, 0))[:F]
            print("per-tile S=%-3d 1/%d active  : %6.2f ms  err=%.1e"
                  % (s, frac, ms, np.abs(got - refs).max()), flush=True)
        except Exception as e:  # noqa: BLE001
            print("per-tile S=%-3d 1/%d active  : FAIL %s"
                  % (s, frac, repr(e)[:150]), flush=True)

    # 3) joint slot kernel (existing design) vs S
    slot_ids = jnp.asarray(r.randint(0, 32, (N,)).astype(np.int32))
    for s in (8, 16, 32):
        sl = jnp.minimum(slot_ids, s - 1)
        try:
            ms, _ = timed(mk_joint(s), [(xb_t, sl, v) for v in vals_sets])
            print("joint slots S=%-3d full-N    : %6.2f ms" % (s, ms),
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print("joint slots S=%-3d full-N    : FAIL %s"
                  % (s, repr(e)[:150]), flush=True)


if __name__ == "__main__":
    main()


# ---------------------------------------------------------------------------
# Round-4 feasibility probe for the partition-step mega-kernel (north-star
# section of docs/Performance.md): IN-TILE stable partition as a
# permutation one-hot matmul — EXACT for byte payloads (each output row is
# one one-hot row of P times integer values <= 255; a single nonzero
# product per output element, so no accumulation error), with the prefix
# sum done as a lower-triangular f32 matvec (Mosaic has no cumsum).
#
# Measured on a v5e chip: ~8.8 ms per 1M x 128-byte-payload pass at
# row_tile 256/512 (per-tile-overhead bound — the skinny [1, t] prefix
# matvec and per-tile setup dominate, not the P @ data matmul), exact
# output, per-tile left-counts delivered in an i32 side output.
#
# Mosaic lowering gotchas hit on the way (all worked around below):
#   - uint8 -> bfloat16 casts unsupported (go via int32);
#   - jnp.cumsum unsupported (triangular matmul instead);
#   - f32 iota unsupported (int iota + cast);
#   - scalar extraction like cl[-1] lowers to dynamic_slice (unsupported)
#     — keep everything 2D and use keepdims reductions.
def partition_tile_kernel(xb_ref, gl_ref, out_ref, cnt_ref):
    xb = xb_ref[...].astype(jnp.int32).astype(jnp.bfloat16)   # [t, C]
    gl2 = gl_ref[...]                                         # [1, t] f32
    t = xb.shape[0]
    iota0 = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    iota1 = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    ut = jnp.where(iota1 <= iota0, 1.0, 0.0)
    cl2 = jax.lax.dot_general(gl2, ut, (((1,), (1,)), ((), ())),
                              precision=jax.lax.Precision.HIGHEST,
                              preferred_element_type=jnp.float32)  # [1, t]
    nl2 = jnp.sum(gl2, axis=1, keepdims=True)
    ii = jax.lax.broadcasted_iota(jnp.int32, (1, t), 1).astype(jnp.float32)
    pos2 = jnp.where(gl2 > 0, cl2 - 1.0, nl2 + (ii + 1.0) - cl2 - 1.0)
    perm = jnp.where(iota0 == pos2.astype(jnp.int32), 1.0, 0.0) \
        .astype(jnp.bfloat16)
    out = jax.lax.dot_general(perm, xb, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    out_ref[...] = out.astype(jnp.int32).astype(jnp.uint8)
    cnt_ref[...] = jnp.broadcast_to(nl2, cnt_ref.shape).astype(jnp.int32)


def mk_partition_tiles(n, c, row_tile):
    @jax.jit
    def run(xb, gl):
        return pl.pallas_call(
            partition_tile_kernel,
            grid=(n // row_tile,),
            in_specs=[pl.BlockSpec((row_tile, c), lambda r: (r, 0)),
                      pl.BlockSpec((1, row_tile), lambda r: (0, r))],
            out_specs=[pl.BlockSpec((row_tile, c), lambda r: (r, 0)),
                       pl.BlockSpec((8, 128), lambda r: (r, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, c), jnp.uint8),
                       jax.ShapeDtypeStruct((n // row_tile * 8, 128),
                                            jnp.int32)],
        )(xb, gl)
    return run
