"""Serving smoke: warm every bucket, fire randomized traffic, assert ZERO
recompiles — the lightgbm_tpu.serving acceptance gate.

Boots a ServingEngine (plus, unless --no-http, the real HTTP server on an
OS-assigned port to prove the transport path), trains or loads a model,
warms every batch bucket, then fires N requests of uniform-random size in
[1, max_batch] and asserts:

- zero predictor-cache misses after warmup;
- zero XLA backend compilations after warmup, observed by the
  jax.monitoring compilation-count hook (serving/metrics.py) — this is
  the strict signal: it also catches retraces the cache key cannot see;
- every served output matches Booster.predict to 1e-6 (checked on a
  sample of requests; refs are computed BEFORE warmup so the reference
  path's own compilations do not pollute the post-warmup count).

Prints ONE JSON line with the verdict + the metrics snapshot. Exit 0 on
pass, 1 on any violated assertion.

Usage:
  python tools/serve_smoke.py [--requests 1000] [--max-batch 4096]
                              [--model path.txt] [--devices 1] [--no-http]
CPU-friendly: JAX_PLATFORMS=cpu python tools/serve_smoke.py --requests 100
"""
import argparse
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))   # repo root for lightgbm_tpu


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=1000)
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--min-bucket", type=int, default=16)
    ap.add_argument("--model", default="", help="model-text file; default "
                    "trains a small binary model in-process")
    ap.add_argument("--devices", type=int, default=1,
                    help="serving devices (0 = all local)")
    ap.add_argument("--parity-sample", type=int, default=25,
                    help="requests checked against Booster.predict")
    ap.add_argument("--no-http", action="store_true",
                    help="skip the HTTP round-trip leg")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import (MicroBatchQueue, ServingEngine,
                                      ServingApp, bucket_sizes,
                                      install_compile_hook, make_server)

    install_compile_hook()   # before any compilation we intend to count
    rng = np.random.RandomState(args.seed)

    if args.model:
        bst = lgb.Booster(model_file=args.model)
    else:
        Xtr = rng.rand(4000, 10).astype(np.float32)
        ytr = ((Xtr[:, 0] + Xtr[:, 1] * Xtr[:, 2]) > 0.6).astype(np.float32)
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbosity": -1},
                        lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
    nf = bst.num_feature()

    engine = ServingEngine(max_batch=args.max_batch,
                           min_bucket=args.min_bucket,
                           num_devices=args.devices)
    engine.registry.register(bst.as_serving_bundle("smoke"))

    # request sizes span the full ladder; refs BEFORE warmup (see module
    # docstring for why)
    sizes = rng.randint(1, engine.max_batch + 1,
                        size=args.requests).astype(int)
    parity_idx = set(
        rng.choice(args.requests, min(args.parity_sample, args.requests),
                   replace=False).tolist())
    parity_refs = {}
    parity_queries = {}
    for i in sorted(parity_idx):
        X = rng.rand(int(sizes[i]), nf).astype(np.float32)
        parity_queries[i] = X
        parity_refs[i] = bst.predict(X)

    t0 = time.time()
    warmed = engine.warmup()
    t_warm = time.time() - t0

    queue = MicroBatchQueue(engine, deadline_ms=1.0).start()
    app = ServingApp(engine, queue)
    server = httport = None
    if not args.no_http:
        server = make_server(app, "127.0.0.1", 0)
        httport = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()

    failures = []
    t0 = time.time()
    rows_total = 0
    for i, n in enumerate(sizes):
        n = int(n)
        if i in parity_idx:
            X = parity_queries[i]
        else:
            X = np.zeros((n, nf), np.float32)
            X[0] = rng.rand(nf)           # cheap per-request variety
        rows_total += n
        out = queue.predict("smoke", X)
        if i in parity_idx:
            err = float(np.max(np.abs(out - parity_refs[i])))
            if not err <= 1e-6:
                failures.append("parity: request %d (%d rows) maxdiff %.3g"
                                % (i, n, err))
    t_fire = time.time() - t0

    if server is not None:
        body = json.dumps({"data": parity_queries[min(parity_idx)].tolist(),
                           "model": "smoke"}).encode()
        rep = json.loads(urllib.request.urlopen(urllib.request.Request(
            "http://127.0.0.1:%d/predict" % httport, data=body)).read())
        err = float(np.max(np.abs(np.asarray(rep["predictions"])
                                  - parity_refs[min(parity_idx)])))
        if not err <= 1e-6:
            failures.append("http parity maxdiff %.3g" % err)
        server.shutdown()
        server.server_close()
    app.close()

    misses = engine.metrics.cache_misses_after_warmup()
    recompiles = engine.metrics.recompiles_after_warmup()
    if misses != 0:
        failures.append("%d predictor-cache misses after warmup" % misses)
    if recompiles != 0:
        failures.append("%d XLA backend compiles after warmup" % recompiles)

    snap = engine.metrics.snapshot()
    print(json.dumps({
        "ok": not failures,
        "failures": failures,
        "requests": args.requests,
        "rows": rows_total,
        "buckets_warmed": warmed,
        "bucket_ladder": bucket_sizes(engine.min_bucket, engine.max_batch),
        "cache_misses_after_warmup": misses,
        "recompiles_after_warmup": recompiles,
        "warmup_seconds": round(t_warm, 3),
        "fire_seconds": round(t_fire, 3),
        "predict_rows_per_sec": round(rows_total / max(t_fire, 1e-9), 1),
        "metrics": snap,
    }))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
