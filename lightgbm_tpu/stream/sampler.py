"""Two-round sample-based binning over a ``ChunkSource``.

Round 1 streams the source once, sampling up to
``bin_construct_sample_cnt`` rows; bin mappers and the EFB/packing layout
come from that sample via ``BinnedDataset.from_matrix`` — the exact code
path every in-memory dataset takes, so boundaries match
``from_file_two_round`` bit-for-bit (same RNG stream, same vectorized
Algorithm R: the fill phase keeps original order, which makes
sample == full data whenever ``bin_construct_sample_cnt >= n`` — the
hook the exact-parity tests rely on). Round 2 streams again and
quantizes each chunk host-side against that layout
(``from_matrix(reference=proto)``), keeping the uint8 chunks SEPARATE:
the resulting ``StreamedDataset`` never concatenates them, so peak host
memory is the quantized chunks (~N*C bytes) plus one float chunk, and
device memory is bounded by ``pipeline.ChunkPipeline``'s prefetch depth.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.dataset import BinnedDataset, Metadata
from ..log import Log, LightGBMError
from .source import ChunkSource


class StreamedDataset(BinnedDataset):
    """A ``BinnedDataset`` whose bin matrix lives as host-side chunks.

    Identical layout metadata (mappers, EFB columns, packing) to the
    in-memory class; ``X_binned`` stays ``None`` and ``chunks`` holds the
    ordered uint8 [c_i, C] pieces (sum of c_i == num_data). Everything
    that needs the matrix resident in one piece — ``save_binary``,
    subset construction, replay-based rollback — refuses with a clear
    error instead of silently concatenating.
    """

    is_streamed = True

    def __init__(self):
        super().__init__()
        self.chunks: List[np.ndarray] = []

    @property
    def chunk_row_counts(self) -> List[int]:
        return [int(c.shape[0]) for c in self.chunks]

    def data_profile(self):
        """Per-feature bin-occupancy profile accumulated chunk-by-chunk
        (parity with the single-shot profile is tested)."""
        if self._data_profile is None:
            from ..obs.drift import DataProfile
            self._data_profile = DataProfile.from_binned_chunks(self)
        return self._data_profile

    def save_binary(self, path: str) -> None:
        raise LightGBMError(
            "save_binary is not supported for streamed datasets "
            "(data_stream_chunk_rows > 0): the bin matrix is never "
            "materialized in one piece. Save the raw source instead.")


def _systematic_sample(stride: int):
    """Stateful every-``stride``-th-row picker (deterministic alternative
    to the reservoir for sorted/grouped data where a uniform reservoir
    could still be preferred by seed; used when ``sample_stride`` > 0)."""
    state = {"next": 0, "seen": 0}

    def pick(c: int) -> np.ndarray:
        lo = state["next"] - state["seen"]
        idx = np.arange(max(lo, 0), c, stride, dtype=np.int64) \
            if lo < c else np.empty(0, np.int64)
        if len(idx):
            state["next"] = state["seen"] + int(idx[-1]) + stride
        state["seen"] += c
        return idx

    return pick


def ingest(source: ChunkSource, config,
           feature_names: Optional[List[str]] = None,
           categorical_feature=None,
           sample_stride: int = 0) -> StreamedDataset:
    """Build a ``StreamedDataset`` from a chunk source (two passes).

    ``sample_stride > 0`` switches round 1 from reservoir sampling to
    systematic every-k-th-row sampling (capped at
    ``bin_construct_sample_cnt`` rows, earliest kept).
    """
    sample_cnt = int(config.bin_construct_sample_cnt)
    rng = np.random.RandomState(config.data_random_seed)
    picker = _systematic_sample(int(sample_stride)) if sample_stride > 0 \
        else None

    source.reset()
    sample_rows: list = []
    labels: list = []
    n_total = 0
    n_features = -1
    n_chunks = 0
    for Xc, yc in source:
        Xc = np.asarray(Xc, np.float64)
        if Xc.ndim != 2:
            raise LightGBMError(
                "chunk %d is not 2-D (shape %s)" % (n_chunks, (Xc.shape,)))
        if n_features < 0:
            n_features = Xc.shape[1]
        elif Xc.shape[1] != n_features:
            raise LightGBMError(
                "chunk %d has %d features, expected %d — every chunk of a "
                "streamed source must share one feature space"
                % (n_chunks, Xc.shape[1], n_features))
        if yc is not None:
            labels.append(np.asarray(yc, np.float64).reshape(-1))
        elif labels:
            raise LightGBMError(
                "chunk %d has no label but earlier chunks did" % n_chunks)
        c = Xc.shape[0]
        if picker is not None:
            for i in picker(c):
                if len(sample_rows) < sample_cnt:
                    sample_rows.append(Xc[i].copy())
        else:
            # vectorized Algorithm R, identical to from_file_two_round
            # (io/dataset.py): fill in order, then row i draws
            # j ~ U[0, n_total+i] and replaces slot j when j < sample_cnt
            fill = max(0, min(sample_cnt - n_total, c))
            for i in range(fill):
                sample_rows.append(Xc[i].copy())
            if fill < c:
                draws = (rng.random_sample(c - fill)
                         * (n_total + np.arange(fill, c) + 1)
                         ).astype(np.int64)
                hits = np.nonzero(draws < sample_cnt)[0]
                for i in hits:
                    sample_rows[draws[i]] = Xc[fill + i].copy()
        n_total += c
        n_chunks += 1
    if n_total == 0:
        raise LightGBMError("streamed source yielded no rows")

    names = feature_names or source.feature_names
    proto = BinnedDataset.from_matrix(
        np.asarray(sample_rows), config,
        feature_names=names, categorical_feature=categorical_feature)

    source.reset()
    chunks: List[np.ndarray] = []
    row = 0
    for Xc, _yc in source:
        bc = BinnedDataset.from_matrix(
            np.asarray(Xc, np.float64), config, reference=proto)
        chunks.append(np.ascontiguousarray(bc.X_binned))
        row += Xc.shape[0]
    if row != n_total:
        raise LightGBMError(
            "source is not restartable: round 2 yielded %d rows, round 1 "
            "saw %d — reset() must rewind to the identical chunk stream"
            % (row, n_total))

    sd = StreamedDataset()
    sd.__dict__.update(proto.__dict__)
    sd.X_binned = None
    sd._device_cache = {}
    sd._data_profile = None
    sd.chunks = chunks
    sd.num_data = n_total
    sd.metadata = Metadata(n_total)
    if labels:
        sd.metadata.set_label(np.concatenate(labels))
    Log.info("stream: ingested %d rows in %d chunks (%d stored columns, "
             "sample=%d rows)", n_total, len(chunks),
             chunks[0].shape[1] if chunks else 0, len(sample_rows))
    return sd
