"""Two-round sample-based binning over a ``ChunkSource``.

Round 1 streams the source once, sampling up to
``bin_construct_sample_cnt`` rows; bin mappers and the EFB/packing layout
come from that sample via ``BinnedDataset.from_matrix`` — the exact code
path every in-memory dataset takes, so boundaries match
``from_file_two_round`` bit-for-bit (same RNG stream, same vectorized
Algorithm R: the fill phase keeps original order, which makes
sample == full data whenever ``bin_construct_sample_cnt >= n`` — the
hook the exact-parity tests rely on). Round 2 streams again and
quantizes each chunk host-side against that layout
(``from_matrix(reference=proto)``), keeping the uint8 chunks SEPARATE:
the resulting ``StreamedDataset`` never concatenates them, so peak host
memory is the quantized chunks (~N*C bytes) plus one float chunk, and
device memory is bounded by ``pipeline.ChunkPipeline``'s prefetch depth.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.dataset import BinnedDataset, Metadata
from ..log import Log, LightGBMError
from .source import ChunkSource


class StreamedDataset(BinnedDataset):
    """A ``BinnedDataset`` whose bin matrix lives as host-side chunks.

    Identical layout metadata (mappers, EFB columns, packing) to the
    in-memory class; ``X_binned`` stays ``None`` and ``chunks`` holds the
    ordered uint8 [c_i, C] pieces (sum of c_i == num_data). Everything
    that needs the matrix resident in one piece — ``save_binary``,
    subset construction, replay-based rollback — refuses with a clear
    error instead of silently concatenating.
    """

    is_streamed = True

    def __init__(self):
        super().__init__()
        self.chunks: List[np.ndarray] = []
        # chunks x chips (sharded ingest): this process holds only its
        # rank's contiguous row block; num_data / metadata stay GLOBAL.
        # shard_row_counts lists every rank's row count in rank order and
        # shard_comm is the host allgather used for the cross-rank drift
        # profile and the checkpoint fingerprint (both collective calls).
        self.shard_rank = 0
        self.shard_world = 1
        self.shard_row_counts: Optional[List[int]] = None
        self.shard_comm = None

    @property
    def chunk_row_counts(self) -> List[int]:
        return [int(c.shape[0]) for c in self.chunks]

    @property
    def shard_num_data(self) -> int:
        """Rows resident on THIS rank (== num_data when unsharded)."""
        if self.shard_row_counts is not None:
            return int(self.shard_row_counts[self.shard_rank])
        return int(self.num_data)

    def data_profile(self):
        """Per-feature bin-occupancy profile accumulated chunk-by-chunk
        (parity with the single-shot profile is tested)."""
        if self._data_profile is None:
            from ..obs.drift import DataProfile
            self._data_profile = DataProfile.from_binned_chunks(self)
        return self._data_profile

    def save_binary(self, path: str) -> None:
        raise LightGBMError(
            "save_binary is not supported for streamed datasets "
            "(data_stream_chunk_rows > 0): the bin matrix is never "
            "materialized in one piece. Save the raw source instead.")


def _systematic_sample(stride: int):
    """Stateful every-``stride``-th-row picker (deterministic alternative
    to the reservoir for sorted/grouped data where a uniform reservoir
    could still be preferred by seed; used when ``sample_stride`` > 0)."""
    state = {"next": 0, "seen": 0}

    def pick(c: int) -> np.ndarray:
        lo = state["next"] - state["seen"]
        idx = np.arange(max(lo, 0), c, stride, dtype=np.int64) \
            if lo < c else np.empty(0, np.int64)
        if len(idx):
            state["next"] = state["seen"] + int(idx[-1]) + stride
        state["seen"] += c
        return idx

    return pick


def ingest(source: ChunkSource, config,
           feature_names: Optional[List[str]] = None,
           categorical_feature=None,
           sample_stride: int = 0,
           comm=None) -> StreamedDataset:
    """Build a ``StreamedDataset`` from a chunk source (two passes).

    ``sample_stride > 0`` switches round 1 from reservoir sampling to
    systematic every-k-th-row sampling (capped at
    ``bin_construct_sample_cnt`` rows, earliest kept).

    ``comm`` (a ``parallel.network.HostComm``) switches on SHARDED ingest
    for a ``stream.source.ShardedSource``: every rank streams only its
    contiguous row block, then one host allgather merges the per-rank
    reservoir samples (rank order == original row order, so with
    ``bin_construct_sample_cnt >= n_global`` the merged sample IS the
    full data in order and bin boundaries are bit-identical to the
    serial / in-memory loaders; an over-cap merge is subsampled with a
    deterministic seed, identical on every rank but not serial-identical)
    and the per-rank labels into a GLOBAL label vector. The returned
    dataset keeps only the local chunks but reports global ``num_data``,
    global metadata, and the shard layout (``shard_rank`` /
    ``shard_world`` / ``shard_row_counts``).
    """
    sample_cnt = int(config.bin_construct_sample_cnt)
    shard_world = int(getattr(source, "shard_world", 1) or 1)
    if comm is None and shard_world > 1:
        from ..parallel import network
        comm = network.default_host_comm(namespace="lgbm_stream_ingest")
        if comm is None:
            raise LightGBMError(
                "sharded streamed ingest (ShardedSource with world=%d) "
                "needs a host allgather: initialize jax.distributed "
                "(parallel.network.init) or pass comm= explicitly"
                % shard_world)
    if comm is not None and shard_world <= 1:
        raise LightGBMError(
            "sharded streamed ingest needs a sharded source "
            "(stream.source.ShardedSource) carrying shard_rank/"
            "shard_world; got an unsharded %s" % type(source).__name__)
    rng = np.random.RandomState(config.data_random_seed)
    picker = _systematic_sample(int(sample_stride)) if sample_stride > 0 \
        else None

    source.reset()
    sample_rows: list = []
    labels: list = []
    n_total = 0
    n_features = -1
    n_chunks = 0
    for Xc, yc in source:
        Xc = np.asarray(Xc, np.float64)
        if Xc.ndim != 2:
            raise LightGBMError(
                "chunk %d is not 2-D (shape %s)" % (n_chunks, (Xc.shape,)))
        if n_features < 0:
            n_features = Xc.shape[1]
        elif Xc.shape[1] != n_features:
            raise LightGBMError(
                "chunk %d has %d features, expected %d — every chunk of a "
                "streamed source must share one feature space"
                % (n_chunks, Xc.shape[1], n_features))
        if yc is not None:
            labels.append(np.asarray(yc, np.float64).reshape(-1))
        elif labels:
            raise LightGBMError(
                "chunk %d has no label but earlier chunks did" % n_chunks)
        c = Xc.shape[0]
        if picker is not None:
            for i in picker(c):
                if len(sample_rows) < sample_cnt:
                    sample_rows.append(Xc[i].copy())
        else:
            # vectorized Algorithm R, identical to from_file_two_round
            # (io/dataset.py): fill in order, then row i draws
            # j ~ U[0, n_total+i] and replaces slot j when j < sample_cnt
            fill = max(0, min(sample_cnt - n_total, c))
            for i in range(fill):
                sample_rows.append(Xc[i].copy())
            if fill < c:
                draws = (rng.random_sample(c - fill)
                         * (n_total + np.arange(fill, c) + 1)
                         ).astype(np.int64)
                hits = np.nonzero(draws < sample_cnt)[0]
                for i in hits:
                    sample_rows[draws[i]] = Xc[fill + i].copy()
        n_total += c
        n_chunks += 1
    if n_total == 0:
        raise LightGBMError("streamed source yielded no rows")

    n_local = n_total
    shard_rank = 0
    shard_row_counts: Optional[List[int]] = None
    global_label: Optional[np.ndarray] = None
    sample_mat = np.asarray(sample_rows)
    if comm is not None:
        shard_rank = int(getattr(source, "shard_rank", 0))
        local_label = np.concatenate(labels) if labels else None
        gathered = comm.allgather({
            "rank": shard_rank, "world": shard_world, "n": int(n_local),
            "nfeat": int(n_features), "sample": sample_mat,
            "label": local_label})
        if len(gathered) != shard_world or any(
                g["rank"] != i or g["world"] != shard_world
                for i, g in enumerate(gathered)):
            raise LightGBMError(
                "sharded ingest rank/world mismatch: expected ranks 0..%d, "
                "got %s" % (shard_world - 1,
                            [(g["rank"], g["world"]) for g in gathered]))
        if len({g["nfeat"] for g in gathered}) != 1:
            raise LightGBMError(
                "sharded ingest feature-count mismatch across ranks: %s"
                % [g["nfeat"] for g in gathered])
        shard_row_counts = [int(g["n"]) for g in gathered]
        has_label = [g["label"] is not None for g in gathered]
        if any(has_label) and not all(has_label):
            raise LightGBMError(
                "sharded ingest: some ranks carry labels and some do not")
        if all(has_label):
            global_label = np.concatenate([g["label"] for g in gathered])
        # rank order == original row order (shard-assignment contract in
        # stream/source.py), so the concatenated sample reproduces what a
        # single process would have kept whenever every rank's reservoir
        # fill phase never overflowed
        sample_mat = np.concatenate([
            np.asarray(g["sample"]).reshape(-1, n_features)
            for g in gathered])
        if sample_mat.shape[0] > sample_cnt:
            sub = np.random.RandomState(config.data_random_seed)
            keep = np.sort(sub.choice(sample_mat.shape[0], sample_cnt,
                                      replace=False))
            sample_mat = sample_mat[keep]
        n_total = int(sum(shard_row_counts))

    names = feature_names or source.feature_names
    proto = BinnedDataset.from_matrix(
        sample_mat, config,
        feature_names=names, categorical_feature=categorical_feature)

    source.reset()
    chunks: List[np.ndarray] = []
    row = 0
    for Xc, _yc in source:
        bc = BinnedDataset.from_matrix(
            np.asarray(Xc, np.float64), config, reference=proto)
        chunks.append(np.ascontiguousarray(bc.X_binned))
        row += Xc.shape[0]
    if row != n_local:
        raise LightGBMError(
            "source is not restartable: round 2 yielded %d rows, round 1 "
            "saw %d — reset() must rewind to the identical chunk stream"
            % (row, n_local))

    sd = StreamedDataset()
    sd.__dict__.update(proto.__dict__)
    sd.X_binned = None
    sd._device_cache = {}
    sd._data_profile = None
    sd.chunks = chunks
    sd.num_data = n_total
    sd.metadata = Metadata(n_total)
    if comm is not None:
        sd.shard_rank = shard_rank
        sd.shard_world = shard_world
        sd.shard_row_counts = shard_row_counts
        sd.shard_comm = comm
        if global_label is not None:
            # every rank holds the FULL label vector: host-side label
            # statistics (boost_from_average, is_unbalance, metrics) then
            # agree bit-for-bit across ranks with zero further comm
            sd.metadata.set_label(global_label)
    elif labels:
        sd.metadata.set_label(np.concatenate(labels))
    Log.info("stream: ingested %d rows in %d chunks (%d stored columns, "
             "sample=%d rows%s)", n_total, len(chunks),
             chunks[0].shape[1] if chunks else 0, sample_mat.shape[0],
             (", shard %d/%d with %d local rows"
              % (shard_rank, shard_world, n_local)
              if comm is not None else ""))
    return sd
