"""Double-buffered host->device chunk transfer.

``jax.device_put`` is asynchronous: it enqueues the copy and returns
immediately, so issuing the NEXT chunk's transfer before sweeping the
current chunk's histograms overlaps PCIe/ICI traffic with compute — the
staging trick of the GPU-GBDT line (arXiv 1706.08359 §4), host-driven.
The pipeline keeps ``prefetch`` transfers in flight and measures how
well the overlap works: ``wait_s`` accumulates only the time the sweep
loop actually blocks on an unfinished copy, so

    overlap_efficiency = 1 - wait_s / total_s

is 1.0 when every transfer finished under the previous sweep and 0.0
when the loop is pure transfer-bound. Those numbers surface in
``tools/stream_smoke.py`` and BENCH_r12.

Chunks are repacked host-side to a UNIFORM ``chunk_rows`` row count
(last chunk zero-padded): every device buffer then has one shape
[R, C], so the jitted per-chunk kernels compile once regardless of how
many chunks the dataset has or how ragged the source's chunking was.
Row ``r`` of uniform chunk ``i`` is global row ``i*R + r``; rows past
``num_data`` are masked off by the grower's ``row_valid``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..log import check


def repack_uniform(chunks: List[np.ndarray], chunk_rows: int
                   ) -> Tuple[List[np.ndarray], int]:
    """Repack ragged uint8 chunks into ``chunk_rows``-row chunks.

    Returns (uniform_chunks, num_rows); every returned chunk has exactly
    ``chunk_rows`` rows (the last is zero-padded). Works chunk-by-chunk —
    never concatenates the full matrix.
    """
    check(chunk_rows > 0, "chunk_rows should be > 0, got %d" % chunk_rows)
    ncols = chunks[0].shape[1] if chunks else 0
    out: List[np.ndarray] = []
    buf = np.zeros((chunk_rows, ncols), np.uint8)
    fill = 0
    total = 0
    for c in chunks:
        c = np.asarray(c, np.uint8)
        total += c.shape[0]
        pos = 0
        while pos < c.shape[0]:
            take = min(chunk_rows - fill, c.shape[0] - pos)
            buf[fill:fill + take] = c[pos:pos + take]
            fill += take
            pos += take
            if fill == chunk_rows:
                out.append(buf)
                buf = np.zeros((chunk_rows, ncols), np.uint8)
                fill = 0
    if fill > 0:
        out.append(buf)          # trailing rows stay zero-padded
    return out, total


class ChunkPipeline:
    """Prefetching iterator over uniform device-resident bin chunks.

    ``packed=True`` stores the uniform host chunks word-packed (int32,
    4 codes per word — core/binpack.py) so every transfer lands in the
    kernel-native layout the packed histogram impls consume directly.
    The byte volume per row is unchanged by the words themselves
    (ceil(C/4)*4 vs C); the transfer halving of ``tpu_bin_packing=
    nibble`` comes from the DATASET pair coding having halved C before
    the chunks were quantized. ``num_cols`` always reports the real
    stored-column count C, not the word count.
    """

    def __init__(self, chunks: List[np.ndarray], chunk_rows: int,
                 prefetch: int = 2, device=None, packed: bool = False):
        self.chunk_rows = int(chunk_rows)
        self.prefetch = max(1, int(prefetch))
        self.device = device
        self.host_chunks, self.num_data = repack_uniform(chunks,
                                                         self.chunk_rows)
        self.num_chunks = len(self.host_chunks)
        self.num_cols = self.host_chunks[0].shape[1] if self.host_chunks \
            else 0
        self.packed = bool(packed)
        if self.packed:
            from ..core.binpack import pack_words_np
            self.host_chunks = [pack_words_np(c) for c in self.host_chunks]
        self.num_padded = self.num_chunks * self.chunk_rows
        # valid (unpadded) rows of each uniform chunk
        self.valid_rows = [
            min(self.chunk_rows, self.num_data - i * self.chunk_rows)
            for i in range(self.num_chunks)]
        # accounting, cumulative across sweeps
        self.sweeps = 0
        self.rows_transferred = 0
        self.wait_s = 0.0
        self.total_s = 0.0

    def _put(self, i: int):
        import jax
        h = self.host_chunks[i]
        return jax.device_put(h, self.device) if self.device is not None \
            else jax.device_put(h)

    @property
    def rows_per_sweep(self) -> int:
        """Rows THIS process transfers per sweep (== num_data when the
        pipeline is unsharded)."""
        return self.num_data

    def sweep(self) -> Iterator[Tuple[int, "object"]]:
        """Yield (chunk_index, device_chunk) once per chunk, in order,
        keeping up to ``prefetch`` transfers in flight ahead of the
        consumer. The consumer should finish its work on a yielded chunk
        before advancing (the buffer is dropped on the next step)."""
        t0 = time.perf_counter()
        inflight: deque = deque()
        for i in range(min(self.prefetch, self.num_chunks)):
            inflight.append((i, self._put(i)))
        while inflight:
            i, dev = inflight.popleft()
            tw = time.perf_counter()
            # the sync IS the measurement: wait_s only accumulates when a
            # transfer failed to hide under the previous chunk's sweep
            dev.block_until_ready()  # lgbm-lint: disable=LGL103 overlap probe
            self.wait_s += time.perf_counter() - tw
            nxt = i + self.prefetch
            if nxt < self.num_chunks:
                inflight.append((nxt, self._put(nxt)))
            yield i, dev
            del dev
        self.sweeps += 1
        self.rows_transferred += self.rows_per_sweep
        self.total_s += time.perf_counter() - t0

    # ------------------------------------------------------------- stats
    def overlap_efficiency(self) -> float:
        return 1.0 - self.wait_s / self.total_s if self.total_s > 0 else 1.0

    def ingest_rows_per_sec(self) -> Optional[float]:
        return self.rows_transferred / self.total_s if self.total_s > 0 \
            else None

    def stats(self) -> dict:
        return {
            "num_chunks": self.num_chunks,
            "chunk_rows": self.chunk_rows,
            "prefetch": self.prefetch,
            "sweeps": self.sweeps,
            "rows_transferred": self.rows_transferred,
            "wait_s": self.wait_s,
            "total_s": self.total_s,
            "overlap_efficiency": self.overlap_efficiency(),
            "ingest_rows_per_sec": self.ingest_rows_per_sec(),
        }


# --------------------------------------------------------- chunks x chips
def split_chunks_rows(chunks: List[np.ndarray], offsets
                      ) -> List[List[np.ndarray]]:
    """Slice an ordered chunk list into per-shard chunk lists along the
    contiguous row offsets — chunk by chunk, never concatenating the
    full matrix (the single-process analog of ``source.ShardedSource``)."""
    world = len(offsets) - 1
    out: List[List[np.ndarray]] = [[] for _ in range(world)]
    pos = 0
    for c in chunks:
        n = int(c.shape[0])
        for p in range(world):
            a = max(int(offsets[p]) - pos, 0)
            b = min(int(offsets[p + 1]) - pos, n)
            if a < b:
                out[p].append(c[a:b])
        pos += n
    check(pos >= int(offsets[-1]),
          "chunk list holds %d rows but shard offsets expect %d"
          % (pos, int(offsets[-1])))
    return out


def shard_rows_host(arr: np.ndarray, offsets, local_padded: int
                    ) -> np.ndarray:
    """Permute a host ``[n, ...]`` array into SHARD-MAJOR padded layout.

    Shard ``p`` owns original rows ``[offsets[p], offsets[p+1])`` (the
    contiguous shard-assignment contract, stream/source.py); in the
    padded layout those rows occupy ``[p*local_padded, p*local_padded +
    n_p)`` and the rest of each shard's block is zero — so a
    ``P(DATA_AXIS)`` row sharding puts every shard's rows (and only its
    rows) on its own device, with padding masked by ``row_valid``.
    Original row ``r`` of shard ``p`` lives at padded index
    ``p*local_padded + (r - offsets[p])``.
    """
    arr = np.asarray(arr)
    world = len(offsets) - 1
    out = np.zeros((world * int(local_padded),) + arr.shape[1:], arr.dtype)
    for p in range(world):
        n_p = int(offsets[p + 1]) - int(offsets[p])
        out[p * local_padded:p * local_padded + n_p] = \
            arr[int(offsets[p]):int(offsets[p + 1])]
    return out


def shard_rows_perm(offsets, local_padded: int) -> np.ndarray:
    """Inverse bookkeeping of :func:`shard_rows_host`: the ``[n]`` index
    vector such that ``padded[perm]`` recovers the original row order."""
    world = len(offsets) - 1
    parts = [np.arange(int(offsets[p + 1]) - int(offsets[p]), dtype=np.int64)
             + p * int(local_padded) for p in range(world)]
    return np.concatenate(parts) if parts else np.zeros(0, np.int64)


class ShardedChunkPipeline(ChunkPipeline):
    """Mesh-mode pipeline: ``sweep()`` yields GLOBAL ``[D*R, C]`` device
    arrays sharded ``P(DATA_AXIS)`` whose shard ``p`` is shard ``p``'s
    local uniform chunk ``i`` — so inside a ``shard_map`` kernel, chunk
    ``i`` looks exactly like the single-device pipeline's chunk ``i`` of
    that shard's rows, and the per-chunk kernels stay byte-identical.

    Every shard is padded (with all-zero chunks) to the GLOBAL maximum
    chunk count, so the host wave loop takes the same number of steps on
    every process — a collective inside the final chunk's kernel then
    lines up by construction. ``num_data``/``num_padded`` are global;
    ``local_padded = num_chunks * chunk_rows`` is one shard's padded row
    block. Word packing is intentionally unsupported here (the mesh
    learners shard the PLAIN feature axis); ``col_pad`` appends zero
    columns so the stored-column count divides the mesh axis when the
    reduce-scatter learner needs it.
    """

    def __init__(self, shard_chunks: List[List[np.ndarray]],
                 shard_row_counts: List[int], chunk_rows: int, mesh,
                 prefetch: int = 2, col_pad: int = 0):
        import jax
        from ..parallel.mesh import DATA_AXIS
        self.mesh = mesh
        self.chunk_rows = int(chunk_rows)
        self.prefetch = max(1, int(prefetch))
        self.device = None
        self.packed = False
        self.shard_row_counts = [int(n) for n in shard_row_counts]
        self.world = len(self.shard_row_counts)
        check(DATA_AXIS in mesh.axis_names,
              "sharded chunk pipeline needs a %r mesh axis" % DATA_AXIS)
        check(int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
              == self.world,
              "shard count %d != mesh size %d" % (
                  self.world,
                  int(np.prod([mesh.shape[a] for a in mesh.axis_names]))))
        # local shards are the mesh positions whose device this process
        # addresses, in mesh order; shard_chunks must line up with them
        pid = jax.process_index()
        devices = list(np.asarray(mesh.devices).reshape(-1))
        self.local_shards = [p for p, d in enumerate(devices)
                             if d.process_index == pid]
        self._local_devices = [devices[p] for p in self.local_shards]
        check(len(shard_chunks) == len(self.local_shards),
              "got chunk lists for %d shards but this process addresses "
              "%d mesh positions" % (len(shard_chunks),
                                     len(self.local_shards)))
        # uniform-repack each local shard; chunk-count padding to the
        # GLOBAL max keeps every process's wave loop in lockstep
        self.num_chunks = max(
            -(-n // self.chunk_rows) for n in self.shard_row_counts)
        R = self.chunk_rows
        self._shard_host_chunks: List[List[np.ndarray]] = []
        ncols = 0
        for li, chunks in enumerate(shard_chunks):
            uni, n = repack_uniform(chunks, R)
            p = self.local_shards[li]
            check(n == self.shard_row_counts[p],
                  "shard %d chunk rows %d != declared count %d"
                  % (p, n, self.shard_row_counts[p]))
            ncols = uni[0].shape[1] if uni else ncols
            if col_pad:
                uni = [np.concatenate(
                    [c, np.zeros((R, col_pad), c.dtype)], axis=1)
                    for c in uni]
            while len(uni) < self.num_chunks:
                uni.append(np.zeros((R, ncols + col_pad), np.uint8))
            self._shard_host_chunks.append(uni)
        self.num_cols = ncols + col_pad
        self.num_data = sum(self.shard_row_counts)
        self.local_padded = self.num_chunks * R
        self.num_padded = self.world * self.local_padded
        self.valid_rows = [
            min(R, max(self.shard_row_counts) - i * R)
            for i in range(self.num_chunks)]
        self.host_chunks = list(range(self.num_chunks))  # indices only
        self._sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(DATA_AXIS, None))
        self.sweeps = 0
        self.rows_transferred = 0
        self.wait_s = 0.0
        self.total_s = 0.0

    @property
    def rows_per_sweep(self) -> int:
        return sum(self.shard_row_counts[p] for p in self.local_shards)

    def shard_offsets(self) -> List[int]:
        """Row offsets of the rank-ordered shard blocks (original row
        space): shard ``p`` owns ``[off[p], off[p+1])``."""
        off = [0]
        for n in self.shard_row_counts:
            off.append(off[-1] + n)
        return off

    def _put(self, i: int):
        import jax
        bufs = [jax.device_put(self._shard_host_chunks[li][i], d)
                for li, d in enumerate(self._local_devices)]
        shape = (self.world * self.chunk_rows, self.num_cols)
        return jax.make_array_from_single_device_arrays(
            shape, self._sharding, bufs)
