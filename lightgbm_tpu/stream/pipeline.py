"""Double-buffered host->device chunk transfer.

``jax.device_put`` is asynchronous: it enqueues the copy and returns
immediately, so issuing the NEXT chunk's transfer before sweeping the
current chunk's histograms overlaps PCIe/ICI traffic with compute — the
staging trick of the GPU-GBDT line (arXiv 1706.08359 §4), host-driven.
The pipeline keeps ``prefetch`` transfers in flight and measures how
well the overlap works: ``wait_s`` accumulates only the time the sweep
loop actually blocks on an unfinished copy, so

    overlap_efficiency = 1 - wait_s / total_s

is 1.0 when every transfer finished under the previous sweep and 0.0
when the loop is pure transfer-bound. Those numbers surface in
``tools/stream_smoke.py`` and BENCH_r12.

Chunks are repacked host-side to a UNIFORM ``chunk_rows`` row count
(last chunk zero-padded): every device buffer then has one shape
[R, C], so the jitted per-chunk kernels compile once regardless of how
many chunks the dataset has or how ragged the source's chunking was.
Row ``r`` of uniform chunk ``i`` is global row ``i*R + r``; rows past
``num_data`` are masked off by the grower's ``row_valid``.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..log import check


def repack_uniform(chunks: List[np.ndarray], chunk_rows: int
                   ) -> Tuple[List[np.ndarray], int]:
    """Repack ragged uint8 chunks into ``chunk_rows``-row chunks.

    Returns (uniform_chunks, num_rows); every returned chunk has exactly
    ``chunk_rows`` rows (the last is zero-padded). Works chunk-by-chunk —
    never concatenates the full matrix.
    """
    check(chunk_rows > 0, "chunk_rows should be > 0, got %d" % chunk_rows)
    ncols = chunks[0].shape[1] if chunks else 0
    out: List[np.ndarray] = []
    buf = np.zeros((chunk_rows, ncols), np.uint8)
    fill = 0
    total = 0
    for c in chunks:
        c = np.asarray(c, np.uint8)
        total += c.shape[0]
        pos = 0
        while pos < c.shape[0]:
            take = min(chunk_rows - fill, c.shape[0] - pos)
            buf[fill:fill + take] = c[pos:pos + take]
            fill += take
            pos += take
            if fill == chunk_rows:
                out.append(buf)
                buf = np.zeros((chunk_rows, ncols), np.uint8)
                fill = 0
    if fill > 0:
        out.append(buf)          # trailing rows stay zero-padded
    return out, total


class ChunkPipeline:
    """Prefetching iterator over uniform device-resident bin chunks.

    ``packed=True`` stores the uniform host chunks word-packed (int32,
    4 codes per word — core/binpack.py) so every transfer lands in the
    kernel-native layout the packed histogram impls consume directly.
    The byte volume per row is unchanged by the words themselves
    (ceil(C/4)*4 vs C); the transfer halving of ``tpu_bin_packing=
    nibble`` comes from the DATASET pair coding having halved C before
    the chunks were quantized. ``num_cols`` always reports the real
    stored-column count C, not the word count.
    """

    def __init__(self, chunks: List[np.ndarray], chunk_rows: int,
                 prefetch: int = 2, device=None, packed: bool = False):
        self.chunk_rows = int(chunk_rows)
        self.prefetch = max(1, int(prefetch))
        self.device = device
        self.host_chunks, self.num_data = repack_uniform(chunks,
                                                         self.chunk_rows)
        self.num_chunks = len(self.host_chunks)
        self.num_cols = self.host_chunks[0].shape[1] if self.host_chunks \
            else 0
        self.packed = bool(packed)
        if self.packed:
            from ..core.binpack import pack_words_np
            self.host_chunks = [pack_words_np(c) for c in self.host_chunks]
        self.num_padded = self.num_chunks * self.chunk_rows
        # valid (unpadded) rows of each uniform chunk
        self.valid_rows = [
            min(self.chunk_rows, self.num_data - i * self.chunk_rows)
            for i in range(self.num_chunks)]
        # accounting, cumulative across sweeps
        self.sweeps = 0
        self.rows_transferred = 0
        self.wait_s = 0.0
        self.total_s = 0.0

    def _put(self, i: int):
        import jax
        h = self.host_chunks[i]
        return jax.device_put(h, self.device) if self.device is not None \
            else jax.device_put(h)

    def sweep(self) -> Iterator[Tuple[int, "object"]]:
        """Yield (chunk_index, device_chunk) once per chunk, in order,
        keeping up to ``prefetch`` transfers in flight ahead of the
        consumer. The consumer should finish its work on a yielded chunk
        before advancing (the buffer is dropped on the next step)."""
        t0 = time.perf_counter()
        inflight: deque = deque()
        for i in range(min(self.prefetch, self.num_chunks)):
            inflight.append((i, self._put(i)))
        while inflight:
            i, dev = inflight.popleft()
            tw = time.perf_counter()
            # the sync IS the measurement: wait_s only accumulates when a
            # transfer failed to hide under the previous chunk's sweep
            dev.block_until_ready()  # lgbm-lint: disable=LGL103 overlap probe
            self.wait_s += time.perf_counter() - tw
            nxt = i + self.prefetch
            if nxt < self.num_chunks:
                inflight.append((nxt, self._put(nxt)))
            yield i, dev
            del dev
        self.sweeps += 1
        self.rows_transferred += self.num_data
        self.total_s += time.perf_counter() - t0

    # ------------------------------------------------------------- stats
    def overlap_efficiency(self) -> float:
        return 1.0 - self.wait_s / self.total_s if self.total_s > 0 else 1.0

    def ingest_rows_per_sec(self) -> Optional[float]:
        return self.rows_transferred / self.total_s if self.total_s > 0 \
            else None

    def stats(self) -> dict:
        return {
            "num_chunks": self.num_chunks,
            "chunk_rows": self.chunk_rows,
            "prefetch": self.prefetch,
            "sweeps": self.sweeps,
            "rows_transferred": self.rows_transferred,
            "wait_s": self.wait_s,
            "total_s": self.total_s,
            "overlap_efficiency": self.overlap_efficiency(),
            "ingest_rows_per_sec": self.ingest_rows_per_sec(),
        }
