"""Out-of-core streamed ingest, binning, and training (docs/OutOfCore.md).

The subsystem that removes the "whole binned dataset in one device
allocation" assumption (ROADMAP out-of-core item; reference
``DatasetLoader``'s two-round sampled loading, PAPER.md §IO):

- ``source``   — the ``ChunkSource`` contract + in-memory / npy-mmap /
  CSV backends yielding bounded float chunks;
- ``sampler``  — round 1: reservoir/stride sample over a source finds
  the bin boundaries (io/binning.BinMapper, identical semantics to
  ``BinnedDataset.from_file_two_round``); round 2: every chunk is
  quantized host-side against that layout into uint8 ``StreamedDataset``
  chunks;
- ``pipeline`` — double-buffered host->device chunk transfer
  (``jax.device_put`` of the next chunk overlapped with the current
  chunk's histogram sweep) with ingest/overlap accounting;
- ``grow_stream`` — the host-driven frontier grower: per-chunk wave
  histograms summed before split finding (histograms are additive, so
  chunked growth is structure-identical to single-shot at the same bin
  boundaries).

Activated by ``data_stream_chunk_rows > 0`` (config.py); the user-facing
entry stays ``lgb.Dataset`` / ``lgb.train``.
"""
from .source import ArraySource, ChunkSource, CsvSource, NpyMmapSource
from .sampler import StreamedDataset, ingest
from .pipeline import ChunkPipeline
from .grow_stream import StreamFrontierGrower

__all__ = [
    "ChunkSource", "ArraySource", "NpyMmapSource", "CsvSource",
    "StreamedDataset", "ingest", "ChunkPipeline", "StreamFrontierGrower",
]
