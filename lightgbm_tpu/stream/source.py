"""Chunked raw-data sources for out-of-core ingest.

A ``ChunkSource`` is the streaming analog of the raw [N, F] matrix every
in-memory path starts from: a restartable iterator of bounded float
chunks. ``reset()`` rewinds it so the two-round loader (stream/sampler.py)
can pass over the data twice — once to sample bin boundaries, once to
quantize — exactly the contract the reference ``DatasetLoader`` has with
its text parsers (dataset_loader.cpp:160-219).

Backends:

- ``ArraySource``   — an in-memory dense matrix, sliced row-wise (the
  degenerate case; exists so every streamed-vs-single-shot parity test
  can run from identical bits);
- ``NpyMmapSource`` — a ``.npy`` file opened with ``mmap_mode="r"``:
  each chunk copies one row-slice out of the OS page cache, so peak
  resident float memory is one chunk regardless of file size;
- ``CsvSource``     — delimited text via ``io/parser.parse_file_chunks``
  (the two-round text front end; LibSVM is rejected up front because a
  sparse file has no global feature count until fully scanned).

Every backend validates eagerly (shape, dtype-coercibility, label
length) so a bad source fails at construction or on the first chunk with
a ``LightGBMError`` naming the problem, never as a shape error deep in
the binning pass.
"""
from __future__ import annotations

import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..log import LightGBMError, check

# one yielded chunk: (X [c, F] float64, label [c] float64 | None)
Chunk = Tuple[np.ndarray, Optional[np.ndarray]]


class ChunkSource:
    """Restartable iterator of (X_chunk, label_chunk) pairs.

    Contract: ``reset()`` rewinds to the first chunk; ``__iter__`` then
    yields every chunk once, in a FIXED order (chunk order is part of
    the streamed dataset's identity — the checkpoint fingerprint hashes
    chunks in order). ``chunk_rows`` bounds every chunk's row count;
    ``feature_names`` may be None until the first chunk has been read.
    """

    chunk_rows: int = 0
    feature_names: Optional[List[str]] = None

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Chunk]:
        raise NotImplementedError


def _check_chunk_rows(chunk_rows: int) -> int:
    chunk_rows = int(chunk_rows)
    check(chunk_rows > 0,
          "stream chunk_rows should be > 0, got %d" % chunk_rows)
    return chunk_rows


class ArraySource(ChunkSource):
    """In-memory dense matrix sliced into row chunks."""

    def __init__(self, data, label=None, chunk_rows: int = 262144):
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        if hasattr(data, "tocsr") or hasattr(data, "tocsc"):
            raise LightGBMError(
                "streamed ingest does not support sparse input; "
                "densify or set data_stream_chunk_rows=0")
        data = np.asarray(data)
        if data.ndim != 2:
            raise LightGBMError(
                "streamed ingest needs 2-D data, got shape %s"
                % (data.shape,))
        try:
            self._X = np.asarray(data, dtype=np.float64)
        except (TypeError, ValueError) as e:
            raise LightGBMError(
                "streamed ingest could not coerce data to float: %s" % e)
        self._y = None
        if label is not None:
            self._y = np.asarray(label, dtype=np.float64).reshape(-1)
            if len(self._y) != self._X.shape[0]:
                raise LightGBMError(
                    "label length %d does not match %d data rows"
                    % (len(self._y), self._X.shape[0]))
        self.num_rows = int(self._X.shape[0])

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[Chunk]:
        n = self._X.shape[0]
        for start in range(0, n, self.chunk_rows):
            stop = min(start + self.chunk_rows, n)
            yield (self._X[start:stop],
                   self._y[start:stop] if self._y is not None else None)


class NpyMmapSource(ChunkSource):
    """Row chunks out of a memory-mapped ``.npy`` matrix.

    ``np.load(mmap_mode="r")`` keeps the file on disk; each yielded chunk
    copies one row-slice (so downstream code may hold it without pinning
    the map). ``label`` is either an in-memory array or a path to a 1-D
    ``.npy`` of matching length.
    """

    def __init__(self, path: str, label=None, chunk_rows: int = 262144):
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        check(os.path.exists(path), "Data file %s doesn't exist" % path)
        self.path = path
        try:
            mm = np.load(path, mmap_mode="r")
        except Exception as e:  # noqa: BLE001 - surface as config error
            raise LightGBMError("could not mmap %s as .npy: %s" % (path, e))
        if mm.ndim != 2:
            raise LightGBMError(
                "%s should hold a 2-D matrix, got shape %s"
                % (path, mm.shape))
        self._shape = mm.shape
        self.num_rows = int(mm.shape[0])
        del mm
        self._y: Optional[np.ndarray] = None
        if isinstance(label, str):
            check(os.path.exists(label),
                  "Label file %s doesn't exist" % label)
            self._y = np.asarray(np.load(label), np.float64).reshape(-1)
        elif label is not None:
            self._y = np.asarray(label, np.float64).reshape(-1)
        if self._y is not None and len(self._y) != self._shape[0]:
            raise LightGBMError(
                "label length %d does not match %d rows of %s"
                % (len(self._y), self._shape[0], path))

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[Chunk]:
        mm = np.load(self.path, mmap_mode="r")
        try:
            n = mm.shape[0]
            for start in range(0, n, self.chunk_rows):
                stop = min(start + self.chunk_rows, n)
                X = np.array(mm[start:stop], dtype=np.float64)
                y = self._y[start:stop] if self._y is not None else None
                yield X, y
        finally:
            del mm


def shard_offsets(total_rows: int, world: int) -> List[int]:
    """The canonical shard-assignment contract: ``world`` CONTIGUOUS
    rank-ordered row blocks, shard ``p`` owning rows
    ``[off[p], off[p+1])`` with ``off[p] = floor(p * N / world)``.
    Concatenating the shards in rank order reproduces the original row
    order — which is what makes the allgathered bin-boundary sample and
    the rank-folded checkpoint fingerprint well defined (the same
    contract as the reference's distributed row partition,
    dataset_loader.cpp:469-495, minus the dropped remainder rows)."""
    check(world >= 1, "shard world should be >= 1, got %d" % world)
    check(total_rows >= world,
          "cannot shard %d rows over %d processes (every shard needs at "
          "least one row)" % (total_rows, world))
    return [total_rows * p // world for p in range(world + 1)]


class ShardedSource(ChunkSource):
    """One rank's contiguous row block of an inner ``ChunkSource``.

    Wraps any restartable source and yields only the rows in
    ``[offsets[rank], offsets[rank+1])``, re-chunked to the inner
    source's ``chunk_rows`` bound. The inner source is still streamed in
    full (chunk row counts are only known by reading), but rows outside
    the shard are dropped immediately, so peak memory stays one chunk.

    ``total_rows`` must be known up front (``ArraySource`` /
    ``NpyMmapSource`` know theirs; text sources need it passed
    explicitly) unless explicit ``offsets`` are given — the hook the
    skewed-shard tests use.
    """

    def __init__(self, inner: ChunkSource, rank: int, world: int,
                 total_rows: Optional[int] = None,
                 offsets: Optional[List[int]] = None):
        self.inner = inner
        self.chunk_rows = inner.chunk_rows
        self.shard_rank = int(rank)
        self.shard_world = int(world)
        check(0 <= self.shard_rank < self.shard_world,
              "shard rank %d out of range for world %d"
              % (self.shard_rank, self.shard_world))
        if offsets is not None:
            offs = [int(o) for o in offsets]
            check(len(offs) == self.shard_world + 1,
                  "explicit shard offsets need world+1=%d entries, got %d"
                  % (self.shard_world + 1, len(offs)))
            check(offs[0] == 0 and
                  all(offs[i] < offs[i + 1] for i in range(len(offs) - 1)),
                  "shard offsets must start at 0 and strictly increase "
                  "(every shard needs at least one row), got %s" % (offs,))
        else:
            if total_rows is None:
                total_rows = getattr(inner, "num_rows", None)
            check(total_rows is not None,
                  "ShardedSource needs total_rows (or explicit offsets) "
                  "for a source that cannot report its row count up front")
            offs = shard_offsets(int(total_rows), self.shard_world)
        self.offsets = offs
        self.total_rows = offs[-1]

    @property
    def feature_names(self):  # inner may learn names on first read
        return self.inner.feature_names

    @feature_names.setter
    def feature_names(self, v):
        self.inner.feature_names = v

    def reset(self) -> None:
        self.inner.reset()

    def __iter__(self) -> Iterator[Chunk]:
        lo = self.offsets[self.shard_rank]
        hi = self.offsets[self.shard_rank + 1]
        pos = 0
        for Xc, yc in self.inner:
            n = Xc.shape[0]
            a = max(lo - pos, 0)
            b = min(hi - pos, n)
            if a < b:
                yield (Xc[a:b],
                       yc[a:b] if yc is not None else None)
            pos += n
            if pos >= hi:
                break
        check(pos >= hi,
              "sharded source exhausted at row %d before reaching shard "
              "end %d — total_rows/offsets overstate the inner source"
              % (pos, hi))


class CsvSource(ChunkSource):
    """Delimited text file streamed through ``parser.parse_file_chunks``."""

    def __init__(self, path: str, chunk_rows: int = 262144,
                 has_header: bool = False, label_column: str = ""):
        from ..io import parser as parser_mod
        self.chunk_rows = _check_chunk_rows(chunk_rows)
        check(os.path.exists(path), "Data file %s doesn't exist" % path)
        if parser_mod.sniff_libsvm(path):
            raise LightGBMError(
                "streamed ingest supports delimited files only; LibSVM "
                "input needs the one-shot parser "
                "(data_stream_chunk_rows=0)")
        self.path = path
        self.has_header = bool(has_header)
        self.label_column = str(label_column)

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[Chunk]:
        from ..io.parser import parse_file_chunks
        for Xc, yc, names in parse_file_chunks(
                self.path, has_header=self.has_header,
                label_column=self.label_column,
                chunk_rows=self.chunk_rows):
            if self.feature_names is None and names:
                self.feature_names = list(names)
            yield np.asarray(Xc, np.float64), np.asarray(yc, np.float64)
