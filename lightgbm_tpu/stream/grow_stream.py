"""Host-driven frontier growth over device-streamed chunks.

The in-memory frontier grower (core/grow_frontier.py) is one jitted
``lax.while_loop`` over the whole [N, C] bin matrix. Here the matrix
never fits on device, so the wave loop moves to the HOST and each wave's
single dataset sweep becomes a sum of per-chunk sweeps — legal because
histograms are additive over row partitions, which is the exact property
that makes the result structure-identical to single-shot growth at the
same bin boundaries (asserted in tests/test_stream.py).

Everything per-ROW except the bin matrix (scores, grad/hess, sample
mask, leaf ids) stays device-resident at full length, padded to
``num_chunks * chunk_rows``; padding rows carry ``sample_mask == 0`` so
they contribute exactly zero to every histogram channel and every
gradient sum, and their (meaningless) leaf ids are never read.

The wave is cut into fixed-shape jitted kernels built from the SAME
helpers the in-memory grower uses (wave_plan / wave_route / wave_slots /
wave_commit / root_state):

- ``_wave_begin``  — per-leaf planning + the loop condition (the ONE
  host sync per wave: a single bool decides whether to sweep);
- ``_chunk_wave``  — per non-final chunk: dynamic-slice the chunk's
  rows out of the full per-row arrays, route them, accumulate the
  smaller-child histogram partial (fixed [R, C] chunk shape ->
  compiles once, independent of how many chunks the dataset has);
- ``_chunk_wave_commit`` — the FINAL chunk's sweep fused with the
  sibling subtraction and pool/tree/best commit: chunks+1 dispatches
  per wave, and the [W, C, B, 3] wave histogram never materializes as
  a standalone dispatch output.

When the dataset is word-packed (``tpu_bin_packing``, core/binpack.py)
the chunks arrive as int32 words and both sweep kernels unpack lanes
in-register; routing gathers the split column straight from the words.

Wave width is FIXED at ``frontier_max_width`` (the bucketing ladder is
disabled when streaming): a ladder would multiply the per-chunk kernel
set by the ladder length and make the compiled-program count depend on
which widths a run happens to visit — the perf gate pins that count
invariant in chunk count instead.

CHUNKS x CHIPS (``mesh`` given): every kernel above is wrapped in ONE
``shard_map`` over the data axis, each shard seeing exactly the block
the single-device kernel would see for its own rows — per-chunk bodies
are reused verbatim, and the learner's collective schedule
(``parallel/learners.py``: psum / reduce-scatter election / top-k
voting) fires only inside ``root_commit`` and the final chunk's fused
``chunk_wave_commit``. Histograms are additive over row partitions AND
over chunks, so accumulating chunk partials locally and reducing once
per wave is exact — and the per-wave collective count/payload is the
PR 12 in-memory number, independent of chunk count. Per-shard-varying
values that must cross the host loop between dispatches (the chunk
histogram accumulators, and the pool under varying-pool learners) ride
a leading mesh-sized axis sharded on the data axis; everything else in
the carried state is replicated. The per-wave host bool sync becomes a
single psum'd int32 continue flag whose output is a fully-replicated
global array — every process reads the SAME device value, so the wave
loops stay in lockstep without any host-side channel.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import compat
from ..bucketing import frontier_max_width
from ..core.grow import GrowParams, TreeArrays, expand_hist
from ..core.grow_frontier import (_FrontierState, root_state, wave_commit,
                                  wave_plan, wave_route, wave_slots)
from ..core.histogram import build_histogram, build_histogram_frontier
from ..core.split import FeatureMeta, find_best_split
from ..log import check
from ..parallel.learners import make_frontier_learner
from ..parallel.mesh import DATA_AXIS
from .pipeline import ChunkPipeline


class StreamFrontierGrower:
    """Grows one tree per ``grow()`` call by sweeping a ChunkPipeline.

    Same contract as ``grow_tree_frontier`` (tree, leaf_id, aux), with
    per-row inputs at the pipeline's PADDED length. With ``mesh`` the
    pipeline must be a ``ShardedChunkPipeline`` and the per-row inputs
    are GLOBAL arrays row-sharded over the data axis in the pipeline's
    shard-major padded layout; the returned tree is fully replicated and
    ``leaf_id`` stays row-sharded.
    """

    def __init__(self, pipeline: ChunkPipeline, meta: FeatureMeta,
                 params: GrowParams, mesh=None):
        check(not params.frontier_bucketing,
              "streamed growth uses a fixed wave width; construct "
              "GrowParams with frontier_bucketing=False")
        self.pipeline = pipeline
        self.params = params
        self.mesh = mesh
        self.trees_grown = 0
        self.waves = 0
        self.wave_dispatches = 0   # jitted calls inside wave loops
        p = params
        R = pipeline.chunk_rows
        ncols = pipeline.num_cols
        l = p.num_leaves
        b = p.num_bins
        sp = p.split
        kb = frontier_max_width(l, p.max_depth)
        self.wave_width = kb
        self._hist_shape = (ncols, b, 3)
        meta_ = meta
        axis = None if mesh is None else DATA_AXIS
        # leaf_id lives at block-local length inside the kernels: the
        # whole padded length when single-device, one shard's padded
        # block under the mesh
        n_rows = pipeline.num_padded if mesh is None \
            else pipeline.local_padded
        if mesh is not None:
            check(not (p.obs_health or p.obs_modelstats),
                  "streamed mesh growth disables obs accumulators; "
                  "construct GrowParams with obs_health/obs_modelstats "
                  "False (gbdt.py does)")
            check(not p.word_packed_cols,
                  "streamed mesh growth takes plain uint8 chunks; "
                  "tpu_bin_packing=word is single-process only")

        def make_lrn(fmask):
            # the feature mask changes per tree (feature_fraction), so the
            # learner closures bind it at trace time inside each kernel
            def child_best(hist_col, sum_g, sum_h, cnt, min_c, max_c):
                return find_best_split(
                    expand_hist(hist_col, sum_g, sum_h, cnt, meta_, p,
                                ncols),
                    meta_, sp, sum_g, sum_h, cnt, fmask,
                    min_constraint=min_c, max_constraint=max_c,
                    with_categorical=p.with_categorical)

            psum = (lambda x: x) if axis is None \
                else (lambda x: lax.psum(x, axis))
            return make_frontier_learner(p, axis, meta_, fmask,
                                         psum, child_best)

        def root_sums(grad, hess, mask):
            return (jnp.sum(grad * mask), jnp.sum(hess * mask),
                    jnp.sum(mask))

        def root_chunk(xb_c, start, grad, hess, mask, acc):
            g_c = lax.dynamic_slice(grad, (start,), (R,))
            h_c = lax.dynamic_slice(hess, (start,), (R,))
            m_c = lax.dynamic_slice(mask, (start,), (R,))
            return acc + build_histogram(
                xb_c, g_c, h_c, m_c, num_bins=b,
                row_chunk=p.row_chunk, impl=p.hist_impl,
                packed_cols=p.word_packed_cols)

        def root_commit(hist_acc, root_g, root_h, root_c, fmask):
            lrn = make_lrn(fmask)
            hist_root = lrn.reduce(hist_acc)
            return root_state(hist_root, root_g, root_h, root_c,
                              n_rows, l, sp, lrn, p, fmask,
                              axis_name=axis)

        def wave_begin(best, num_leaves):
            do = (num_leaves < l) & jnp.any(best.gain > 0.0)
            plan = wave_plan(best, num_leaves, kb, l)
            return do, plan

        def chunk_wave(xb_c, start, leaf_id, grad, hess, mask, plan,
                       hist_acc):
            (gval, gleaf, valid, nvalid, node, right_leaf, cur,
             rank_of_leaf) = plan
            lid_c = lax.dynamic_slice(leaf_id, (start,), (R,))
            g_c = lax.dynamic_slice(grad, (start,), (R,))
            h_c = lax.dynamic_slice(hess, (start,), (R,))
            m_c = lax.dynamic_slice(mask, (start,), (R,))
            new_lid, active, rs, go_left = wave_route(
                xb_c, lid_c, cur, rank_of_leaf, right_leaf, meta_,
                p.with_efb, p.with_categorical,
                packed_cols=p.word_packed_cols)
            _left_small, slot = wave_slots(cur, active, go_left, rs)
            part = build_histogram_frontier(
                xb_c, slot, g_c, h_c, m_c, num_bins=b, num_slots=kb,
                row_chunk=p.row_chunk, impl=p.hist_impl,
                packed_cols=p.word_packed_cols)
            leaf_id = lax.dynamic_update_slice(leaf_id, new_lid, (start,))
            return leaf_id, hist_acc + part

        def commit_state(s: _FrontierState, plan, hist_small, leaf_id,
                         fmask):
            lrn = make_lrn(fmask)
            (gval, gleaf, valid, nvalid, node, right_leaf, cur,
             rank_of_leaf) = plan
            left_small = cur.left_count <= cur.right_count
            hs = lrn.reduce(hist_small)
            (pool, tree, leaf_min, leaf_max, best, health,
             mstats) = wave_commit(
                s, kb, l, gval, gleaf, valid, nvalid, node, right_leaf,
                cur, left_small, hs, meta_, sp, p.max_depth, lrn)
            return _FrontierState(leaf_id=leaf_id, hist_pool=pool,
                                  best=best, tree=tree, leaf_min=leaf_min,
                                  leaf_max=leaf_max, health=health,
                                  mstats=mstats)

        def chunk_wave_commit(xb_c, start, s: _FrontierState, leaf_id,
                              grad, hess, mask, plan, hist_acc, fmask):
            # LAST chunk of the wave: its sweep, the sibling subtraction
            # and the 2K-child bin-scan commit fuse into ONE dispatch, so
            # the [W, C, B, 3] wave histogram never leaves the compiled
            # region as a standalone output (chunks+2 -> chunks+1
            # dispatches per wave — the streamed analog of the in-memory
            # grower's fused wave body)
            leaf_id, hist_acc = chunk_wave(xb_c, start, leaf_id, grad,
                                           hess, mask, plan, hist_acc)
            return commit_state(s, plan, hist_acc, leaf_id, fmask)

        if mesh is None:
            self._root_sums = jax.jit(root_sums)
            self._root_chunk = jax.jit(root_chunk)
            self._root_commit = jax.jit(root_commit)
            self._wave_begin = jax.jit(wave_begin)
            self._chunk_wave = jax.jit(chunk_wave)
            self._chunk_wave_commit = jax.jit(chunk_wave_commit)
            self._zero_root_acc = None
            self._zero_wave_acc = None
            self._audit_fns = {}
            return

        # ---------------------------------------------- chunks x chips
        # Per-shard-varying tensors that must survive between host-level
        # dispatches (chunk accumulators; the pool under varying-pool
        # learners) carry a leading mesh-sized axis sharded on DATA_AXIS:
        # each shard's block is its local value, so nothing is ever
        # averaged/collapsed by an out_spec and nothing is communicated
        # between chunks — the only collectives are the learner schedule
        # inside root_commit / the final fused chunk (payload == PR 12).
        varying = bool(p.voting_top_k > 0 or p.frontier_rs)
        self._varying_pool = varying
        rows = P(DATA_AXIS)
        repl = P()
        xspec = P(DATA_AXIS, None)
        lead = P(DATA_AXIS)        # leading-axis prefix for accumulators
        state_spec = _FrontierState(
            leaf_id=rows, hist_pool=(lead if varying else repl),
            best=repl, tree=repl, leaf_min=repl, leaf_max=repl,
            health=None, mstats=None)

        def _pack(s: _FrontierState) -> _FrontierState:
            return s._replace(hist_pool=s.hist_pool[None]) if varying \
                else s

        def _unpack(s: _FrontierState) -> _FrontierState:
            return s._replace(hist_pool=s.hist_pool[0]) if varying else s

        def root_chunk_mesh(xb_c, start, grad, hess, mask, acc):
            return root_chunk(xb_c, start, grad, hess, mask, acc[0])[None]

        def root_commit_mesh(hist_acc, root_g, root_h, root_c, fmask):
            return _pack(root_commit(hist_acc[0], root_g, root_h, root_c,
                                     fmask))

        def wave_begin_mesh(best, num_leaves):
            # the ONE per-wave sync: a psum'd continue flag whose result
            # is fully replicated, so every process's host loop reads the
            # same device value (no host-side channel, no divergence)
            do, plan = wave_begin(best, num_leaves)
            return lax.psum(do.astype(jnp.int32), axis), plan

        def chunk_wave_mesh(xb_c, start, leaf_id, grad, hess, mask, plan,
                            hist_acc):
            leaf_id, h = chunk_wave(xb_c, start, leaf_id, grad, hess,
                                    mask, plan, hist_acc[0])
            return leaf_id, h[None]

        def chunk_wave_commit_mesh(xb_c, start, s, leaf_id, grad, hess,
                                   mask, plan, hist_acc, fmask):
            s = _unpack(s)
            leaf_id, h = chunk_wave(xb_c, start, leaf_id, grad, hess,
                                    mask, plan, hist_acc[0])
            return _pack(commit_state(s, plan, h, leaf_id, fmask))

        # the unjitted shard_map'd stage fns are kept for the jaxpr
        # auditor (analysis/jaxpr_audit.streamed_sharded_fn composes one
        # full wave from them): jax.make_jaxpr on these traces the exact
        # per-dispatch program without compiling or perturbing the jitted
        # executables above
        self._audit_fns = {}

        def sm(name, fn, in_specs, out_specs):
            raw = compat.shard_map(fn, mesh, in_specs, out_specs,
                                   check_vma=False)
            self._audit_fns[name] = raw
            return jax.jit(raw)

        # root sums need no explicit axis: jnp.sum over the global
        # row-sharded arrays lowers to a GSPMD all-reduce and yields
        # replicated scalars
        self._root_sums = jax.jit(root_sums)
        self._root_chunk = sm(
            "root_chunk", root_chunk_mesh,
            (xspec, repl, rows, rows, rows, lead), lead)
        self._root_commit = sm(
            "root_commit", root_commit_mesh,
            (lead, repl, repl, repl, repl), state_spec)
        self._wave_begin = sm("wave_begin", wave_begin_mesh,
                              (repl, repl), (repl, repl))
        self._chunk_wave = sm(
            "chunk_wave", chunk_wave_mesh,
            (xspec, repl, rows, rows, rows, rows, repl, lead),
            (rows, lead))
        self._chunk_wave_commit = sm(
            "chunk_wave_commit", chunk_wave_commit_mesh,
            (xspec, repl, state_spec, rows, rows, rows, rows, repl, lead,
             repl),
            state_spec)
        # zero accumulators are device-put once (host zeros are globally
        # available, so multi-process device_put is legal) and reused
        # every wave — transfers stay one chunk per dispatch
        world = pipeline.world
        shard0 = NamedSharding(mesh, P(DATA_AXIS))
        self._zero_root_acc = jax.device_put(
            np.zeros((world,) + self._hist_shape, np.float32), shard0)
        self._zero_wave_acc = jax.device_put(
            np.zeros((world, kb) + self._hist_shape, np.float32), shard0)

    # ----------------------------------------------------------------- grow
    def grow(self, grad: jnp.ndarray, hess: jnp.ndarray,
             sample_mask: jnp.ndarray, feature_mask: jnp.ndarray,
             trace_span=None
             ) -> Tuple[TreeArrays, jnp.ndarray, Optional[jnp.ndarray]]:
        """Grow one tree. ``grad``/``hess``/``sample_mask`` are full
        padded-length device arrays; ``sample_mask`` must already be 0 on
        padding rows (and on bagged-out / GOSS-dropped rows).

        ``trace_span`` (obs/reqtrace.py, optional) gets one child per
        frontier wave — chunk-transfer wait (the pipeline's ``wait_s``
        delta) vs host dispatch time, plus the fused last-chunk commit —
        mirroring the serving request span tree on the training side.
        Pure host bookkeeping: the dispatched programs are identical with
        tracing on or off."""
        pipe = self.pipeline
        R = pipe.chunk_rows
        meshed = self.mesh is not None
        sample_mask = sample_mask.astype(jnp.float32)
        tspan = trace_span if trace_span else None
        if tspan is not None:
            rspan = tspan.child("root_sweep", chunks=pipe.num_chunks)
            w_mark = pipe.wait_s
        root_g, root_h, root_c = self._root_sums(grad, hess, sample_mask)
        acc = self._zero_root_acc if meshed \
            else jnp.zeros(self._hist_shape, jnp.float32)
        for i, xb_c in pipe.sweep():
            # np scalar start: every process passes the identical value,
            # so the replicated in_spec holds by construction
            acc = self._root_chunk(xb_c, np.int32(i * R), grad, hess,
                                   sample_mask, acc)
        state = self._root_commit(acc, root_g, root_h, root_c,
                                  feature_mask)
        if tspan is not None:
            rspan.end(transfer_wait_ms=round(
                (pipe.wait_s - w_mark) * 1000.0, 3))

        last = pipe.num_chunks - 1
        while True:
            do, plan = self._wave_begin(state.best, state.tree.num_leaves)
            if not bool(do):          # the one host sync per wave
                break
            if tspan is not None:
                wspan = tspan.child("wave", wave=self.waves,
                                    chunks=pipe.num_chunks)
                w_mark = pipe.wait_s
            hist_acc = self._zero_wave_acc if meshed \
                else jnp.zeros((self.wave_width,) + self._hist_shape,
                               jnp.float32)
            leaf_id = state.leaf_id
            dispatches = 1            # wave_begin
            for i, xb_c in pipe.sweep():
                if i == last:
                    # final chunk: sweep + sibling subtraction + commit
                    # in one fused dispatch (the wave histogram stays an
                    # internal value of the compiled region)
                    state = self._chunk_wave_commit(
                        xb_c, np.int32(i * R), state, leaf_id, grad,
                        hess, sample_mask, plan, hist_acc, feature_mask)
                else:
                    leaf_id, hist_acc = self._chunk_wave(
                        xb_c, np.int32(i * R), leaf_id, grad, hess,
                        sample_mask, plan, hist_acc)
                dispatches += 1
            self.waves += 1
            self.wave_dispatches += dispatches
            if tspan is not None:
                wspan.end(dispatches=dispatches, fused_commit=True,
                          transfer_wait_ms=round(
                              (pipe.wait_s - w_mark) * 1000.0, 3))

        self.trees_grown += 1
        if self.params.obs_modelstats:
            return state.tree, state.leaf_id, (state.health, state.mstats)
        return state.tree, state.leaf_id, state.health
