"""Engine-side resume: load a snapshot and put the whole run back.

``restore`` rehydrates everything ``engine.train`` assembled before the
boosting loop: the driver's device/RNG state (GBDT.load_training_state),
the validation score caches, and the loop-level callback state (eval
history into record_evaluation / the checkpoint callback's own record,
early-stopping slots). After it returns, the loop continues at the exact
iteration the snapshot captured, on the same PRNG trajectory.
"""
from __future__ import annotations

import collections
from typing import Any, Dict, Iterable, List, Optional

from ..log import Log, LightGBMError
from . import snapshot as snap_mod
from .manager import CheckpointManager, SnapshotHandle


def load_latest(directory: str,
                keep_last_n: int = 3) -> Optional[SnapshotHandle]:
    """Newest verifiable snapshot in ``directory`` (None = start fresh)."""
    return CheckpointManager(directory, keep_last_n=keep_last_n).load_latest()


def _fill_store(store: Dict, history: Dict[str, Dict[str, list]]) -> None:
    for data_name, per in (history or {}).items():
        dst = store.setdefault(data_name, collections.OrderedDict())
        for metric_name, values in per.items():
            dst.setdefault(metric_name, []).extend(values)


def restore(booster, handle: SnapshotHandle,
            callbacks: Optional[Iterable] = None) -> int:
    """Restore ``booster`` (+ loop callbacks) from ``handle``.

    Returns the number of boosting iterations the checkpointed run had
    already completed (on top of any init model), so the caller can shrink
    its remaining-round budget.
    """
    from .. import callback as callback_mod

    impl = booster._impl
    meta = handle.meta
    if meta.get("boosting_type", impl.boosting_type) != impl.boosting_type:
        raise LightGBMError(
            "checkpoint was written by boosting=%s but this run uses "
            "boosting=%s" % (meta.get("boosting_type"), impl.boosting_type))
    snap_mod.check_compatibility(meta, booster.config, impl.train_data)
    impl.load_training_state(meta, handle.arrays)

    loop = meta.get("train_loop") or {}
    history = loop.get("eval_history") or {}
    es_state = loop.get("early_stopping")
    for cb in callbacks or []:
        if getattr(cb, "is_checkpoint", False):
            cb.seed_history(history)
        elif isinstance(cb, callback_mod._RecordEvaluation):
            _fill_store(cb.store, history)
        elif isinstance(cb, callback_mod._EarlyStopping) and es_state:
            cb.set_state(es_state)

    completed = int(meta["iteration"]) - int(meta.get("num_init_iteration",
                                                      0))
    Log.info("checkpoint: restored snapshot %s from %s (%d iteration(s) "
             "already trained)", handle.entry.get("id"), handle.directory,
             completed)
    return completed
