"""lightgbm_tpu.checkpoint — preemption-safe training checkpoints.

A snapshot captures the COMPLETE training state — trees as raw arrays, the
f32 score matrix, every RNG cursor (bagging/GOSS ``PRNGKey``,
feature-fraction and DART ``RandomState``, DART tree weights), eval
history and early-stopping slots — under a checksummed, atomically-written
manifest with retention. A run killed at iteration *k* and resumed with
``engine.train(..., resume_from=dir)`` produces a model file byte-identical
to the uninterrupted run; corrupt/truncated snapshots are detected and
skipped in favor of the previous valid one. See docs/Checkpointing.md.
"""
from .callback import checkpoint
from .manager import CheckpointManager, SnapshotHandle
from .manifest import Manifest
from .resume import load_latest, restore
from .snapshot import (check_compatibility, config_hash,
                       dataset_fingerprint)

__all__ = [
    "checkpoint", "CheckpointManager", "SnapshotHandle", "Manifest",
    "load_latest", "restore", "check_compatibility", "config_hash",
    "dataset_fingerprint",
]
