"""CheckpointManager: the save/load driver over manifest + snapshot.

Owns one checkpoint directory. ``save`` captures a booster's (or bare
boosting driver's) training state into an immutable snapshot, publishes it
in the manifest, and applies retention (``keep_last_n`` newest + the
best-so-far snapshot by validation metric). ``load_latest`` returns the
newest snapshot that passes checksum verification, transparently falling
back past truncated/corrupt tails — or raises when a manifest exists but
nothing in it is loadable (silent data loss is never an option).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Tuple

from ..log import Log, LightGBMError
from .manifest import Manifest
from . import snapshot as snap_mod


class SnapshotHandle:
    """One loaded snapshot: state meta/arrays + the servable model path."""

    def __init__(self, directory: str, entry: Dict[str, Any],
                 meta: Dict[str, Any], arrays: Dict[str, Any],
                 model_path: str):
        self.directory = directory
        self.entry = entry
        self.meta = meta
        self.arrays = arrays
        self.model_path = model_path

    @property
    def iteration(self) -> int:
        return int(self.meta.get("iteration", self.entry.get("iteration", 0)))


def _impl_of(target):
    """Accept a basic.Booster or a bare boosting driver (bench.py style)."""
    return target._impl if hasattr(target, "_impl") else target


class CheckpointManager:

    def __init__(self, directory: str, keep_last_n: int = 3):
        if not directory:
            raise LightGBMError("checkpoint directory must be non-empty")
        self.directory = directory
        self.keep_last_n = int(keep_last_n)

    # ------------------------------------------------------------ save
    def save(self, target, train_loop: Optional[Dict[str, Any]] = None,
             eval_entry: Optional[Tuple] = None) -> Dict[str, Any]:
        """Snapshot ``target`` (Booster or driver) at its current iteration.

        ``train_loop`` carries loop-level state the driver doesn't own
        (eval history, early-stopping slots); ``eval_entry`` is one
        ``(data, metric, value, bigger_better)`` tuple used for the
        best-so-far retention flag.
        """
        impl = _impl_of(target)
        os.makedirs(self.directory, exist_ok=True)
        manifest = Manifest.load(self.directory) or Manifest(self.directory)

        meta, arrays = impl.training_state()
        meta["snapshot_version"] = snap_mod.SNAPSHOT_VERSION
        meta["config_hash"] = snap_mod.config_hash(impl.config)
        if impl.train_data is not None:
            meta["dataset_fingerprint"] = snap_mod.dataset_fingerprint(
                impl.train_data)
        meta["unix_time"] = time.time()
        if train_loop:
            meta["train_loop"] = train_loop

        if hasattr(target, "model_to_string"):
            model_text = target.model_to_string()
        else:
            from ..io import model_text as mt
            ds = impl.train_data
            model_text = mt.model_to_string(
                impl, list(ds.feature_names), list(ds.get_feature_infos()))

        snap_id = int(meta["iteration"])
        entry = snap_mod.write_snapshot(self.directory, snap_id, meta,
                                        arrays, model_text)
        entry["unix_time"] = meta["unix_time"]
        if eval_entry is not None:
            entry["eval"] = {"data": str(eval_entry[0]),
                             "metric": str(eval_entry[1]),
                             "value": float(eval_entry[2]),
                             "bigger_better": bool(eval_entry[3])}

        manifest.entries = [e for e in manifest.entries
                            if int(e["id"]) != snap_id]
        manifest.add_entry(entry)
        self._flag_best(manifest, entry)
        manifest.config_hash = meta["config_hash"]
        manifest.dataset_fingerprint = meta.get("dataset_fingerprint", "")
        manifest.prune(self.keep_last_n)
        manifest.save()
        return entry

    def save_refit(self, target, data_profile=None) -> Dict[str, Any]:
        """Publish a REFIT snapshot: trees only (structure + re-estimated
        leaf values), no resumable training state.

        This is how the continuous-training loop (docs/Fleet.md) ships a
        refitted model to the serving fleet: the snapshot gets the next
        free id so ``latest_model`` — the CheckpointWatcher poll target —
        hot-rolls it, while training resume (``load_latest``) SKIPS it
        and keeps resuming from the last full training snapshot, so
        checkpoint -> refit -> resume round-trips byte-stably.

        ``data_profile`` (obs.drift.DataProfile, typically built from the
        refit window) rides in the snapshot meta; the serving side picks
        it up via the sibling-meta seam (serving/registry.py), which is
        what makes post-refit drift scores recover.
        """
        impl = _impl_of(target)
        if not getattr(impl, "models", None):
            raise LightGBMError("save_refit: target has no trees")
        os.makedirs(self.directory, exist_ok=True)
        manifest = Manifest.load(self.directory) or Manifest(self.directory)

        tree_meta, arrays = snap_mod.trees_to_arrays(impl.models)
        k = max(int(getattr(impl, "num_tree_per_iteration", 1)), 1)
        meta: Dict[str, Any] = {
            "snapshot_version": snap_mod.SNAPSHOT_VERSION,
            "refit": True,
            "iteration": len(impl.models) // k,
            "config_hash": snap_mod.config_hash(impl.config),
            "unix_time": time.time(),
            "trees": tree_meta,
        }
        if data_profile is not None:
            meta["data_profile"] = data_profile.to_json_dict()

        if hasattr(target, "model_to_string"):
            model_text = target.model_to_string()
        else:
            from ..io import model_text as mt
            ds = impl.train_data
            model_text = mt.model_to_string(
                impl, list(ds.feature_names), list(ds.get_feature_infos()))

        snap_id = 1 + max((int(e["id"]) for e in manifest.entries),
                          default=int(meta["iteration"]) - 1)
        entry = snap_mod.write_snapshot(self.directory, snap_id, meta,
                                        arrays, model_text)
        entry["refit"] = True
        entry["unix_time"] = meta["unix_time"]
        manifest.entries = [e for e in manifest.entries
                            if int(e["id"]) != snap_id]
        manifest.add_entry(entry)
        manifest.prune(self.keep_last_n)
        manifest.save()
        return entry

    @staticmethod
    def _flag_best(manifest: Manifest, entry: Dict[str, Any]) -> None:
        ev = entry.get("eval")
        if not ev:
            return
        best = None
        for e in manifest.entries:
            if e.get("best") and e.get("eval") and e is not entry:
                best = e
                break
        if best is None:
            entry["best"] = True
            return
        bigger = bool(ev["bigger_better"])
        improved = (ev["value"] > best["eval"]["value"] if bigger
                    else ev["value"] < best["eval"]["value"])
        if improved:
            best["best"] = False
            entry["best"] = True

    # ------------------------------------------------------------ load
    def load_latest(self) -> Optional[SnapshotHandle]:
        """Newest verifiable snapshot, or None when the directory has no
        (readable) manifest — the fresh-start case a preemption-safe launch
        script hits on its very first run. Raises when a manifest lists
        snapshots but every one of them is corrupt."""
        manifest = Manifest.load(self.directory)
        if manifest is None or not manifest.entries:
            return None
        # refit snapshots (save_refit) are trees-only servables, not
        # resumable training state — training resume skips them and picks
        # up from the last FULL snapshot underneath
        train_entries = [e for e in manifest.entries if not e.get("refit")]
        if not train_entries:
            return None
        entry = manifest.latest_valid_entry(skip=lambda e: e.get("refit"))
        if entry is None:
            raise LightGBMError(
                "checkpoint directory %s has a manifest with %d snapshot(s) "
                "but none passed verification; refusing to silently start "
                "over" % (self.directory, len(train_entries)))
        if int(entry["id"]) != max(int(e["id"]) for e in train_entries):
            Log.warning("checkpoint: resuming from snapshot %s (newer "
                        "snapshots failed verification)", entry["id"])
        meta, arrays, model_path = snap_mod.read_snapshot(self.directory,
                                                          entry)
        return SnapshotHandle(self.directory, entry, meta, arrays, model_path)

    def latest_model(self) -> Optional[Tuple[int, str]]:
        """(snapshot id, model-text path) of the newest verifiable snapshot
        — the serving hot-roll hook's cheap poll target."""
        manifest = Manifest.load(self.directory)
        if manifest is None or not manifest.entries:
            return None
        entry = manifest.latest_valid_entry()
        if entry is None:
            return None
        return (int(entry["id"]),
                os.path.join(self.directory, entry["files"]["model"]))
