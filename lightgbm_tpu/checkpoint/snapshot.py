"""Snapshot serialization: complete training state <-> files on disk.

One snapshot is three files, all content-addressed by the manifest:

- ``snap_NNNNNNNN.state.npz``  — every array the boosting driver needs to
  continue bit-exactly: the raw HostTree fields (NOT a text round-trip — the
  doubles that go back into training are the doubles that came out), the f32
  score matrix, per-valid-set score caches, the bagging/GOSS ``PRNGKey``,
  the Mersenne-Twister key vectors of the feature-fraction (and DART drop)
  ``RandomState``, and CEGB leaves.
- ``snap_NNNNNNNN.meta.json``  — JSON-safe scalars: iteration counters,
  config hash, dataset fingerprint, RNG cursors, DART tree weights, the
  train-loop state (eval history + early-stopping slots).
- ``snap_NNNNNNNN.model.txt``  — ordinary model text, so a snapshot doubles
  as a servable model (serving.ModelRegistry.watch_dir hot-rolls it).

Determinism contract: restoring arrays verbatim (instead of replaying trees
through the predictor) is what makes a resumed run's scores — and therefore
every later split decision — bitwise identical to the uninterrupted run.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..log import LightGBMError, Log
from .manifest import atomic_write_bytes, sha256_file

SNAPSHOT_VERSION = 1

# HostTree array fields persisted verbatim (boosting/gbdt.py HostTree);
# absent fields (e.g. on text-loaded trees) keep the constructor defaults.
TREE_FIELDS = (
    "split_feature", "split_gain", "threshold", "threshold_bin",
    "default_left", "missing_type", "is_categorical", "cat_bitset",
    "cat_bitset_bin", "left_child", "right_child", "split_leaf",
    "internal_value", "internal_weight", "internal_count",
    "leaf_value", "leaf_weight", "leaf_count")

# parameters that do not change what a resumed run computes — excluded from
# the config hash so e.g. retargeting num_iterations or moving output paths
# does not spuriously flag a mismatch
_NON_SEMANTIC_PARAMS = frozenset({
    "config", "task", "data", "valid", "num_iterations", "num_threads",
    "verbosity", "output_model", "snapshot_freq", "input_model",
    "output_result", "convert_model", "convert_model_language",
    "early_stopping_round", "first_metric_only", "metric_freq",
    "checkpoint_dir", "checkpoint_period", "checkpoint_keep", "resume",
})


def snapshot_basename(snap_id: int) -> str:
    return "snap_%08d" % snap_id


def config_hash(config) -> str:
    """Stable hash of the semantically-relevant parameters."""
    d = config.to_dict()
    items = sorted((k, repr(v)) for k, v in d.items()
                   if k not in _NON_SEMANTIC_PARAMS)
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def dataset_fingerprint(binned) -> str:
    """Hash of the binned matrix + label: a resumed run must see the exact
    training data the snapshot was built from (cached on the dataset —
    O(bytes) once, not per snapshot)."""
    cached = getattr(binned, "_ckpt_fingerprint", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    xb = binned.X_binned
    if xb is not None:
        h.update(np.ascontiguousarray(xb).tobytes())
        h.update(repr(xb.shape).encode())
    elif getattr(binned, "is_streamed", False):
        # streamed dataset: no single matrix to hash. The fingerprint is
        # the bin layout (mapper boundaries — two sources that bin
        # differently must not resume each other) plus the ordered chunk
        # contents; chunking itself is NOT hashed beyond order, so the
        # same rows in the same order with a different chunk_rows still
        # match (the trained model is chunking-invariant by construction)
        for m in binned.bin_mappers:
            h.update(repr(sorted(m.to_dict().items())).encode())
        for c in binned.chunks:
            h.update(np.ascontiguousarray(c).tobytes())
        h.update(repr((binned.num_data,
                       binned.chunks[0].shape[1] if binned.chunks
                       else 0)).encode())
        comm = getattr(binned, "shard_comm", None)
        if comm is not None:
            # sharded stream: fold the RANK-ORDERED (rank, local digest,
            # local rows) tuples into one fingerprint shared by every
            # rank. Resume then refuses a reshuffled shard assignment —
            # the same rows dealt to different ranks change the tuple
            # order and thus the digest — while the identical layout
            # reproduces it exactly. COLLECTIVE: lockstep on all ranks.
            local = h.hexdigest()
            gathered = comm.allgather(
                (int(binned.shard_rank), local,
                 int(binned.shard_num_data)))
            h = hashlib.sha256()
            for rank, dig, nrows in sorted(gathered):
                h.update(repr((int(rank), str(dig), int(nrows))).encode())
    label = getattr(binned.metadata, "label", None)
    if label is not None:
        h.update(np.ascontiguousarray(np.asarray(label)).tobytes())
    fp = h.hexdigest()[:16]
    try:
        binned._ckpt_fingerprint = fp
    except AttributeError:
        pass
    return fp


def rng_state_split(rng: np.random.RandomState) -> Tuple[Dict, np.ndarray]:
    """RandomState -> (JSON-safe cursor, uint32 key vector)."""
    alg, keys, pos, has_gauss, cached = rng.get_state()
    return ({"alg": alg, "pos": int(pos), "has_gauss": int(has_gauss),
             "cached_gaussian": float(cached)},
            np.asarray(keys, np.uint32))


def rng_state_join(meta: Dict, keys: np.ndarray) -> Tuple:
    return (str(meta.get("alg", "MT19937")), np.asarray(keys, np.uint32),
            int(meta["pos"]), int(meta["has_gauss"]),
            float(meta["cached_gaussian"]))


def trees_to_arrays(models: List) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """HostTree list -> (meta, arrays) with per-tree prefixed keys."""
    meta = {"num_trees": len(models),
            "num_leaves": [int(t.num_leaves) for t in models],
            "num_leaves_actual": [int(getattr(t, "num_leaves_actual",
                                              t.num_leaves))
                                  for t in models],
            "shrinkage": [float(getattr(t, "shrinkage", 1.0))
                          for t in models]}
    arrays: Dict[str, np.ndarray] = {}
    for i, t in enumerate(models):
        for f in TREE_FIELDS:
            v = getattr(t, f, None)
            if v is not None:
                arrays["t%d_%s" % (i, f)] = np.asarray(v)
    return meta, arrays


def trees_from_arrays(meta: Dict[str, Any],
                      arrays: Dict[str, np.ndarray]) -> List:
    from ..boosting.gbdt import HostTree
    models = []
    for i in range(int(meta["num_trees"])):
        ht = HostTree(int(meta["num_leaves"][i]))
        ht.num_leaves_actual = int(meta["num_leaves_actual"][i])
        ht.shrinkage = float(meta["shrinkage"][i])
        for f in TREE_FIELDS:
            key = "t%d_%s" % (i, f)
            if key in arrays:
                setattr(ht, f, np.array(arrays[key]))
        models.append(ht)
    return models


def write_snapshot(directory: str, snap_id: int, meta: Dict[str, Any],
                   arrays: Dict[str, np.ndarray],
                   model_text: str) -> Dict[str, Any]:
    """Write the three snapshot files atomically; returns the manifest
    entry ({id, iteration, files, sha256, ...})."""
    base = snapshot_basename(snap_id)
    state_name = base + ".state.npz"
    meta_name = base + ".meta.json"
    model_name = base + ".model.txt"

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(os.path.join(directory, state_name), buf.getvalue())
    atomic_write_bytes(os.path.join(directory, meta_name),
                       json.dumps(meta, sort_keys=True).encode())
    atomic_write_bytes(os.path.join(directory, model_name),
                       model_text.encode())

    sha = {name: sha256_file(os.path.join(directory, name))
           for name in (state_name, meta_name, model_name)}
    # ckpt_write seam (docs/Resilience.md): a ckpt_torn fault truncates
    # the state file AFTER its sha was computed — exactly a torn write —
    # so the manifest check catches it and resume falls back a snapshot
    from ..resilience import faults
    faults.inject("ckpt_write", snapshot=int(snap_id),
                  path=os.path.join(directory, state_name))
    return {"id": int(snap_id),
            "iteration": int(meta.get("iteration", snap_id)),
            "files": {"state": state_name, "meta": meta_name,
                      "model": model_name},
            "sha256": sha}


def read_snapshot(directory: str,
                  entry: Dict[str, Any]) -> Tuple[Dict, Dict, str]:
    """Manifest entry -> (meta, arrays, model_path). Caller is expected to
    have verified checksums (Manifest.verify_entry / latest_valid_entry)."""
    files = entry["files"]
    with open(os.path.join(directory, files["meta"]), "r") as fh:
        meta = json.load(fh)
    if int(meta.get("snapshot_version", 0)) > SNAPSHOT_VERSION:
        raise LightGBMError(
            "snapshot %s written by a newer snapshot_version (%s > %d)"
            % (entry.get("id"), meta.get("snapshot_version"),
               SNAPSHOT_VERSION))
    with np.load(os.path.join(directory, files["state"])) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, arrays, os.path.join(directory, files["model"])


def check_compatibility(meta: Dict[str, Any], config,
                        binned) -> None:
    """Config mismatch warns (hyper-parameter tweaks on resume are a
    legitimate-if-sharp tool); dataset mismatch raises (resuming RNG and
    scores against different rows is silent corruption)."""
    want_fp = meta.get("dataset_fingerprint", "")
    have_fp = dataset_fingerprint(binned) if binned is not None else ""
    if want_fp and have_fp and want_fp != have_fp:
        raise LightGBMError(
            "checkpoint was written for a different dataset (fingerprint "
            "%s != %s); resume requires the identical training data"
            % (want_fp, have_fp))
    want_ch = meta.get("config_hash", "")
    have_ch = config_hash(config)
    if want_ch and want_ch != have_ch:
        Log.warning(
            "checkpoint config hash %s != current %s: parameters changed "
            "since the snapshot; the resumed run will NOT be byte-identical "
            "to an uninterrupted one", want_ch, have_ch)
