"""Versioned checkpoint manifest: the directory's source of truth.

A checkpoint directory holds immutable snapshot files plus ONE mutable
object — ``MANIFEST.json`` — listing every snapshot with per-file sha256
checksums. All writes are atomic (tmp + ``os.replace``), and the previous
manifest survives as ``MANIFEST.json.bak`` so even a crash between the two
renames leaves a loadable directory. Readers never trust a snapshot the
manifest doesn't vouch for: loading walks entries newest -> oldest and the
first entry whose files all exist *and* hash clean wins; anything else is
skipped with a warning (the preemption-mid-write case the subsystem exists
for).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

from ..log import Log, LightGBMError

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_BAK = "MANIFEST.json.bak"
FORMAT_VERSION = 1


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + rename in the same directory, fsynced before the rename so the
    rename never publishes a partially-flushed file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


class Manifest:
    """In-memory view of MANIFEST.json with atomic persistence."""

    def __init__(self, directory: str):
        self.directory = directory
        self.format_version = FORMAT_VERSION
        self.config_hash: str = ""
        self.dataset_fingerprint: str = ""
        self.entries: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ io
    @property
    def path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    @classmethod
    def load(cls, directory: str) -> Optional["Manifest"]:
        """Read the manifest, falling back to the .bak copy when the primary
        is missing or corrupt. Returns None when neither exists."""
        primary = os.path.join(directory, MANIFEST_NAME)
        backup = os.path.join(directory, MANIFEST_BAK)
        for path in (primary, backup):
            if not os.path.exists(path):
                continue
            try:
                with open(path, "r") as fh:
                    raw = json.load(fh)
            except (ValueError, OSError) as e:
                Log.warning("checkpoint manifest %s unreadable (%s); trying "
                            "fallback", path, e)
                continue
            if raw.get("format_version", 0) > FORMAT_VERSION:
                raise LightGBMError(
                    "checkpoint manifest %s has format_version %s, newer "
                    "than this build understands (%d)"
                    % (path, raw.get("format_version"), FORMAT_VERSION))
            m = cls(directory)
            m.format_version = int(raw.get("format_version", FORMAT_VERSION))
            m.config_hash = str(raw.get("config_hash", ""))
            m.dataset_fingerprint = str(raw.get("dataset_fingerprint", ""))
            m.entries = list(raw.get("entries", []))
            if path == backup:
                Log.warning("checkpoint manifest restored from %s",
                            MANIFEST_BAK)
            return m
        return None

    def save(self) -> None:
        """Atomically publish the manifest, demoting the previous one to
        .bak first (so a crash mid-save still leaves a valid manifest)."""
        payload = json.dumps({
            "format_version": self.format_version,
            "config_hash": self.config_hash,
            "dataset_fingerprint": self.dataset_fingerprint,
            "entries": self.entries,
        }, indent=1, sort_keys=True).encode()
        if os.path.exists(self.path):
            try:
                os.replace(self.path, os.path.join(self.directory,
                                                   MANIFEST_BAK))
            except OSError:
                pass
        atomic_write_bytes(self.path, payload)

    # ------------------------------------------------------------ entries
    def add_entry(self, entry: Dict[str, Any]) -> None:
        self.entries.append(entry)
        self.entries.sort(key=lambda e: int(e["id"]))

    def verify_entry(self, entry: Dict[str, Any]) -> bool:
        """True when every file the entry lists exists and hashes clean."""
        for fname, digest in entry.get("sha256", {}).items():
            path = os.path.join(self.directory, fname)
            if not os.path.exists(path):
                Log.warning("checkpoint snapshot %s missing file %s",
                            entry.get("id"), fname)
                return False
            if sha256_file(path) != digest:
                Log.warning("checkpoint snapshot %s failed checksum on %s "
                            "(truncated or corrupt write)",
                            entry.get("id"), fname)
                return False
        return True

    def latest_valid_entry(self, skip=None) -> Optional[Dict[str, Any]]:
        """Newest entry that verifies; corrupt tails are skipped loudly.
        ``skip(entry) -> bool`` filters entries OUT silently first — how
        training resume passes over refit snapshots (trees-only, no
        resumable state) that serving hot-rolls happily."""
        for entry in sorted(self.entries, key=lambda e: -int(e["id"])):
            if skip is not None and skip(entry):
                continue
            if self.verify_entry(entry):
                return entry
            Log.warning("checkpoint: falling back past corrupt snapshot %s",
                        entry.get("id"))
        return None

    def prune(self, keep_last_n: int) -> None:
        """Retention: keep the newest ``keep_last_n`` entries plus any entry
        flagged best-so-far plus the newest FULL training snapshot (a run
        of refit snapshots must never prune away the only resumable
        state); delete the files of everything else."""
        if keep_last_n <= 0 or len(self.entries) <= keep_last_n:
            return
        ordered = sorted(self.entries, key=lambda e: -int(e["id"]))
        keep = list(ordered[:keep_last_n])
        keep_ids = {int(e["id"]) for e in keep}
        for e in ordered[keep_last_n:]:
            if e.get("best"):
                keep.append(e)
                keep_ids.add(int(e["id"]))
        if not any(not e.get("refit") for e in keep):
            for e in ordered[keep_last_n:]:
                if not e.get("refit"):
                    keep.append(e)
                    keep_ids.add(int(e["id"]))
                    break
        for e in ordered:
            if int(e["id"]) in keep_ids:
                continue
            for fname in e.get("sha256", {}):
                try:
                    os.remove(os.path.join(self.directory, fname))
                except OSError:
                    pass
        self.entries = sorted(keep, key=lambda e: int(e["id"]))
