"""The ``checkpoint(...)`` training callback: periodic + SIGTERM snapshots.

Runs after each iteration, ordered between record_evaluation (20) and
early_stopping (30) so a snapshot at iteration *i* already carries *i*'s
eval history but is written before an early stop can unwind the loop.

Deliberately does NOT declare ``only_consumes_evals``: its presence forces
the engine onto the per-iteration path instead of the fused on-device
block loop (GBDT.train_many), whose blocked PRNG-key derivation differs.
That is load-bearing for the determinism guarantee — a checkpointed run
and its resumed continuation walk the same key sequence.

SIGTERM (preemption notice) is latched by a signal handler and honored at
the next iteration boundary — the only point where the training state is
consistent — then the previous handler is restored and the signal
re-raised so the process still dies like a SIGTERM'd one (exit 143).
"""
from __future__ import annotations

import collections
import signal
import threading
from typing import Any, Dict, Optional

from ..log import Log
from .manager import CheckpointManager


def _null_span():
    from ..obs.trace import _NULL_SPAN
    return _NULL_SPAN


class _Checkpoint:
    before_iteration = False
    order = 25
    is_checkpoint = True

    def __init__(self, directory: str, period: int = 1,
                 keep_last_n: int = 3, on_sigterm: bool = True):
        self.manager = CheckpointManager(directory, keep_last_n=keep_last_n)
        self.period = int(period)
        self.on_sigterm = bool(on_sigterm)
        self.history: Dict[str, Dict[str, list]] = {}
        self._sigterm = False
        self._prev_handler: Any = None
        self._installed = False

    # ------------------------------------------------------------ resume
    def seed_history(self, history: Dict[str, Dict[str, list]]) -> None:
        """Pre-fill eval history from a restored snapshot so later
        snapshots carry the full record, not just the post-resume tail."""
        self.history = {d: collections.OrderedDict(
            (m, list(v)) for m, v in per.items())
            for d, per in (history or {}).items()}

    # ------------------------------------------------------------ signal
    def _install_sigterm(self) -> None:
        if self._installed or not self.on_sigterm:
            return
        self._installed = True
        if threading.current_thread() is not threading.main_thread():
            Log.warning("checkpoint: not on the main thread; SIGTERM "
                        "snapshotting disabled for this run")
            return
        try:
            self._prev_handler = signal.signal(signal.SIGTERM, self._latch)
        except ValueError:   # no signal support in this context
            self._prev_handler = None

    def _latch(self, signum, frame) -> None:
        # only latch: the training state is mid-iteration here, so the
        # snapshot happens at the next after-iteration callback
        self._sigterm = True

    def _resign(self) -> None:
        """Put the previous handler back and re-deliver SIGTERM."""
        try:
            signal.signal(signal.SIGTERM,
                          self._prev_handler or signal.SIG_DFL)
        except ValueError:
            pass
        signal.raise_signal(signal.SIGTERM)

    # ------------------------------------------------------------ call
    def _early_stopping_state(self, env) -> Optional[list]:
        for cb in getattr(env.model, "_callbacks", []) or []:
            get_state = getattr(cb, "get_state", None)
            if get_state is not None and hasattr(cb, "stopping_rounds"):
                return get_state()
        return None

    def __call__(self, env) -> None:
        self._install_sigterm()
        if not hasattr(env.model, "_impl"):
            return   # cv's CVBooster: per-fold checkpointing unsupported
        for entry in env.evaluation_result_list or []:
            per = self.history.setdefault(entry[0], collections.OrderedDict())
            per.setdefault(entry[1], []).append(entry[2])

        it = env.iteration + 1
        due = (self.period > 0 and it % self.period == 0) \
            or it == env.end_iteration or self._sigterm
        if due:
            eval_entry = next(
                (e for e in env.evaluation_result_list or []
                 if e[0] not in ("training",
                                 getattr(env.model, "train_set_name",
                                         "training"))),
                None)
            train_loop: Dict[str, Any] = {"eval_history": self.history}
            es = self._early_stopping_state(env)
            if es is not None:
                train_loop["early_stopping"] = es
            obs = getattr(env.model._impl, "obs", None)
            span = (obs.span("checkpoint_save", iteration=it)
                    if obs is not None else _null_span())
            with span:
                self.manager.save(env.model, train_loop=train_loop,
                                  eval_entry=eval_entry)
            from ..obs.registry import get_registry
            get_registry().counter(
                "lgbm_checkpoint_saves_total",
                "Training checkpoints written.").inc()
        if self._sigterm:
            Log.warning("checkpoint: SIGTERM received; snapshot saved at "
                        "iteration %d in %s; exiting", it,
                        self.manager.directory)
            obs = getattr(env.model._impl, "obs", None)
            if obs is not None and hasattr(obs, "crash_flush"):
                # fsync the event stream + dump the flight recorder NOW,
                # while training state is still coherent; _resign()
                # re-delivers SIGTERM to the previous handler (the
                # recorder's, which finds its dump already latched)
                obs.crash_flush("sigterm")
            self._resign()


def checkpoint(directory: str, period: int = 1, keep_last_n: int = 3,
               on_sigterm: bool = True) -> _Checkpoint:
    """Create the checkpoint callback (docs/Checkpointing.md).

    Snapshots the complete training state into ``directory`` every
    ``period`` iterations, at the final iteration, and on SIGTERM (at the
    next iteration boundary); keeps the newest ``keep_last_n`` snapshots
    plus the best-so-far by validation metric.
    """
    return _Checkpoint(directory, period=period, keep_last_n=keep_last_n,
                       on_sigterm=on_sigterm)
