"""Plotting utilities: feature importance, metric history, tree rendering.

Covers the same public surface as the reference's plotting module
(plot_importance / plot_metric / plot_tree / create_tree_digraph), built on
three shared helpers (_resolve_booster, _new_axes, _style_axes) so each
plot function is mostly declarative. matplotlib and graphviz are optional
imports with informative errors.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster


def _resolve_booster(obj) -> Booster:
    """Accept a Booster or a fitted sklearn wrapper (``.booster_``)."""
    if isinstance(obj, Booster):
        return obj
    inner = getattr(obj, "booster_", None)
    if isinstance(inner, Booster):
        return inner
    raise TypeError("expected a Booster or fitted LGBMModel, got %s"
                    % type(obj).__name__)


def _pair(value, name: str):
    """Validate an (a, b) tuple argument (xlim/ylim/figsize)."""
    if not (isinstance(value, tuple) and len(value) == 2):
        raise TypeError("%s must be a tuple of 2 elements." % name)
    return value


def _new_axes(ax, figsize):
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    if figsize is not None:
        _pair(figsize, "figsize")
    return plt.subplots(1, 1, figsize=figsize)[1]


def _style_axes(ax, *, title, xlabel, ylabel, xlim=None, ylim=None,
                grid=True):
    if xlim is not None:
        ax.set_xlim(_pair(xlim, "xlim"))
    if ylim is not None:
        ax.set_ylim(_pair(ylim, "ylim"))
    for setter, value in ((ax.set_title, title), (ax.set_xlabel, xlabel),
                          (ax.set_ylabel, ylabel)):
        if value is not None:
            setter(value)
    ax.grid(grid)
    return ax


def _require_matplotlib(what: str):
    try:
        import matplotlib  # noqa: F401
    except ImportError as e:
        raise ImportError("matplotlib is required to %s" % what) from e


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type: str = "split", max_num_features=None,
                    ignore_zero: bool = True, figsize=None, grid: bool = True,
                    precision: Optional[int] = 3, **kwargs):
    """Horizontal bar chart of per-feature importance."""
    _require_matplotlib("plot importance")
    bst = _resolve_booster(booster)
    values = np.asarray(bst.feature_importance(importance_type), np.float64)
    names = list(bst.feature_name())
    if values.size == 0:
        raise ValueError("the model has no feature importances to plot")

    order = np.argsort(values, kind="stable")
    if ignore_zero:
        order = order[values[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[-max_num_features:]
    shown = values[order]
    ypos = np.arange(order.size)

    ax = _new_axes(ax, figsize)
    ax.barh(ypos, shown, align="center", height=height, **kwargs)
    fmt = ("%%.%df" % precision) if (precision is not None
                                     and importance_type == "gain") else None
    for y, v in zip(ypos, shown):
        ax.text(v + 1, y, fmt % v if fmt else str(int(v)), va="center")
    ax.set_yticks(ypos)
    ax.set_yticklabels([names[i] for i in order])
    return _style_axes(ax, title=title, xlabel=xlabel, ylabel=ylabel,
                       xlim=xlim, ylim=ylim, grid=grid)


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None,
                grid: bool = True):
    """Line chart of a recorded eval metric across iterations.

    ``booster`` is either the dict filled by ``record_evaluation`` or a
    fitted sklearn wrapper carrying ``evals_result_``.
    """
    _require_matplotlib("plot metrics")
    if isinstance(booster, dict):
        history = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        history = deepcopy(booster.evals_result_)
    else:
        raise TypeError("expected a record_evaluation dict or fitted "
                        "LGBMModel, got %s" % type(booster).__name__)
    if not history:
        raise ValueError("no recorded evaluation results to plot")

    names = dataset_names if dataset_names is not None else list(history)
    first = history[names[0]]
    if metric is None:
        if len(first) != 1:
            raise ValueError("several metrics were recorded; pass `metric` "
                             "to pick one of %s" % sorted(first))
        metric = next(iter(first))
    elif metric not in first:
        raise ValueError("metric %r was not recorded for dataset %r"
                         % (metric, names[0]))

    ax = _new_axes(ax, figsize)
    lo, hi = float("inf"), float("-inf")
    for name in names:
        series = history[name][metric]
        lo, hi = min(lo, min(series)), max(hi, max(series))
        ax.plot(range(len(series)), series, label=name)
    ax.legend(loc="best")
    if ylim is None:
        margin = (hi - lo) * 0.2
        ylim = (lo - margin, hi + margin)
    return _style_axes(ax, title=title, xlabel=xlabel,
                       ylabel=metric if ylabel == "auto" else ylabel,
                       xlim=xlim, ylim=ylim, grid=grid)


def _node_label(node: Dict[str, Any], feature_names, show_info, precision):
    """Build the graphviz label for one dumped-model node."""
    def rnd(x):
        return round(x, precision) if isinstance(x, float) else x

    if "split_index" in node:
        feat = node["split_feature"]
        feat_name = (feature_names[feat] if feature_names
                     else "feature %d" % feat)
        lines = ["%s %s %s" % (feat_name, node.get("decision_type", "<="),
                               rnd(node["threshold"]))]
        for key in show_info:
            if key in ("split_gain", "internal_value"):
                lines.append("%s: %s" % (key, rnd(node[key])))
            elif key == "internal_count":
                lines.append("count: %d" % node[key])
        return "split%d" % node["split_index"], "\n".join(lines)
    lines = ["leaf %d: %s" % (node["leaf_index"], rnd(node["leaf_value"]))]
    if "leaf_count" in show_info:
        lines.append("count: %d" % node["leaf_count"])
    return "leaf%d" % node["leaf_index"], "\n".join(lines)


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: Optional[int] = 3, **kwargs):
    """Build a graphviz Digraph of one tree from the dumped model."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("graphviz is required to draw trees") from e
    bst = _resolve_booster(booster)
    model = bst.dump_model()
    trees = model["tree_info"]
    if not 0 <= tree_index < len(trees):
        raise IndexError("tree_index %d out of range (model has %d trees)"
                         % (tree_index, len(trees)))
    feature_names = model.get("feature_names")
    show_info = show_info or []

    graph = Digraph(**kwargs)
    stack = [(trees[tree_index]["tree_structure"], None, None)]
    while stack:
        node, parent, branch = stack.pop()
        name, label = _node_label(node, feature_names, show_info, precision)
        graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, branch)
        if "split_index" in node:
            stack.append((node["right_child"], name, "no"))
            stack.append((node["left_child"], name, "yes"))
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info=None, precision: Optional[int] = 3, **kwargs):
    """Render one tree into a matplotlib axis (via graphviz png)."""
    _require_matplotlib("plot trees")
    import matplotlib.image as mpimg
    from io import BytesIO
    ax = _new_axes(ax, figsize)
    graph = create_tree_digraph(booster, tree_index, show_info, precision,
                                **kwargs)
    ax.imshow(mpimg.imread(BytesIO(graph.pipe(format="png"))))
    ax.axis("off")
    return ax
