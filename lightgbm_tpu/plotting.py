"""Plotting utilities.

Reference: python-package/lightgbm/plotting.py — plot_importance (:30),
plot_metric (:144), plot_tree / create_tree_digraph (:318). matplotlib and
graphviz are optional; informative errors otherwise (compat.py pattern).
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError("%s must be a tuple of 2 elements." % obj_name)


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type: str = "split", max_num_features=None,
                    ignore_zero: bool = True, figsize=None, grid: bool = True,
                    precision: Optional[int] = 3, **kwargs):
    """plotting.py:30."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance")

    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type)
        feature_name = booster.feature_name()
    elif hasattr(booster, "booster_"):
        importance = booster.booster_.feature_importance(importance_type)
        feature_name = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")

    if not len(importance):
        raise ValueError("Booster's feature_importance is empty")
    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                ("%." + str(precision) + "f") % x if precision is not None
                and importance_type == "gain" else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None,
                grid: bool = True):
    """plotting.py:144: plot recorded eval history (record_evaluation dict or
    a fitted LGBMModel)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric")

    if isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        eval_results = deepcopy(booster.evals_result_)
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    name = dataset_names[0]
    metrics_for_one = eval_results[name]
    if metric is None:
        if len(metrics_for_one) > 1:
            raise ValueError("more than one metric available, pick one")
        metric, results = list(metrics_for_one.items())[0]
    else:
        if metric not in metrics_for_one:
            raise ValueError("specific metric not found")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result, min_result = max(results), min(results)
    for name in dataset_names:
        results = eval_results[name][metric]
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        ax.plot(range(num_iteration), results, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    else:
        range_result = max_result - min_result
        ax.set_ylim(min_result - range_result * 0.2,
                    max_result + range_result * 0.2)
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_graphviz(tree_info: Dict, show_info: List[str],
                 feature_names: List[str], precision=3, **kwargs):
    """plotting.py:244 _to_graphviz."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree")

    def add(root, parent=None, decision=None):
        if "split_index" in root:
            name = "split%d" % root["split_index"]
            f = root["split_feature"]
            label = feature_names[f] if feature_names else "feature %d" % f
            label += " %s %s" % (root.get("decision_type", "<="),
                                 round(root["threshold"], precision)
                                 if isinstance(root["threshold"], float)
                                 else root["threshold"])
            for info in show_info:
                if info in ("split_gain", "internal_value"):
                    label += "\n%s: %s" % (info, round(root[info], precision))
                elif info == "internal_count":
                    label += "\ncount: %d" % root[info]
            graph.node(name, label=label)
            add(root["left_child"], name, "yes")
            add(root["right_child"], name, "no")
        else:
            name = "leaf%d" % root["leaf_index"]
            label = "leaf %d: %s" % (root["leaf_index"],
                                     round(root["leaf_value"], precision))
            if "leaf_count" in show_info:
                label += "\ncount: %d" % root["leaf_count"]
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    graph = Digraph(**kwargs)
    add(tree_info["tree_structure"])
    return graph


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: Optional[int] = 3, **kwargs):
    """plotting.py:318."""
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", None)
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range")
    if show_info is None:
        show_info = []
    return _to_graphviz(tree_infos[tree_index], show_info, feature_names,
                        precision, **kwargs)


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info=None, precision: Optional[int] = 3, **kwargs):
    """plotting.py:390s: render via graphviz into a matplotlib axis."""
    try:
        import matplotlib.pyplot as plt
        import matplotlib.image as image
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree")
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster, tree_index, show_info, precision,
                                **kwargs)
    from io import BytesIO
    s = BytesIO(graph.pipe(format="png"))
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
