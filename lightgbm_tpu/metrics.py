"""Evaluation metrics.

Re-design of src/metric/* (metric.h interface, regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp, map_metric.hpp,
xentropy_metric.hpp, plus the fork's topavg_metric.hpp / topavgdiff_metric.hpp
registered at metric.cpp:56-59). Metrics run host-side in NumPy — they are
evaluated once every ``metric_freq`` iterations on scores pulled from device,
so they are off the hot path by construction.

Conventions mirror the reference: ``Eval(score, objective)`` applies the
objective's ConvertOutput internally where the reference does;
``factor_to_bigger_better`` drives early stopping direction.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .config import Config
from .log import Log, LightGBMError, check
from .io.dataset import Metadata

_EPS = 1e-15


def _sigmoid(x, s=1.0):
    return 1.0 / (1.0 + np.exp(-s * x))


class Metric:
    """metric.h interface analog."""

    def __init__(self, config: Config):
        self.config = config
        self.names: List[str] = []
        self.factor_to_bigger_better = 1.0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.label = None if metadata.label is None else np.asarray(metadata.label)
        self.weights = None if metadata.weight is None else np.asarray(metadata.weight)
        self.sum_weights = (float(num_data) if self.weights is None
                            else float(self.weights.sum()))

    def eval(self, score: np.ndarray, convert_output=None) -> List[float]:
        raise NotImplementedError


# ------------------------------------------------------------- regression
class _PointwiseMetric(Metric):
    """regression_metric.hpp RegressionMetric<PointWiseLossCalculator>."""
    metric_name = ""
    bigger_better = False
    apply_convert = True

    def __init__(self, config):
        super().__init__(config)
        self.names = [self.metric_name]
        self.factor_to_bigger_better = 1.0 if self.bigger_better else -1.0

    def point_loss(self, label: np.ndarray, score: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, avg: float) -> float:
        return avg

    def eval(self, score, convert_output=None) -> List[float]:
        score = np.asarray(score, np.float64).reshape(-1)
        if self.apply_convert and convert_output is not None:
            score = np.asarray(convert_output(score))
        losses = self.point_loss(self.label.astype(np.float64), score)
        if self.weights is not None:
            avg = float(np.sum(losses * self.weights) / self.sum_weights)
        else:
            avg = float(np.mean(losses))
        return [self.transform(avg)]


class L2Metric(_PointwiseMetric):
    metric_name = "l2"
    def point_loss(self, y, s): return (s - y) ** 2


class RMSEMetric(_PointwiseMetric):
    metric_name = "rmse"
    def point_loss(self, y, s): return (s - y) ** 2
    def transform(self, avg): return math.sqrt(avg)


class L1Metric(_PointwiseMetric):
    metric_name = "l1"
    def point_loss(self, y, s): return np.abs(s - y)


class QuantileMetric(_PointwiseMetric):
    metric_name = "quantile"
    def point_loss(self, y, s):
        a = self.config.alpha
        d = y - s
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    metric_name = "huber"
    def point_loss(self, y, s):
        a = self.config.alpha
        d = np.abs(s - y)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    metric_name = "fair"
    def point_loss(self, y, s):
        c = self.config.fair_c
        x = np.abs(s - y)
        return c * x - c * c * np.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    metric_name = "poisson"
    def point_loss(self, y, s):
        eps = 1e-10
        s = np.maximum(s, eps)
        return s - y * np.log(s)


class MAPEMetric(_PointwiseMetric):
    metric_name = "mape"
    def point_loss(self, y, s):
        return np.abs((y - s)) / np.maximum(1.0, np.abs(y))


class GammaMetric(_PointwiseMetric):
    metric_name = "gamma"
    def point_loss(self, y, s):
        # negative gamma log-likelihood with psi=1 (regression_metric.hpp)
        s = np.maximum(s, 1e-10)
        return y / s + np.log(s)


class GammaDevianceMetric(_PointwiseMetric):
    metric_name = "gamma_deviance"
    def point_loss(self, y, s):
        frac = y / np.maximum(s, 1e-10)
        return 2.0 * (-np.log(frac) + frac - 1.0)


class TweedieMetric(_PointwiseMetric):
    metric_name = "tweedie"
    def point_loss(self, y, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        a = y * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


# ----------------------------------------------------------------- binary
class BinaryLoglossMetric(_PointwiseMetric):
    """binary_metric.hpp BinaryLoglossMetric (prob via ConvertOutput)."""
    metric_name = "binary_logloss"
    def point_loss(self, y, p):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class BinaryErrorMetric(_PointwiseMetric):
    metric_name = "binary_error"
    def point_loss(self, y, p):
        return np.where(p > 0.5, 1.0 - y, y).astype(np.float64)


class AUCMetric(Metric):
    """binary_metric.hpp:150-263 — weighted sorted-scan AUC."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["auc"]
        self.factor_to_bigger_better = 1.0

    def eval(self, score, convert_output=None) -> List[float]:
        # raw scores fine: AUC is rank-based (reference uses raw score too)
        score = np.asarray(score, np.float64).reshape(-1)
        y = self.label > 0
        w = self.weights if self.weights is not None else np.ones_like(score)
        order = np.argsort(-score, kind="stable")
        s, yy, ww = score[order], y[order], w[order]
        # group ties: accumulate per threshold block
        sum_pos = 0.0
        accum = 0.0
        cur_pos = 0.0
        cur_neg = 0.0
        threshold = s[0] if len(s) else 0.0
        for i in range(len(s)):
            if s[i] != threshold:
                threshold = s[i]
                accum += cur_neg * (cur_pos * 0.5 + sum_pos)
                sum_pos += cur_pos
                cur_neg = cur_pos = 0.0
            cur_neg += (not yy[i]) * ww[i]
            cur_pos += yy[i] * ww[i]
        accum += cur_neg * (cur_pos * 0.5 + sum_pos)
        sum_pos += cur_pos
        sum_neg = float(np.sum(w)) - sum_pos
        if sum_pos <= 0 or sum_neg <= 0:
            return [1.0]
        return [accum / (sum_pos * sum_neg)]


# -------------------------------------------------------------- multiclass
class MultiLoglossMetric(Metric):
    """multiclass_metric.hpp multi_logloss."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["multi_logloss"]
        self.factor_to_bigger_better = -1.0
        self.num_class = config.num_class

    def eval(self, score, convert_output=None) -> List[float]:
        p = np.asarray(score, np.float64).reshape(-1, self.num_class)
        if convert_output is not None:
            p = np.asarray(convert_output(p))
        idx = self.label.astype(np.int64)
        pt = np.clip(p[np.arange(len(idx)), idx], 1e-15, None)
        losses = -np.log(pt)
        if self.weights is not None:
            return [float(np.sum(losses * self.weights) / self.sum_weights)]
        return [float(np.mean(losses))]


class MultiErrorMetric(Metric):
    def __init__(self, config):
        super().__init__(config)
        self.names = ["multi_error"]
        self.factor_to_bigger_better = -1.0
        self.num_class = config.num_class

    def eval(self, score, convert_output=None) -> List[float]:
        p = np.asarray(score, np.float64).reshape(-1, self.num_class)
        pred = np.argmax(p, axis=1)
        err = (pred != self.label.astype(np.int64)).astype(np.float64)
        if self.weights is not None:
            return [float(np.sum(err * self.weights) / self.sum_weights)]
        return [float(np.mean(err))]


# ----------------------------------------------------------------- xentropy
class CrossEntropyMetric(_PointwiseMetric):
    metric_name = "xentropy"
    def point_loss(self, y, p):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return -(y * np.log(p) + (1 - y) * np.log(1 - p))


class CrossEntropyLambdaMetric(_PointwiseMetric):
    metric_name = "xentlambda"
    def point_loss(self, y, hhat):
        # hhat = log1p(exp(score)) via ConvertOutput
        hhat = np.maximum(hhat, 1e-15)
        z = 1.0 - np.exp(-hhat)
        z = np.clip(z, 1e-15, 1 - 1e-15)
        return -(y * np.log(z) + (1 - y) * np.log(1 - z))


class KLDivMetric(_PointwiseMetric):
    metric_name = "kldiv"
    def point_loss(self, y, p):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        yc = np.clip(y, 1e-15, 1 - 1e-15)
        return (yc * np.log(yc / p) + (1 - yc) * np.log((1 - yc) / (1 - p)))


# ------------------------------------------------------------------ ranking
class _QueryMetric(Metric):
    """Shared per-query machinery (rank_metric.hpp / map_metric.hpp)."""

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = [int(k) for k in (config.eval_at or [1, 2, 3, 4, 5])]
        self.factor_to_bigger_better = 1.0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        check(metadata.query_boundaries is not None,
              "query information required for ranking metric")
        self.qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(self.qb) - 1
        # per-query weights (metadata.cpp LoadQueryWeights: mean of the
        # query's document weights) — weighted queries contribute
        # proportionally to the metric, exactly rank_metric.hpp's
        # query_weights_ / sum_query_weights_ accumulation
        qw = metadata.query_weights
        self.query_weights = (None if qw is None
                              else np.asarray(qw, np.float64))
        self.sum_query_weights = (float(self.num_queries)
                                  if self.query_weights is None
                                  else float(self.query_weights.sum()))

    def per_query(self, y: np.ndarray, s: np.ndarray) -> List[float]:
        raise NotImplementedError

    def eval(self, score, convert_output=None) -> List[float]:
        score = np.asarray(score, np.float64).reshape(-1)
        totals = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lo, hi = self.qb[q], self.qb[q + 1]
            pq = np.asarray(self.per_query(self.label[lo:hi], score[lo:hi]))
            totals += pq if self.query_weights is None \
                else self.query_weights[q] * pq
        return list(totals / self.sum_query_weights)


class NDCGMetric(_QueryMetric):
    """rank_metric.hpp NDCG@k with label_gain weighting."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["ndcg@%d" % k for k in self.eval_at]
        from .objectives import default_label_gain
        gains = config.label_gain
        self.label_gain = (np.asarray(gains, np.float64) if gains
                           else default_label_gain())

    def per_query(self, y, s):
        n = len(y)
        disc = 1.0 / np.log2(2.0 + np.arange(n))
        yi = y.astype(np.int64)
        order = np.argsort(-s, kind="stable")
        out = []
        for k in self.eval_at:
            kk = min(k, n)
            ideal = np.sort(self.label_gain[yi])[::-1]
            max_dcg = float(np.sum(ideal[:kk] * disc[:kk]))
            if max_dcg <= 0:
                out.append(1.0)  # all-zero-label query counts as perfect
            else:
                dcg = float(np.sum(self.label_gain[yi[order[:kk]]] * disc[:kk]))
                out.append(dcg / max_dcg)
        return out


class MAPMetric(_QueryMetric):
    """map_metric.hpp MAP@k (binary relevance)."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["map@%d" % k for k in self.eval_at]

    def per_query(self, y, s):
        order = np.argsort(-s, kind="stable")
        rel = (y[order] > 0).astype(np.float64)
        cum = np.cumsum(rel)
        prec = cum / (1.0 + np.arange(len(rel)))
        out = []
        for k in self.eval_at:
            kk = min(k, len(rel))
            npos = rel[:kk].sum()
            out.append(float(np.sum(prec[:kk] * rel[:kk]) / npos) if npos > 0 else 0.0)
        return out


class TopavgMetric(_QueryMetric):
    """Fork-custom: mean label of the |k| lowest-scored docs per query
    (topavg_metric.hpp:65-92; negative k takes from the highest-scored end).
    The running sum is cumulative across the eval_at list, exactly like the
    reference's ``cur_left`` walk."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["topavg@%d" % k for k in self.eval_at]

    def per_query(self, y, s):
        n = len(y)
        sorted_idx = np.argsort(s, kind="stable")  # ascending by score
        out = []
        sum_label = 0.0
        cur_left = 0
        for k in self.eval_at:
            is_reverse = k < 0
            a = abs(k)
            cur_k = min(a, n)
            for j in range(cur_left, cur_k):
                rank_idx = n - j - 1 if is_reverse else j
                sum_label += float(y[sorted_idx[rank_idx]])
            out.append(sum_label / a)
            cur_left = cur_k
        return out


class TopavgdiffMetric(_QueryMetric):
    """Fork-custom: mean (top label - bottom label) over top-k positions
    (topavgdiff_metric.hpp:64-88); scores sorted descending."""

    def __init__(self, config):
        super().__init__(config)
        self.names = ["topavgdiff@%d" % k for k in self.eval_at]

    def per_query(self, y, s):
        n = len(y)
        sorted_idx = np.argsort(-s, kind="stable")  # descending
        out = []
        sum_label = 0.0
        cur_left = 0
        for k in self.eval_at:
            cur_k = min(int(k), n)
            for j in range(cur_left, cur_k):
                sum_label += float(y[sorted_idx[j]] - y[sorted_idx[n - j - 1]])
            out.append(sum_label / (cur_k * 2) if cur_k else 0.0)
            cur_left = cur_k
        return out


# ------------------------------------------------------------------ factory
_METRIC_ALIASES = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression": "l2",
    "l2_root": "rmse", "root_mean_squared_error": "rmse", "rmse": "rmse",
    "quantile": "quantile", "huber": "huber", "fair": "fair",
    "poisson": "poisson", "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "gamma_deviance": "gamma_deviance", "tweedie": "tweedie",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multi_error": "multi_error",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "kldiv": "kldiv", "kullback_leibler": "kldiv",
    "ndcg": "ndcg", "lambdarank": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "topavg": "topavg", "topavgdiff": "topavgdiff",
}

_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "xentropy": CrossEntropyMetric, "xentlambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric,
    "ndcg": NDCGMetric, "map": MAPMetric,
    "topavg": TopavgMetric, "topavgdiff": TopavgdiffMetric,
}


def default_metric_for_objective(objective: str) -> Optional[str]:
    """metric.cpp: empty metric -> objective's own metric."""
    mapping = {
        "regression": "l2", "regression_l1": "l1", "huber": "huber",
        "fair": "fair", "poisson": "poisson", "quantile": "quantile",
        "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
        "binary": "binary_logloss", "multiclass": "multi_logloss",
        "multiclassova": "multi_logloss", "xentropy": "xentropy",
        "xentlambda": "xentlambda", "lambdarank": "ndcg",
    }
    return mapping.get(objective)


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (metric.cpp:15-59). Returns None for 'none'."""
    base = name.split("@")[0].strip().lower()
    if base in ("none", "null", "custom", "na", ""):
        return None
    canon = _METRIC_ALIASES.get(base)
    if canon is None:
        raise LightGBMError("Unknown metric type name: %s" % name)
    cfg = config
    if "@" in name:
        ats = [int(v) for v in name.split("@")[1].split(":")]
        cfg = config.copy()
        cfg.eval_at = ats
    return _METRICS[canon](cfg)
