"""Text data parsing: CSV / TSV / LibSVM with format auto-detection.

Re-design of the reference parser (src/io/parser.cpp Parser::CreateParser,
include/LightGBM/dataset.h:249-273) — host-side, NumPy-vectorized rather than
char-by-char C++; the result feeds BinnedDataset.from_matrix.
"""
from __future__ import annotations

import io
import os
from typing import List, Optional, Tuple

import numpy as np

from ..log import Log, LightGBMError, check


def _detect_format(sample_lines: List[str]) -> Tuple[str, str]:
    """Returns (kind, delimiter); kind in {csv, tsv, libsvm}.

    Mirrors Parser::CreateParser's heuristic: lines whose non-first tokens all
    look like ``idx:value`` are LibSVM; otherwise the delimiter yielding the
    most numeric columns wins (parser.cpp:100-160).
    """
    line = next((l for l in sample_lines if l.strip()), "")
    for delim, kind in (("\t", "tsv"), (",", "csv"), (" ", "space")):
        if delim in line:
            tokens = line.strip().split(delim)
            rest = tokens[1:] if len(tokens) > 1 else tokens
            if rest and all(":" in t for t in rest if t):
                return "libsvm", delim
            try:
                float(tokens[0])
                return ("csv" if kind == "csv" else "tsv" if kind == "tsv"
                        else "csv"), delim
            except ValueError:
                return ("csv" if kind == "csv" else "tsv" if kind == "tsv"
                        else "csv"), delim
    return "csv", ","


def _resolve_label_idx(label_column: str, header_names: Optional[List[str]]) -> int:
    if not label_column:
        return 0
    if label_column.startswith("name:"):
        name = label_column[5:]
        if header_names is None or name not in header_names:
            raise LightGBMError("Could not find label column %s in data file "
                                "or data file doesn't contain header" % name)
        return header_names.index(name)
    return int(label_column)


def _try_parse_native(path: str, has_header: bool, label_column: str
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          Optional[List[str]]]]:
    """Use the C++ parser (native/src/text_parser.cpp) when available —
    the reference's C++ parsing stack behind its C API; Python fallback
    otherwise."""
    from ..native import parse_file_native
    label_idx = 0
    header_names = None
    if label_column.startswith("name:"):
        # need the header to resolve the index before the native call
        with open(path, "r") as fh:
            first = fh.readline().strip()
        delim = "\t" if "\t" in first else ("," if "," in first else " ")
        header_names = first.split(delim)
        label_idx = _resolve_label_idx(label_column, header_names)
    elif label_column:
        label_idx = int(label_column)
    try:
        res = parse_file_native(path, has_header, label_idx)
    except Exception as e:
        if type(e).__name__ == "LightGBMError":
            raise
        return None
    if res is None:
        return None
    X, y, tokens, fmt = res
    if tokens is not None and fmt == 0 and label_idx < len(tokens):
        tokens = [t for i, t in enumerate(tokens) if i != label_idx]
    return X, y, tokens


def parse_file(path: str, has_header: bool = False, label_column: str = "",
               max_lines: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray, Optional[List[str]]]:
    """Parse a data file into (features [N, F] float64, label [N], names).

    LibSVM feature indices are 0-based columns of the output matrix; the label
    is the configured column for delimited formats, the leading token for
    LibSVM.
    """
    check(os.path.exists(path), "Data file %s doesn't exist" % path)
    if max_lines is None:
        native = _try_parse_native(path, has_header, label_column)
        if native is not None:
            return native
    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    if max_lines is not None:
        lines = lines[:max_lines]
    lines = [l for l in lines if l.strip()]
    if not lines:
        raise LightGBMError("Data file %s is empty" % path)

    header_names: Optional[List[str]] = None
    kind, delim = _detect_format(lines[:10] if not has_header else lines[1:11])
    if has_header:
        header_names = lines[0].strip().split(delim)
        lines = lines[1:]

    if kind == "libsvm":
        labels = np.empty(len(lines), dtype=np.float64)
        rows: List[List[Tuple[int, float]]] = []
        max_idx = -1
        for i, line in enumerate(lines):
            tokens = line.strip().split(delim)
            labels[i] = float(tokens[0])
            row = []
            for t in tokens[1:]:
                if not t:
                    continue
                k, v = t.split(":", 1)
                k = int(k)
                row.append((k, float(v)))
                max_idx = max(max_idx, k)
            rows.append(row)
        X = np.zeros((len(lines), max_idx + 1), dtype=np.float64)
        for i, row in enumerate(rows):
            for k, v in row:
                X[i, k] = v
        return X, labels, header_names

    # delimited
    label_idx = _resolve_label_idx(label_column, header_names)
    X, labels = _parse_delimited_block(lines, delim, label_idx)
    if header_names is not None:
        header_names = [h for i, h in enumerate(header_names) if i != label_idx]
    return X, labels, header_names


def load_query_file(data_path: str) -> Optional[np.ndarray]:
    """Load ``<data>.query`` group sizes if present (metadata.cpp query file)."""
    qpath = data_path + ".query"
    if not os.path.exists(qpath):
        return None
    return np.loadtxt(qpath, dtype=np.int64).reshape(-1)


def load_weight_file(data_path: str) -> Optional[np.ndarray]:
    """Load ``<data>.weight`` per-row weights if present (metadata.cpp)."""
    wpath = data_path + ".weight"
    if not os.path.exists(wpath):
        return None
    return np.loadtxt(wpath, dtype=np.float64).reshape(-1)


def load_init_score_file(data_path: str) -> Optional[np.ndarray]:
    wpath = data_path + ".init"
    if not os.path.exists(wpath):
        return None
    return np.loadtxt(wpath, dtype=np.float64).reshape(-1)


def sniff_libsvm(path: str) -> bool:
    """True when the file looks like LibSVM (sparse k:v tokens) — the
    two_round chunked loader needs a global feature count, so such files
    take the one-shot parser instead."""
    if not os.path.exists(path):
        return False
    head = []
    with open(path, "r") as fh:
        for line in fh:
            if line.strip():
                head.append(line.rstrip("\n"))
            if len(head) >= 10:
                break
    if not head:
        return False
    kind, _ = _detect_format(head)
    return kind == "libsvm"


def _parse_delimited_block(lines: List[str], delim: str, label_idx: int):
    """genfromtxt a block of delimited lines -> (X, labels). Shared by the
    one-shot and chunked loaders so format fixes apply to both."""
    data = np.genfromtxt(io.StringIO("\n".join(lines)), delimiter=delim,
                         dtype=np.float64)
    if data.ndim == 1:
        data = data.reshape(len(lines), -1)
    labels = data[:, label_idx].copy()
    X = np.delete(data, label_idx, axis=1)
    return X, labels


def parse_file_chunks(path: str, has_header: bool = False,
                      label_column: str = "", chunk_rows: int = 262144):
    """Stream a delimited data file as (X [c, F] float64, label [c]) chunks.

    The two-round loading front end (dataset_loader.cpp:160-219's
    >memory-file path): nothing larger than one chunk of float64 is ever
    materialized. LibSVM needs a global feature count up front, so sparse
    files take the one-shot parser instead.
    """
    check(os.path.exists(path), "Data file %s doesn't exist" % path)
    with open(path, "r") as fh:
        head = []
        for line in fh:
            if line.strip():
                head.append(line)
            if len(head) >= 11:
                break
    if not head:
        raise LightGBMError("Data file %s is empty" % path)
    kind, delim = _detect_format([l.rstrip("\n") for l in
                                  (head[1:] if has_header else head)])
    if kind == "libsvm":
        raise LightGBMError(
            "two_round loading supports delimited files only; "
            "LibSVM input needs the one-shot parser")
    header_names: Optional[List[str]] = None
    with open(path, "r") as fh:
        if has_header:
            header_names = fh.readline().strip().split(delim)
        label_idx = _resolve_label_idx(label_column, header_names)
        names = None
        if header_names is not None:
            names = [h for i, h in enumerate(header_names)
                     if i != label_idx]
        buf: List[str] = []

        def flush():
            return _parse_delimited_block(buf, delim, label_idx)

        for line in fh:
            if not line.strip():
                continue
            buf.append(line.rstrip("\n"))
            if len(buf) >= chunk_rows:
                yield flush() + (names,)
                buf = []
        if buf:
            yield flush() + (names,)
