"""Binned Dataset + Metadata.

TPU-native re-design of the reference Dataset/Metadata/DatasetLoader
(include/LightGBM/dataset.h:36-627, src/io/dataset.cpp, src/io/metadata.cpp,
src/io/dataset_loader.cpp). Differences by design:

- Storage is a single dense ``[num_data, num_features] uint8`` bin matrix —
  the TPU histogram kernels want one contiguous HBM operand, not per-group
  Bin objects (dense_bin.hpp / sparse_bin.hpp). Sparse inputs are densified
  at bin time; ``max_bin <= 256`` keeps it one byte per value.
- EFB-style trivial-feature dropping happens here (used_feature mapping like
  dataset.h:613-618); full exclusive-feature bundling operates on the binned
  matrix as a host-side column merge.
- The "bin once, train many" artifact (dataset_loader.cpp:266 LoadFromBinFile)
  is an ``.npz`` cache of the bin matrix + mappers + metadata.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..log import Log, LightGBMError, check
from .binning import BinMapper, BinType, MissingType


class Metadata:
    """Labels / weights / query boundaries / init scores (dataset.h:36-245)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # [num_queries+1] int
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        arr = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        check(len(arr) == self.num_data or self.num_data == 0,
              "Length of label is not same with #data")
        self.label = arr
        self.num_data = len(arr)

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        arr = np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        check(len(arr) == self.num_data, "Length of weight is not same with #data")
        self.weight = arr

    def set_query(self, group: Optional[Sequence[int]]) -> None:
        """Accepts per-query sizes (LightGBM group format) -> boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        arr = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        boundaries = np.concatenate([[0], np.cumsum(arr)])
        check(boundaries[-1] == self.num_data,
              "Sum of query counts is not same with #data")
        self.query_boundaries = boundaries.astype(np.int32)

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.ascontiguousarray(init_score, dtype=np.float64).reshape(-1)
        self.init_score = arr

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


def _parse_categorical(categorical_feature, feature_names: List[str]) -> List[int]:
    out: List[int] = []
    if not categorical_feature:
        return out
    if isinstance(categorical_feature, str):
        categorical_feature = [c for c in categorical_feature.split(",") if c]
    for c in categorical_feature:
        if isinstance(c, str) and not c.lstrip("-").isdigit():
            if c in feature_names:
                out.append(feature_names.index(c))
            else:
                raise LightGBMError("Unknown categorical feature name %s" % c)
        else:
            out.append(int(c))
    return sorted(set(out))


class BinnedDataset:
    """The core training artifact: bin matrix + mappers + metadata.

    This is the analog of the reference ``Dataset`` (dataset.h:278-627); the
    user-facing lazy ``Dataset`` wrapper lives in ``lightgbm_tpu.basic``.
    """

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[BinMapper] = []          # per original feature
        self.used_features: List[int] = []              # original idx of stored cols
        self.X_binned: Optional[np.ndarray] = None      # [num_data, num_used] uint8
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_bin: int = 255
        self._device_cache: Dict[Any, Any] = {}

    # ------------------------------------------------------------ construct
    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    label: Optional[Sequence[float]] = None,
                    weight: Optional[Sequence[float]] = None,
                    group: Optional[Sequence[int]] = None,
                    init_score: Optional[Sequence[float]] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Optional[Union[str, List]] = None,
                    reference: Optional["BinnedDataset"] = None) -> "BinnedDataset":
        """Bin a raw [N, F] float matrix (DatasetLoader::CostructFromSampleData
        analog, dataset_loader.cpp:700-820)."""
        data = np.asarray(data)
        if data.ndim != 2:
            raise LightGBMError("Data should be 2-D, got shape %s" % (data.shape,))
        n, f = data.shape
        self = cls()
        self.num_data = n
        self.num_total_features = f
        self.max_bin = config.max_bin
        self.feature_names = feature_names or ["Column_%d" % i for i in range(f)]

        if reference is not None:
            # validation set: reuse the reference's bin mappers / layout
            check(f == reference.num_total_features,
                  "The number of features in data (%d) is not the same as it was "
                  "in training data (%d)" % (f, reference.num_total_features))
            self.bin_mappers = reference.bin_mappers
            self.used_features = reference.used_features
            self.feature_names = reference.feature_names
        else:
            cat_idx = set(_parse_categorical(
                categorical_feature if categorical_feature is not None
                else config.categorical_feature, self.feature_names))
            self.bin_mappers = []
            sample_cnt = min(n, config.bin_construct_sample_cnt)
            if sample_cnt < n:
                rng = np.random.RandomState(config.data_random_seed)
                sample_idx = np.sort(rng.choice(n, sample_cnt, replace=False))
            else:
                sample_idx = slice(None)
            data64 = np.asarray(data, dtype=np.float64)
            for j in range(f):
                col = data64[:, j][sample_idx]
                mapper = BinMapper()
                # the reference sampler stores only non-zero values; replicate
                # (NaNs fail both comparisons and are kept)
                nz = col[~((col >= -1e-35) & (col <= 1e-35))]
                mapper.find_bin(
                    nz, total_sample_cnt=len(col), max_bin=config.max_bin,
                    min_data_in_bin=config.min_data_in_bin,
                    min_split_data=config.min_data_in_leaf,
                    bin_type=BinType.CATEGORICAL if j in cat_idx else BinType.NUMERICAL,
                    use_missing=config.use_missing,
                    zero_as_missing=config.zero_as_missing)
                self.bin_mappers.append(mapper)
            self.used_features = [j for j in range(f)
                                  if not self.bin_mappers[j].is_trivial]
            if not self.used_features:
                Log.warning("There are no meaningful features, as all feature "
                            "values are constant.")

        cols = []
        data64 = np.asarray(data, dtype=np.float64)
        for j in self.used_features:
            cols.append(self.bin_mappers[j].values_to_bins(data64[:, j]).astype(np.uint8))
        self.X_binned = (np.stack(cols, axis=1) if cols
                         else np.zeros((n, 0), dtype=np.uint8))

        self.metadata = Metadata(n)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_query(group)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------ accessors
    @property
    def num_features(self) -> int:
        """Number of stored (non-trivial) features."""
        return len(self.used_features)

    def feature_num_bin(self, used_idx: int) -> int:
        return self.bin_mappers[self.used_features[used_idx]].num_bin

    def real_feature_index(self, used_idx: int) -> int:
        """Inner (stored) -> original feature index (dataset.h:613)."""
        return self.used_features[used_idx]

    def inner_feature_index(self, real_idx: int) -> int:
        try:
            return self.used_features.index(real_idx)
        except ValueError:
            return -1

    def max_num_bin(self) -> int:
        return max((self.feature_num_bin(i) for i in range(self.num_features)),
                   default=1)

    def get_feature_infos(self) -> List[str]:
        """Model-file ``feature_infos`` strings ([min:max] / categorical list)."""
        infos = []
        for j in range(self.num_total_features):
            m = self.bin_mappers[j] if j < len(self.bin_mappers) else None
            if m is None or m.is_trivial:
                infos.append("none")
            elif m.bin_type == BinType.CATEGORICAL:
                infos.append(":".join(str(c) for c in sorted(m.bin_2_categorical)))
            else:
                infos.append("[%s:%s]" % (repr(m.min_val), repr(m.max_val)))
        return infos

    # ------------------------------------------------------------ binary cache
    def save_binary(self, path: str) -> None:
        """Binary dataset cache (dataset.h:394 SaveBinaryFile analog)."""
        meta = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "used_features": self.used_features,
            "feature_names": self.feature_names,
            "max_bin": self.max_bin,
            "bin_mappers": [m.to_dict() for m in self.bin_mappers],
        }
        arrays: Dict[str, np.ndarray] = {"X_binned": self.X_binned}
        if self.metadata.label is not None:
            arrays["label"] = self.metadata.label
        if self.metadata.weight is not None:
            arrays["weight"] = self.metadata.weight
        if self.metadata.query_boundaries is not None:
            arrays["query_boundaries"] = self.metadata.query_boundaries
        if self.metadata.init_score is not None:
            arrays["init_score"] = self.metadata.init_score
        np.savez_compressed(path, __meta__=np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        with np.load(path) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            self = cls()
            self.num_data = meta["num_data"]
            self.num_total_features = meta["num_total_features"]
            self.used_features = list(meta["used_features"])
            self.feature_names = list(meta["feature_names"])
            self.max_bin = meta["max_bin"]
            self.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
            self.X_binned = z["X_binned"]
            self.metadata = Metadata(self.num_data)
            if "label" in z:
                self.metadata.set_label(z["label"])
            if "weight" in z:
                self.metadata.set_weight(z["weight"])
            if "query_boundaries" in z:
                qb = z["query_boundaries"]
                self.metadata.query_boundaries = qb.astype(np.int32)
            if "init_score" in z:
                self.metadata.set_init_score(z["init_score"])
        return self
