"""Binned Dataset + Metadata.

TPU-native re-design of the reference Dataset/Metadata/DatasetLoader
(include/LightGBM/dataset.h:36-627, src/io/dataset.cpp, src/io/metadata.cpp,
src/io/dataset_loader.cpp). Differences by design:

- Storage is a single dense ``[num_data, num_columns] uint8`` bin matrix —
  the TPU histogram kernels want one contiguous HBM operand, not per-group
  Bin objects (dense_bin.hpp / sparse_bin.hpp). ``max_bin <= 256`` keeps it
  one byte per value.
- Sparse inputs (scipy CSR/CSC) are binned column-by-column without ever
  materializing the dense float matrix, and EFB (io/bundle.py, the
  dataset.cpp:67-177 analog) packs mutually-exclusive sparse features into
  shared columns — so a 95%-sparse input stores ~#bundles columns, not F.
- Trivial-feature dropping keeps the used_feature mapping (dataset.h:613-618);
  ``col_features``/``col_offsets`` record the bundle layout
  (feature_group.h:35-50 bin_offsets_ analog).
- The "bin once, train many" artifact (dataset_loader.cpp:266 LoadFromBinFile)
  is an ``.npz`` cache of the bin matrix + mappers + metadata.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..log import Log, LightGBMError, check
from .binning import BinMapper, BinType, MissingType
from .bundle import bundle_offsets, find_bundles


def _is_sparse(data) -> bool:
    return hasattr(data, "tocsc") and hasattr(data, "nnz")


class Metadata:
    """Labels / weights / query boundaries / init scores (dataset.h:36-245)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # [num_queries+1] int
        self.init_score: Optional[np.ndarray] = None
        self._query_weights: Optional[np.ndarray] = None    # lazy cache

    def set_label(self, label: Sequence[float]) -> None:
        arr = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        check(len(arr) == self.num_data or self.num_data == 0,
              "Length of label is not same with #data")
        self.label = arr
        self.num_data = len(arr)

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        arr = np.ascontiguousarray(weight, dtype=np.float32).reshape(-1)
        check(len(arr) == self.num_data, "Length of weight is not same with #data")
        self.weight = arr
        self._query_weights = None

    def set_query(self, group: Optional[Sequence[int]]) -> None:
        """Accepts per-query sizes (LightGBM group format) -> boundaries."""
        if group is None:
            self.query_boundaries = None
            return
        arr = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        boundaries = np.concatenate([[0], np.cumsum(arr)])
        check(boundaries[-1] == self.num_data,
              "Sum of query counts is not same with #data")
        self.query_boundaries = boundaries.astype(np.int32)
        self._query_weights = None

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.ascontiguousarray(init_score, dtype=np.float64).reshape(-1)
        self.init_score = arr

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    @property
    def query_weights(self) -> Optional[np.ndarray]:
        """Per-query weight = MEAN of the query's document weights
        (metadata.cpp LoadQueryWeights); None unless BOTH per-row weights
        and query boundaries are set. Derived lazily so binary-cache loads
        (which assign fields directly) and any set order all work."""
        if self.weight is None or self.query_boundaries is None:
            return None
        if self._query_weights is None \
                or len(self._query_weights) != self.num_queries:
            qb = np.asarray(self.query_boundaries, np.int64)
            sums = np.add.reduceat(self.weight.astype(np.float64), qb[:-1])
            counts = np.maximum(np.diff(qb), 1)
            self._query_weights = (sums / counts).astype(np.float32)
        return self._query_weights


def _parse_categorical(categorical_feature, feature_names: List[str]) -> List[int]:
    out: List[int] = []
    if not categorical_feature:
        return out
    if isinstance(categorical_feature, str):
        categorical_feature = [c for c in categorical_feature.split(",") if c]
    for c in categorical_feature:
        if isinstance(c, str) and not c.lstrip("-").isdigit():
            if c in feature_names:
                out.append(feature_names.index(c))
            else:
                raise LightGBMError("Unknown categorical feature name %s" % c)
        else:
            out.append(int(c))
    return sorted(set(out))


class BinnedDataset:
    """The core training artifact: bin matrix + mappers + metadata.

    This is the analog of the reference ``Dataset`` (dataset.h:278-627); the
    user-facing lazy ``Dataset`` wrapper lives in ``lightgbm_tpu.basic``.
    """

    # overridden by stream.sampler.StreamedDataset, whose bin matrix lives
    # in host chunks (``chunks``) instead of ``X_binned``
    is_streamed = False

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.bin_mappers: List[BinMapper] = []          # per original feature
        self.used_features: List[int] = []              # original idx of used feats
        self.X_binned: Optional[np.ndarray] = None      # [num_data, num_cols] uint8
        # EFB layout (feature_group.h:35-50): stored column -> member original
        # features + their bin offsets; singletons have offsets == [0] (raw
        # encoding). With no bundling these mirror used_features 1:1.
        self.col_features: List[List[int]] = []
        self.col_offsets: List[List[int]] = []
        self.col_num_bin: List[int] = []
        # joint-coded pairs of small features (Dense4bitsBin analog):
        # stored value = bin_a * num_bin_b + bin_b
        self.col_packed: List[bool] = []
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_bin: int = 255
        # effective values of construction-time params that the binned
        # representation depends on (Dataset::ResetConfig's immutable set,
        # dataset.cpp:327-348); authoritative for post-construct
        # update-param checking even when the handle came from a .bin file
        self.bin_params: Dict[str, Any] = {}
        self._device_cache: Dict[Any, Any] = {}
        self._data_profile = None   # lazy obs.drift.DataProfile cache

    _BIN_PARAM_KEYS = ("max_bin", "bin_construct_sample_cnt",
                       "min_data_in_bin", "use_missing", "zero_as_missing",
                       "sparse_threshold")

    def _record_bin_params(self, config: Config) -> None:
        self.bin_params = {k: getattr(config, k)
                           for k in self._BIN_PARAM_KEYS
                           if hasattr(config, k)}

    # ------------------------------------------------------------ construct
    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    label: Optional[Sequence[float]] = None,
                    weight: Optional[Sequence[float]] = None,
                    group: Optional[Sequence[int]] = None,
                    init_score: Optional[Sequence[float]] = None,
                    feature_names: Optional[List[str]] = None,
                    categorical_feature: Optional[Union[str, List]] = None,
                    reference: Optional["BinnedDataset"] = None) -> "BinnedDataset":
        """Bin a raw [N, F] matrix — dense ndarray or scipy sparse CSR/CSC
        (DatasetLoader::CostructFromSampleData analog, dataset_loader.cpp:
        700-820; sparse path never densifies the float matrix)."""
        sparse = _is_sparse(data)
        if sparse:
            csc = data.tocsc()
            csc.sum_duplicates()
            n, f = csc.shape
            data64 = None
        else:
            csc = None
            data = np.asarray(data)
            if data.ndim != 2:
                raise LightGBMError("Data should be 2-D, got shape %s"
                                    % (data.shape,))
            n, f = data.shape
            data64 = np.asarray(data, dtype=np.float64)
        self = cls()
        self.num_data = n
        self.num_total_features = f
        self.max_bin = config.max_bin
        self._record_bin_params(config)
        self.feature_names = feature_names or ["Column_%d" % i for i in range(f)]

        def column_nonzeros(j):
            """(rows, float64 values) of column j's stored/non-zero entries."""
            if sparse:
                sl = slice(csc.indptr[j], csc.indptr[j + 1])
                return csc.indices[sl], np.asarray(csc.data[sl], np.float64)
            col = data64[:, j]
            rows = np.flatnonzero(~((col >= -1e-35) & (col <= 1e-35)))
            return rows, col[rows]

        if reference is not None:
            # validation set: reuse the reference's bin mappers / layout
            check(f == reference.num_total_features,
                  "The number of features in data (%d) is not the same as it was "
                  "in training data (%d)" % (f, reference.num_total_features))
            self.bin_mappers = reference.bin_mappers
            self.used_features = reference.used_features
            self.feature_names = reference.feature_names
            self.col_features = reference.col_features
            self.col_offsets = reference.col_offsets
            self.col_num_bin = reference.col_num_bin
            self.col_packed = reference.col_packed
        else:
            cat_idx = set(_parse_categorical(
                categorical_feature if categorical_feature is not None
                else config.categorical_feature, self.feature_names))
            sample_cnt = min(n, config.bin_construct_sample_cnt)
            if sample_cnt < n:
                rng = np.random.RandomState(config.data_random_seed)
                sample_rows = np.sort(rng.choice(n, sample_cnt, replace=False))
                # row id -> sample position (-1 = not sampled)
                sample_pos = np.full(n, -1, np.int64)
                sample_pos[sample_rows] = np.arange(sample_cnt)
            else:
                sample_rows = None
                sample_pos = None

            self.bin_mappers = []
            nz_sample: List[np.ndarray] = []   # per feature, sample positions
            for j in range(f):
                rows, vals = column_nonzeros(j)
                if sample_pos is not None:
                    pos = sample_pos[rows]
                    keep = pos >= 0
                    rows_s, vals_s = pos[keep], vals[keep]
                else:
                    rows_s, vals_s = rows, vals
                nz_sample.append(rows_s.astype(np.int64))
                mapper = BinMapper()
                # only non-zero values feed FindBin, like the reference's
                # sampler (NaNs fail both comparisons and are kept)
                mapper.find_bin(
                    vals_s, total_sample_cnt=sample_cnt,
                    max_bin=config.max_bin,
                    min_data_in_bin=config.min_data_in_bin,
                    min_split_data=config.min_data_in_leaf,
                    bin_type=(BinType.CATEGORICAL if j in cat_idx
                              else BinType.NUMERICAL),
                    use_missing=config.use_missing,
                    zero_as_missing=config.zero_as_missing)
                self.bin_mappers.append(mapper)
            self.used_features = [j for j in range(f)
                                  if not self.bin_mappers[j].is_trivial]
            if not self.used_features:
                Log.warning("There are no meaningful features, as all feature "
                            "values are constant.")

            # ---- EFB grouping (dataset.cpp:67-177 analog) ----------------
            if config.enable_bundle and len(self.used_features) > 1:
                bundles = find_bundles(
                    [nz_sample[j] for j in self.used_features], sample_cnt,
                    [self.bin_mappers[j].num_bin for j in self.used_features],
                    config.max_conflict_rate,
                    sparse_threshold=config.sparse_threshold)
                # bundle entries index into used_features; map back
                bundles = [[self.used_features[i] for i in b] for b in bundles]
            else:
                bundles = [[j] for j in self.used_features]
            self.col_features = bundles
            self.col_offsets = []
            self.col_num_bin = []
            num_bin_of = {j: self.bin_mappers[j].num_bin
                          for j in self.used_features}
            for b in bundles:
                offs, total = bundle_offsets(b, num_bin_of)
                self.col_offsets.append(offs)
                self.col_num_bin.append(total)
            n_bundled = sum(1 for b in bundles if len(b) > 1)
            if n_bundled:
                Log.info("EFB: %d features bundled into %d columns "
                         "(%d multi-feature bundles)",
                         len(self.used_features), len(bundles), n_bundled)
            self.col_packed = [False] * len(self.col_features)
            # mesh learners shard/pad the feature axis assuming an identity
            # feature->column layout; keep packing single-device-only (the
            # booster raises if a packed dataset reaches a mesh anyway)
            if config.enable_nbit_packing and \
                    config.tree_learner == "serial" and not config.mesh_shape:
                # tpu_bin_packing=nibble raises the joint-code cap to the
                # full byte (256) so every <=16-bin pair shares a column
                # regardless of the dataset's histogram width — the
                # Dense4bitsBin "two bins per byte" applied dataset-wide
                # (core/binpack.py). Other modes keep the conservative
                # cap (B never grows past the widest existing column).
                from ..core.binpack import resolve_bin_packing
                from ..core.partition import tpu_shaped_backend
                mode = resolve_bin_packing(
                    getattr(config, "tpu_bin_packing", "auto"),
                    streamed=False, tpu_shaped=tpu_shaped_backend(),
                    col_num_bin=self.col_num_bin)
                self._pack_small_pairs(
                    pair_cap=256 if mode == "nibble" else 0)

        # ---- build the stored uint8 columns ------------------------------
        def full_bin_column(j):
            m = self.bin_mappers[j]
            if sparse:
                zero_bin = int(m.values_to_bins(np.zeros(1))[0])
                colb = np.full(n, zero_bin, np.uint8)
                rows, vals = column_nonzeros(j)
                if len(rows):
                    colb[rows] = m.values_to_bins(vals).astype(np.uint8)
                return colb
            return m.values_to_bins(data64[:, j]).astype(np.uint8)

        cols = []
        for ci, (feats, offs) in enumerate(zip(self.col_features,
                                               self.col_offsets)):
            if self._col_is_packed(ci):
                ja, jb = feats
                nb_b = self.bin_mappers[jb].num_bin
                colb = (full_bin_column(ja).astype(np.uint16) * nb_b
                        + full_bin_column(jb)).astype(np.uint8)
            elif len(feats) == 1 and offs[0] == 0:
                colb = full_bin_column(feats[0])
            else:
                colb = np.zeros(n, np.uint8)
                for off, j in zip(offs, feats):
                    m = self.bin_mappers[j]
                    rows, vals = column_nonzeros(j)
                    bins = m.values_to_bins(vals)
                    sel = bins != m.default_bin
                    colb[rows[sel]] = (off + bins[sel]).astype(np.uint8)
            cols.append(colb)
        self.X_binned = (np.stack(cols, axis=1) if cols
                         else np.zeros((n, 0), dtype=np.uint8))

        self.metadata = Metadata(n)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_query(group)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------ sharded
    @classmethod
    def from_file_two_round(cls, path: str, config: Config,
                            chunk_rows: int = 262144,
                            reference: "BinnedDataset" = None,
                            feature_names=None, categorical_feature=None
                            ) -> "BinnedDataset":
        """Two-round streaming load (two_round / use_two_round_loading —
        dataset_loader.cpp:160-219's >memory path re-imagined host-side).

        Round 1 streams the file once, reservoir-sampling up to
        ``bin_construct_sample_cnt`` rows (bin mappers and the EFB/packing
        layout come from the sample, exactly like the reference's sampled
        bin finding) and collecting labels. Round 2 streams again, binning
        each chunk against that layout into the preallocated uint8 matrix.
        Peak float64 footprint is one chunk, not the whole file.
        """
        from .parser import parse_file_chunks
        from ..log import check as _check

        sample_cnt = int(config.bin_construct_sample_cnt)
        rng = np.random.RandomState(config.data_random_seed)
        sample_rows: list = []
        labels: list = []
        names = None
        first_row = None
        n_total = 0
        for Xc, yc, chunk_names in parse_file_chunks(
                path, has_header=config.header,
                label_column=config.label_column, chunk_rows=chunk_rows):
            labels.append(yc)
            names = names or chunk_names
            if first_row is None:
                first_row = Xc[:1].copy()
            if reference is None:
                # Algorithm R, vectorized per chunk: the fill phase keeps
                # original order (sample == full data when N <= sample_cnt);
                # afterwards each row i draws j ~ U[0, n_total+i] and
                # replaces slot j when j < sample_cnt. Rows are COPIED so
                # the parent float64 chunk can be freed — holding views
                # would keep every chunk alive, defeating the streaming
                # point.
                c = Xc.shape[0]
                fill = max(0, min(sample_cnt - n_total, c))
                for i in range(fill):
                    sample_rows.append(Xc[i].copy())
                if fill < c:
                    draws = (rng.random_sample(c - fill)
                             * (n_total + np.arange(fill, c) + 1)
                             ).astype(np.int64)
                    hits = np.nonzero(draws < sample_cnt)[0]
                    for i in hits:
                        sample_rows[draws[i]] = Xc[fill + i].copy()
            n_total += Xc.shape[0]
        _check(n_total > 0, "Data file %s is empty" % path)
        label = np.concatenate(labels)

        proto = reference
        if proto is None:
            proto = cls.from_matrix(
                np.asarray(sample_rows), config,
                feature_names=feature_names or names,
                categorical_feature=categorical_feature)

        xb = np.empty((n_total, proto.X_binned.shape[1]), np.uint8)
        row = 0
        for Xc, _yc, _names in parse_file_chunks(
                path, has_header=config.header,
                label_column=config.label_column, chunk_rows=chunk_rows):
            bc = cls.from_matrix(Xc, config, reference=proto)
            xb[row:row + Xc.shape[0]] = bc.X_binned
            row += Xc.shape[0]

        if reference is not None:
            # a validation set binned against the training layout: clone the
            # layout through the reference-alignment path (no sampling run)
            ds = cls.from_matrix(first_row, config, reference=reference)
        else:
            ds = proto
        ds.X_binned = xb
        ds.num_data = n_total
        ds.metadata = Metadata(n_total)
        ds.metadata.set_label(label)
        return ds

    @classmethod
    def from_sharded(cls, local_data, config: Config, comm=None,
                     label: Optional[Sequence[float]] = None,
                     weight: Optional[Sequence[float]] = None,
                     init_score: Optional[Sequence[float]] = None,
                     feature_names: Optional[List[str]] = None,
                     categorical_feature: Optional[Union[str, List]] = None
                     ) -> "BinnedDataset":
        """Distributed ingest: every host binds only its own row shard.

        The reference's distributed loading (dataset_loader.cpp:469-495 row
        partition, :548-640 feature-sharded bin finding + Allgather of
        BinMappers) re-designed for exact parity: each host samples its local
        rows, the per-feature samples are allgathered (bounded by
        bin_construct_sample_cnt), and every host runs FindBin on the merged
        sample — so bin boundaries are identical on all hosts (and identical
        to a single-host run over the union sample), without any host ever
        holding the full matrix.

        ``comm`` implements ``allgather(obj) -> list`` over hosts (see
        lightgbm_tpu.parallel.network; tests use a loopback). The returned
        dataset covers only the local rows; training on a 'data'-axis mesh
        then shards naturally.
        """
        local_data = np.asarray(local_data)
        check(local_data.ndim == 2, "local shard must be 2-D")
        n_local, f = local_data.shape
        if comm is None:
            from ..parallel import network as _net
            comm = _net.active_comm()
            if comm is None:
                raise LightGBMError(
                    "from_sharded needs a comm (or a transport registered "
                    "via LGBM_NetworkInitWithFunctions)")
        sizes = comm.allgather(n_local)
        total_n = int(sum(sizes))

        # per-host row sample, proportional share of the global sample budget
        budget = max(1, int(config.bin_construct_sample_cnt
                            * (n_local / max(total_n, 1))))
        sample_cnt = min(n_local, budget)
        if sample_cnt < n_local:
            rng = np.random.RandomState(config.data_random_seed + 1
                                        + len(sizes))
            rows = np.sort(rng.choice(n_local, sample_cnt, replace=False))
            sample = np.asarray(local_data[rows], np.float64)
        else:
            sample = np.asarray(local_data, np.float64)

        # merge per-feature non-zero sampled values across hosts (the
        # Allgather at dataset_loader.cpp:615-640, but of raw sample values
        # so FindBin sees the union sample -> identical mappers everywhere)
        local_nz = []
        for j in range(f):
            col = sample[:, j]
            local_nz.append(col[~((col >= -1e-35) & (col <= 1e-35))])
        gathered = comm.allgather((len(sample), local_nz))
        merged_cnt = int(sum(c for c, _ in gathered))
        merged = [np.concatenate([g[1][j] for g in gathered])
                  for j in range(f)]

        names = feature_names or ["Column_%d" % i for i in range(f)]
        cat_idx = set(_parse_categorical(
            categorical_feature if categorical_feature is not None
            else config.categorical_feature, names))
        mappers: List[BinMapper] = []
        for j in range(f):
            m = BinMapper()
            m.find_bin(merged[j], total_sample_cnt=merged_cnt,
                       max_bin=config.max_bin,
                       min_data_in_bin=config.min_data_in_bin,
                       min_split_data=config.min_data_in_leaf,
                       bin_type=(BinType.CATEGORICAL if j in cat_idx
                                 else BinType.NUMERICAL),
                       use_missing=config.use_missing,
                       zero_as_missing=config.zero_as_missing)
            mappers.append(m)

        self = cls()
        self.num_data = n_local
        self.num_total_features = f
        self.max_bin = config.max_bin
        self._record_bin_params(config)
        self.feature_names = names
        self.bin_mappers = mappers
        self.used_features = [j for j in range(f) if not mappers[j].is_trivial]
        # bundling needs a global conflict view; keep the identity layout in
        # sharded mode (EFB is a single-host/mesh-local optimization for now)
        self.col_features = [[j] for j in self.used_features]
        self.col_offsets = [[0] for _ in self.used_features]
        self.col_num_bin = [mappers[j].num_bin for j in self.used_features]
        self.col_packed = [False] * len(self.col_features)

        data64 = np.asarray(local_data, np.float64)
        cols = [mappers[j].values_to_bins(data64[:, j]).astype(np.uint8)
                for j in self.used_features]
        self.X_binned = (np.stack(cols, axis=1) if cols
                         else np.zeros((n_local, 0), np.uint8))
        self.metadata = Metadata(n_local)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------ accessors
    @property
    def num_features(self) -> int:
        """Number of stored (non-trivial) features."""
        return len(self.used_features)

    def feature_num_bin(self, used_idx: int) -> int:
        return self.bin_mappers[self.used_features[used_idx]].num_bin

    def real_feature_index(self, used_idx: int) -> int:
        """Inner (stored) -> original feature index (dataset.h:613)."""
        return self.used_features[used_idx]

    def inner_feature_index(self, real_idx: int) -> int:
        try:
            return self.used_features.index(real_idx)
        except ValueError:
            return -1

    def max_num_bin(self) -> int:
        return max((self.feature_num_bin(i) for i in range(self.num_features)),
                   default=1)

    def data_profile(self):
        """Per-feature bin-occupancy profile of the training data
        (obs.drift.DataProfile), computed lazily from the already-binned
        matrix — one bincount pass per feature — and cached. Persisted in
        checkpoint snapshot meta and the serving ModelBundle as the
        reference distribution for train/serve drift scoring."""
        if self._data_profile is None:
            from ..obs.drift import DataProfile
            self._data_profile = DataProfile.from_binned_dataset(self)
        return self._data_profile

    # ------------------------------------------------------------ EFB layout
    @property
    def num_columns(self) -> int:
        """Stored bin-matrix columns (== num_features when nothing bundled)."""
        return len(self.col_features)

    def max_col_bins(self) -> int:
        """Largest encoded bin count of any stored column (histogram B)."""
        return max(self.col_num_bin, default=1)

    @property
    def has_bundles(self) -> bool:
        return any(len(b) > 1 and not self._col_is_packed(ci)
                   for ci, b in enumerate(self.col_features))

    def _col_is_packed(self, ci: int) -> bool:
        return ci < len(self.col_packed) and self.col_packed[ci]

    @property
    def has_packed(self) -> bool:
        return any(self.col_packed)

    def _pack_small_pairs(self, pair_cap: int = 0) -> None:
        """Joint-code pairs of small singleton numerical features into one
        stored column (value = bin_a * num_bin_b + bin_b) — the
        Dense4bitsBin idea (dense_nbits_bin.hpp:38-82) re-shaped for the
        [N, C] uint8 matrix: instead of nibble-shifting inside a bin
        object, two features share a column whose joint histogram is
        marginalized per feature at split-search time. With ``pair_cap``
        0 a pair is only formed when it fits the dataset's existing
        histogram width, so B never grows; tpu_bin_packing=nibble passes
        256 (the uint8 code space) to force dataset-wide pairing — C
        halves for small-bin features at the price of a wider B."""
        b_max = int(pair_cap) or max(self.col_num_bin, default=0)
        cand = [ci for ci in range(len(self.col_features))
                if len(self.col_features[ci]) == 1
                and not self.col_packed[ci]
                and self.bin_mappers[self.col_features[ci][0]].bin_type
                != BinType.CATEGORICAL
                and self.bin_mappers[self.col_features[ci][0]].num_bin <= 16]
        # widest first, paired greedily while the product fits b_max
        cand.sort(key=lambda ci:
                  -self.bin_mappers[self.col_features[ci][0]].num_bin)
        drop = set()
        pairs = 0
        while len(cand) >= 2:
            ca = cand.pop(0)
            cb = cand.pop()          # widest with narrowest
            ja = self.col_features[ca][0]
            jb = self.col_features[cb][0]
            nb_a = self.bin_mappers[ja].num_bin
            nb_b = self.bin_mappers[jb].num_bin
            if nb_a * nb_b > b_max:
                # the widest can pair with no one (cb is the narrowest);
                # drop it and keep pairing the rest
                cand.append(cb)
                continue
            self.col_features[ca] = [ja, jb]
            self.col_offsets[ca] = [0, 0]
            self.col_num_bin[ca] = nb_a * nb_b
            self.col_packed[ca] = True
            drop.add(cb)
            pairs += 1
        if drop:
            keep = [i for i in range(len(self.col_features))
                    if i not in drop]
            self.col_features = [self.col_features[i] for i in keep]
            self.col_offsets = [self.col_offsets[i] for i in keep]
            self.col_num_bin = [self.col_num_bin[i] for i in keep]
            self.col_packed = [self.col_packed[i] for i in keep]
            Log.info("nbit packing: %d small-feature pairs share a column "
                     "(%d stored columns)", pairs, len(self.col_features))

    def feature_layout(self):
        """Per used-feature (inner index) storage arrays:
        (feat_col, feat_offset, feat_bundled, pack_div, pack_mod,
        pack_partner) — where each feature lives in the stored matrix, at
        which bin offset (EFB), and how to extract it from a joint-coded
        pair column (packing): feature bin = (value // div) % mod, with
        `partner` = the other feature's bin count (marginalization width).
        div/mod are 1/0 for unpacked features."""
        fcount = self.num_features
        feat_col = np.zeros(fcount, np.int32)
        feat_offset = np.zeros(fcount, np.int32)
        feat_bundled = np.zeros(fcount, bool)
        pack_div = np.ones(fcount, np.int32)
        pack_mod = np.zeros(fcount, np.int32)
        pack_partner = np.ones(fcount, np.int32)
        inner = {j: i for i, j in enumerate(self.used_features)}
        for ci, (feats, offs) in enumerate(zip(self.col_features,
                                               self.col_offsets)):
            if self._col_is_packed(ci):
                ja, jb = feats
                nb_a = self.bin_mappers[ja].num_bin
                nb_b = self.bin_mappers[jb].num_bin
                ia, ib = inner[ja], inner[jb]
                feat_col[ia] = feat_col[ib] = ci
                pack_div[ia], pack_mod[ia] = nb_b, nb_a
                pack_partner[ia] = nb_b
                pack_div[ib], pack_mod[ib] = 1, nb_b
                pack_partner[ib] = nb_a
                continue
            for off, j in zip(offs, feats):
                i = inner[j]
                feat_col[i] = ci
                feat_offset[i] = off
                feat_bundled[i] = len(feats) > 1
        return (feat_col, feat_offset, feat_bundled, pack_div, pack_mod,
                pack_partner)

    def get_feature_infos(self) -> List[str]:
        """Model-file ``feature_infos`` strings ([min:max] / categorical list)."""
        infos = []
        for j in range(self.num_total_features):
            m = self.bin_mappers[j] if j < len(self.bin_mappers) else None
            if m is None or m.is_trivial:
                infos.append("none")
            elif m.bin_type == BinType.CATEGORICAL:
                infos.append(":".join(str(c) for c in sorted(m.bin_2_categorical)))
            else:
                infos.append("[%s:%s]" % (repr(m.min_val), repr(m.max_val)))
        return infos

    # ------------------------------------------------------------ binary cache
    def save_binary(self, path: str) -> None:
        """Binary dataset cache (dataset.h:394 SaveBinaryFile analog)."""
        meta = {
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "used_features": self.used_features,
            "feature_names": self.feature_names,
            "max_bin": self.max_bin,
            "bin_params": self.bin_params,
            "bin_mappers": [m.to_dict() for m in self.bin_mappers],
            "col_features": self.col_features,
            "col_offsets": self.col_offsets,
            "col_num_bin": self.col_num_bin,
            "col_packed": self.col_packed,
        }
        arrays: Dict[str, np.ndarray] = {"X_binned": self.X_binned}
        if self.metadata.label is not None:
            arrays["label"] = self.metadata.label
        if self.metadata.weight is not None:
            arrays["weight"] = self.metadata.weight
        if self.metadata.query_boundaries is not None:
            arrays["query_boundaries"] = self.metadata.query_boundaries
        if self.metadata.init_score is not None:
            arrays["init_score"] = self.metadata.init_score
        # write through a file handle: savez appends ".npz" to bare paths,
        # but the caller's filename (e.g. via LGBM_DatasetSaveBinary) is a
        # contract
        with open(path, "wb") as fh:
            np.savez_compressed(fh, __meta__=np.frombuffer(
                json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    @classmethod
    def load_binary(cls, path: str) -> "BinnedDataset":
        with np.load(path) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            self = cls()
            self.num_data = meta["num_data"]
            self.num_total_features = meta["num_total_features"]
            self.used_features = list(meta["used_features"])
            self.feature_names = list(meta["feature_names"])
            self.max_bin = meta["max_bin"]
            self.bin_params = dict(meta.get("bin_params", {}))
            self.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
            self.col_features = [list(b) for b in meta.get(
                "col_features", [[j] for j in self.used_features])]
            self.col_offsets = [list(o) for o in meta.get(
                "col_offsets", [[0]] * len(self.col_features))]
            self.col_num_bin = list(meta.get("col_num_bin", []))
            if not self.col_num_bin:
                self.col_num_bin = [self.bin_mappers[b[0]].num_bin
                                    for b in self.col_features]
            self.col_packed = list(meta.get(
                "col_packed", [False] * len(self.col_features)))
            self.X_binned = z["X_binned"]
            self.metadata = Metadata(self.num_data)
            if "label" in z:
                self.metadata.set_label(z["label"])
            if "weight" in z:
                self.metadata.set_weight(z["weight"])
            if "query_boundaries" in z:
                qb = z["query_boundaries"]
                self.metadata.query_boundaries = qb.astype(np.int32)
            if "init_score" in z:
                self.metadata.set_init_score(z["init_score"])
        return self
