"""LightGBM-compatible model text format.

Re-implementation of the reference model serialization
(src/boosting/gbdt_model_text.cpp:244-341 SaveModelToString, :343+
LoadModelFromString; src/io/tree.cpp:207-238 Tree::ToString, :300+ Tree(str))
so models trained here can be read by reference tooling and vice versa.

Layout (kModelVersion "v2", gbdt_model_text.cpp:13):

    tree
    version=v2
    num_class=1
    num_tree_per_iteration=1
    label_index=0
    max_feature_idx=27
    objective=binary sigmoid:1
    feature_names=...
    feature_infos=...
    tree_sizes=...
    <blank>
    Tree=0
    num_leaves=31
    num_cat=0
    split_feature=...
    ...
    shrinkage=0.1
    <blank>
    end of trees
    feature importances / parameters blocks

decision_type is bit-packed (tree.h:14-15,183-207): bit0 categorical,
bit1 default_left, bits2-3 missing type.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..log import LightGBMError

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2

_MISSING_NAMES = {0: "None", 1: "Zero", 2: "NaN"}


def _fmt(x: float) -> str:
    """Shortest round-trip float formatting (like C++ max_digits10 output)."""
    if x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(float(x))


def _join(arr, fmt=str) -> str:
    return " ".join(fmt(v) for v in arr)


def tree_to_string(ht, tree_index: int) -> str:
    """One ``Tree=i`` block (tree.cpp Tree::ToString:207-238)."""
    nn = max(ht.num_leaves_actual - 1, 0)
    nl = ht.num_leaves_actual
    lines = ["Tree=%d" % tree_index,
             "num_leaves=%d" % nl]
    # categorical bookkeeping: nodes with is_categorical store a running
    # index into the cat_threshold bitset words (tree.cpp cat_boundaries_)
    cat_nodes = [i for i in range(nn) if ht.is_categorical[i]]
    num_cat = len(cat_nodes)
    lines.append("num_cat=%d" % num_cat)
    if nn > 0:
        decision_type = np.zeros(nn, np.int32)
        thresholds = []
        cat_boundaries = [0]
        cat_threshold: List[int] = []
        cat_idx = 0
        for i in range(nn):
            dt = 0
            if ht.is_categorical[i]:
                dt |= K_CATEGORICAL_MASK
            if ht.default_left[i]:
                dt |= K_DEFAULT_LEFT_MASK
            dt |= (int(ht.missing_type[i]) & 3) << 2
            decision_type[i] = dt
            if ht.is_categorical[i]:
                # threshold = index into cat_boundaries (tree.h:276-291)
                thresholds.append(str(cat_idx))
                words = [int(w) for w in ht.cat_bitset[i]]
                while len(words) > 1 and words[-1] == 0:
                    words.pop()
                cat_threshold.extend(words)
                cat_boundaries.append(len(cat_threshold))
                cat_idx += 1
            else:
                thresholds.append(_fmt(float(ht.threshold[i])))
        lines.append("split_feature=" + _join(ht.split_feature[:nn]))
        lines.append("split_gain=" + _join(ht.split_gain[:nn], _fmt))
        lines.append("threshold=" + " ".join(thresholds))
        lines.append("decision_type=" + _join(decision_type))
        lines.append("left_child=" + _join(ht.left_child[:nn]))
        lines.append("right_child=" + _join(ht.right_child[:nn]))
        lines.append("leaf_value=" + _join(ht.leaf_value[:nl], _fmt))
        lines.append("leaf_count=" + _join(ht.leaf_count[:nl]))
        lines.append("internal_value=" + _join(ht.internal_value[:nn], _fmt))
        lines.append("internal_count=" + _join(ht.internal_count[:nn]))
        if num_cat > 0:
            lines.append("cat_boundaries=" + _join(cat_boundaries))
            lines.append("cat_threshold=" + _join(cat_threshold))
    else:
        lines.append("split_feature=")
        lines.append("split_gain=")
        lines.append("threshold=")
        lines.append("decision_type=")
        lines.append("left_child=")
        lines.append("right_child=")
        lines.append("leaf_value=" + _fmt(float(ht.leaf_value[0])))
        lines.append("leaf_count=" + str(int(ht.leaf_count[0])))
        lines.append("internal_value=")
        lines.append("internal_count=")
    lines.append("shrinkage=" + _fmt(ht.shrinkage))
    return "\n".join(lines) + "\n\n"


def objective_to_string(objective, config) -> str:
    """ObjectiveFunction::ToString analogs (each objective's ToString)."""
    if objective is None:
        return "custom"
    name = objective.name
    if name == "binary":
        return "binary sigmoid:%s" % _fmt(config.sigmoid)
    if name == "multiclass":
        return "multiclass num_class:%d" % config.num_class
    if name == "multiclassova":
        return "multiclassova num_class:%d sigmoid:%s" % (
            config.num_class, _fmt(config.sigmoid))
    if name == "regression" and config.reg_sqrt:
        return "regression sqrt"
    if name == "quantile":
        return "quantile alpha:%s" % _fmt(config.alpha)
    if name == "huber":
        return "huber"
    return name


def model_to_string(booster, feature_names: List[str],
                    feature_infos: List[str],
                    num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    parameters: str = "") -> str:
    """GBDT::SaveModelToString (gbdt_model_text.cpp:244-341)."""
    k = booster.num_tree_per_iteration
    total_iter = len(booster.models) // max(k, 1)
    start_iteration = min(max(start_iteration, 0), total_iter)
    if num_iteration is not None and num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * k,
                       len(booster.models))
    else:
        num_used = len(booster.models)
    start_model = start_iteration * k

    out = ["tree", "version=v2",
           "num_class=%d" % booster.num_class,
           "num_tree_per_iteration=%d" % k,
           "label_index=0",
           "max_feature_idx=%d" % (len(feature_names) - 1)]
    out.append("objective=%s" %
               objective_to_string(booster.objective, booster.config))
    if booster.average_output:
        out.append("average_output")
    out.append("feature_names=" + " ".join(feature_names))
    out.append("feature_infos=" + " ".join(feature_infos))

    tree_strs = []
    for idx, i in enumerate(range(start_model, num_used)):
        tree_strs.append(tree_to_string(booster.models[i], idx))
    out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    out.append("")
    body = "\n".join(out) + "\n" + "".join(tree_strs) + "end of trees\n"

    # feature importances (gbdt_model_text.cpp:303-319)
    imp = booster.feature_importance("split")
    pairs = sorted(((imp[i], feature_names[i]) for i in range(len(imp))
                    if i < len(feature_names) and imp[i] > 0), reverse=True)
    body += "\nfeature importances:\n"
    for v, name in pairs:
        body += "%s=%d\n" % (name, int(v))
    if parameters:
        body += "\nparameters:\n" + parameters + "\nend of parameters\n"
    return body


class LoadedTree:
    """Parsed tree block, shaped like boosting.gbdt.HostTree for prediction."""

    def __init__(self, kv: Dict[str, str]):
        nl = int(kv["num_leaves"])
        num_cat = int(kv.get("num_cat", "0"))
        self.num_leaves = nl
        self.num_leaves_actual = nl
        nn = max(nl - 1, 0)

        def arr(key, dtype, n, default=0):
            s = kv.get(key, "").strip()
            if not s:
                return np.full(n, default, dtype)
            vals = np.array(s.split(" "), dtype=np.float64)
            return vals.astype(dtype)

        self.split_feature = arr("split_feature", np.int32, nn)
        self.split_gain = arr("split_gain", np.float32, nn)
        self.left_child = arr("left_child", np.int32, nn, -1)
        self.right_child = arr("right_child", np.int32, nn, -1)
        if nl > 1:
            self.leaf_value = arr("leaf_value", np.float64, nl)
            self.leaf_count = arr("leaf_count", np.int64, nl)
        else:
            self.leaf_value = np.array(
                [float(kv.get("leaf_value", "0") or 0)], np.float64)
            self.leaf_count = np.array(
                [int(float(kv.get("leaf_count", "0") or 0))], np.int64)
        self.internal_value = arr("internal_value", np.float64, nn)
        self.internal_count = arr("internal_count", np.int64, nn)
        self.leaf_weight = np.zeros(nl, np.float64)
        self.internal_weight = np.zeros(nn, np.float64)
        dt = arr("decision_type", np.int32, nn)
        self.is_categorical = (dt & K_CATEGORICAL_MASK) > 0
        self.default_left = (dt & K_DEFAULT_LEFT_MASK) > 0
        self.missing_type = (dt >> 2) & 3
        self.shrinkage = float(kv.get("shrinkage", "1"))

        thr_tokens = kv.get("threshold", "").split() if nn else []
        self.threshold = np.zeros(nn, np.float64)
        self.threshold_bin = np.zeros(nn, np.int32)
        cat_words = 8
        if num_cat > 0:
            boundaries = arr("cat_boundaries", np.int64, num_cat + 1)
            cat_words = max(cat_words,
                            int(np.max(np.diff(boundaries), initial=0)))
        self.cat_bitset = np.zeros((max(nn, 1), cat_words), np.uint32)
        if num_cat > 0:
            words = arr("cat_threshold", np.int64, 0) \
                if not kv.get("cat_threshold", "").strip() else \
                np.array(kv["cat_threshold"].split(), np.int64)
            for i in range(nn):
                if self.is_categorical[i]:
                    ci = int(float(thr_tokens[i]))
                    w = words[boundaries[ci]:boundaries[ci + 1]]
                    self.cat_bitset[i, :len(w)] = w.astype(np.uint32)
        for i in range(nn):
            if not self.is_categorical[i]:
                self.threshold[i] = float(thr_tokens[i])

        # reconstruct split_leaf for replay prediction: the leaf slot of node
        # t is the terminal of its left-child spine (Tree::Split keeps the
        # split leaf's index on the left child, tree.cpp:49-67)
        self.split_leaf = np.full(nn, -1, np.int32)
        for t in range(nn):
            node = t
            while True:
                child = self.left_child[node]
                if child < 0:
                    self.split_leaf[t] = ~child
                    break
                node = child

    @property
    def num_nodes(self) -> int:
        return self.num_leaves_actual - 1

    def predict_table(self, max_nodes: int, max_leaves: int, cat_words=None):
        from ..core import tree as tree_mod
        return tree_mod.pack_predict_table(self, max_nodes, max_leaves,
                                           cat_words)


def parse_model_string(model_str: str) -> Dict:
    """GBDT::LoadModelFromString (gbdt_model_text.cpp:343+)."""
    if "tree" not in model_str[:200]:
        raise LightGBMError("Model format error: no 'tree' header")
    head, _, rest = model_str.partition("Tree=")
    kv: Dict[str, str] = {}
    for line in head.splitlines():
        line = line.strip()
        if "=" in line:
            k, _, v = line.partition("=")
            kv[k] = v
    trees: List[LoadedTree] = []
    body = "Tree=" + rest if rest else ""
    for block in body.split("Tree=")[1:]:
        block = block.split("end of trees")[0]
        tkv: Dict[str, str] = {}
        for line in block.splitlines():
            if "=" in line:
                k, _, v = line.partition("=")
                tkv[k.strip()] = v
        trees.append(LoadedTree(tkv))

    objective_str = kv.get("objective", "")
    result = {
        "num_class": int(kv.get("num_class", "1")),
        "num_tree_per_iteration": int(kv.get("num_tree_per_iteration", "1")),
        "max_feature_idx": int(kv.get("max_feature_idx", "0")),
        "label_index": int(kv.get("label_index", "0")),
        "objective": objective_str,
        "average_output": "average_output" in head,
        "feature_names": kv.get("feature_names", "").split(),
        "feature_infos": kv.get("feature_infos", "").split(),
        "trees": trees,
    }
    # trailing parameters block (loaded_parameter_, :492-497)
    if "\nparameters:" in model_str:
        params_part = model_str.split("\nparameters:", 1)[1]
        params_part = params_part.split("end of parameters")[0]
        result["parameters"] = params_part.strip()
    return result


def parse_model_file(path: str) -> Dict:
    """Load + parse a model-text file (GBDT::LoadModelFromFile analog).

    The serving registry uses this as its fail-fast pass: a malformed file
    raises here, before any Booster/device state is built."""
    with open(path, "r") as fh:
        return parse_model_string(fh.read())


def model_to_json(booster, feature_names: List[str],
                  feature_infos: List[str],
                  num_iteration: Optional[int] = None) -> str:
    """GBDT::DumpModel JSON (gbdt_model_text.cpp:15-58, tree.cpp:242-301)."""
    k = booster.num_tree_per_iteration
    num_used = len(booster.models)
    if num_iteration is not None and num_iteration > 0:
        num_used = min(num_iteration * k, num_used)

    def node_to_json(ht, index):
        if index < 0:  # leaf
            li = ~index
            return {
                "leaf_index": int(li),
                "leaf_value": float(ht.leaf_value[li]),
                "leaf_count": int(ht.leaf_count[li]),
            }
        d = {
            "split_index": int(index),
            "split_feature": int(ht.split_feature[index]),
            "split_gain": float(ht.split_gain[index]),
            "threshold": float(ht.threshold[index]),
            "decision_type": "==" if ht.is_categorical[index] else "<=",
            "default_left": bool(ht.default_left[index]),
            "missing_type": _MISSING_NAMES.get(int(ht.missing_type[index]),
                                               "None"),
            "internal_value": float(ht.internal_value[index]),
            "internal_count": int(ht.internal_count[index]),
            "left_child": node_to_json(ht, int(ht.left_child[index])),
            "right_child": node_to_json(ht, int(ht.right_child[index])),
        }
        return d

    trees = []
    for i in range(num_used):
        ht = booster.models[i]
        t = {"tree_index": i,
             "num_leaves": int(ht.num_leaves_actual),
             "shrinkage": float(ht.shrinkage)}
        if ht.num_leaves_actual <= 1:
            t["tree_structure"] = {"leaf_value": float(ht.leaf_value[0])}
        else:
            t["tree_structure"] = node_to_json(ht, 0)
        trees.append(t)

    return json.dumps({
        "name": "tree",
        "version": "v2",
        "num_class": booster.num_class,
        "num_tree_per_iteration": k,
        "label_index": 0,
        "max_feature_idx": len(feature_names) - 1,
        "objective": objective_to_string(booster.objective, booster.config),
        "average_output": booster.average_output,
        "feature_names": feature_names,
        "feature_infos": feature_infos,
        "tree_info": trees,
    }, indent=2)


def model_to_cpp(parsed: Dict) -> str:
    """Standalone C++ scorer from a parsed model (the ModelToIfElse /
    convert_model export, gbdt_model_text.cpp:60-243): one nested if/else
    function per tree plus PredictRaw / Predict entry points with the
    objective's link function applied."""
    trees: List = parsed["trees"]
    k = parsed["num_tree_per_iteration"]
    obj = parsed.get("objective", "").split()
    obj_name = obj[0] if obj else ""
    sigmoid = 1.0
    for tok in obj[1:]:
        if tok.startswith("sigmoid:"):
            sigmoid = float(tok.split(":", 1)[1])

    lines: List[str] = [
        "// generated by lightgbm_tpu task=convert_model",
        "#include <cmath>",
        "#include <cstring>",
        "",
        "static inline bool IsZero(double v) "
        "{ return v > -1e-35 && v <= 1e-35; }",
        "",
    ]

    def emit_node(ht, root, root_depth):
        # explicit work stack — trees can be chain-shaped (depth ~num_leaves)
        # and must not hit the Python recursion limit
        stack = [("node", root, root_depth)]
        while stack:
            kind, payload, depth = stack.pop()
            pad = "  " * depth
            if kind == "text":
                lines.append(pad + payload)
                continue
            index = payload
            if index < 0:
                lines.append("%sreturn %.17g;"
                             % (pad, float(ht.leaf_value[~index])))
                continue
            f = int(ht.split_feature[index])
            missing = int(ht.missing_type[index])
            dl = bool(ht.default_left[index])
            if ht.is_categorical[index]:
                nw = ht.cat_bitset.shape[1]
                words = ", ".join("0x%xu" % int(w)
                                  for w in ht.cat_bitset[index])
                lines.append(
                    "%s{ static const unsigned cat[%d] = {%s};"
                    % (pad, nw, words))
                lines.append("%s  int c = (int)arr[%d];" % (pad, f))
                lines.append(
                    "%s  if (!std::isnan(arr[%d]) && c >= 0 && c < %d && "
                    "((cat[c >> 5] >> (c & 31)) & 1)) {" % (pad, f, nw * 32))
                closer = "} }"
            else:
                thr = float(ht.threshold[index])
                cond = "arr[%d] <= %.17g" % (f, thr)
                if missing == 2:    # NaN
                    cond = ("(std::isnan(arr[%d]) ? %s : (%s))"
                            % (f, "true" if dl else "false", cond))
                elif missing == 1:  # Zero
                    cond = ("((IsZero(arr[%d]) || std::isnan(arr[%d])) ? "
                            "%s : (%s))"
                            % (f, f, "true" if dl else "false", cond))
                else:
                    cond = ("(std::isnan(arr[%d]) ? 0.0 <= %.17g : (%s))"
                            % (f, thr, cond))
                lines.append("%sif (%s) {" % (pad, cond))
                closer = "}"
            stack.append(("text", closer, depth))
            stack.append(("node", int(ht.right_child[index]), depth + 1))
            stack.append(("text", "} else {", depth))
            stack.append(("node", int(ht.left_child[index]), depth + 1))

    for i, ht in enumerate(trees):
        lines.append("static double PredictTree%d(const double* arr) {" % i)
        if ht.num_leaves_actual <= 1:
            lines.append("  return %.17g;" % float(ht.leaf_value[0]))
        else:
            emit_node(ht, 0, 1)
        lines.append("}")
        lines.append("")

    lines.append('extern "C" void PredictRaw(const double* arr, double* out) {')
    lines.append("  for (int c = 0; c < %d; ++c) out[c] = 0.0;" % k)
    for i in range(len(trees)):
        lines.append("  out[%d] += PredictTree%d(arr);" % (i % k, i))
    if parsed.get("average_output"):
        niter = max(len(trees) // max(k, 1), 1)
        lines.append("  for (int c = 0; c < %d; ++c) out[c] /= %d.0;"
                     % (k, niter))
    lines.append("}")
    lines.append("")
    lines.append('extern "C" void Predict(const double* arr, double* out) {')
    lines.append("  PredictRaw(arr, out);")
    if "sqrt" in obj[1:]:
        # reg_sqrt back-transform: sign(x) * x^2 (regression_objective.hpp)
        lines.append("  for (int c = 0; c < %d; ++c) "
                     "out[c] = (out[c] < 0 ? -1.0 : 1.0) * out[c] * out[c];"
                     % k)
    if obj_name in ("binary", "cross_entropy", "xentropy"):
        lines.append("  out[0] = 1.0 / (1.0 + std::exp(%.17g * -out[0]));"
                     % sigmoid)
    elif obj_name in ("multiclass", "softmax"):
        lines.append("  double m = out[0], s = 0.0;")
        lines.append("  for (int c = 1; c < %d; ++c) if (out[c] > m) m = out[c];" % k)
        lines.append("  for (int c = 0; c < %d; ++c) { out[c] = std::exp(out[c] - m); s += out[c]; }" % k)
        lines.append("  for (int c = 0; c < %d; ++c) out[c] /= s;" % k)
    elif obj_name in ("multiclassova", "multiclass_ova", "ova", "ovr"):
        lines.append("  for (int c = 0; c < %d; ++c) "
                     "out[c] = 1.0 / (1.0 + std::exp(%.17g * -out[c]));"
                     % (k, sigmoid))
    elif obj_name in ("poisson", "gamma", "tweedie"):
        lines.append("  for (int c = 0; c < %d; ++c) out[c] = std::exp(out[c]);" % k)
    lines.append("}")
    lines.append("")
    return "\n".join(lines)
