"""Feature quantization: BinMapper.

TPU-native re-design of the reference binning (include/LightGBM/bin.h:61-209,
src/io/bin.cpp FindBin/GreedyFindBin/FindBinWithZeroAsOneBin). Semantics are
kept bit-for-bit where it matters for split parity:

- greedy equal-count bin boundaries with ``min_data_in_bin`` and "big count
  value" handling;
- zero always gets its own bin (bins split around +/- kZeroThreshold);
- missing handling: MissingType None / Zero (zero bin doubles as missing) /
  NaN (dedicated last bin);
- categorical: categories sorted by count, rare categories dropped, mapped to
  bins; unseen/negative categories -> NaN treatment.

Host-side (NumPy): binning runs once per dataset; the binned int matrix is the
device-resident artifact everything else trains on.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..log import Log, check

# bin.h kZeroThreshold
K_ZERO_THRESHOLD = 1e-35
_EPS = 1e-15


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2


class BinType:
    NUMERICAL = 0
    CATEGORICAL = 1


def _get_double_upper_bound(x: float) -> float:
    """Common::GetDoubleUpperBound — nextafter so values == boundary bin left."""
    return math.nextafter(x, math.inf)


def _check_double_equal(a: float, b: float) -> bool:
    return b <= math.nextafter(a, math.inf)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                    max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy equal-count boundary search (bin.cpp GreedyFindBin)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    check(max_bin > 0, "max_bin should be > 0")
    # plain lists: the loops below are scalar-sequential (running counts and
    # adaptive thresholds), and numpy scalar indexing would dominate them
    dv = distinct_values.tolist()
    cn = counts.tolist()
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += cn[i]
            if cur_cnt >= min_data_in_bin:
                val = _get_double_upper_bound((dv[i] + dv[i + 1]) / 2.0)
                if not bin_upper_bound or not _check_double_equal(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt = 0
        bin_upper_bound.append(float("inf"))
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big_np = counts >= mean_bin_size
    rest_bin_cnt -= int(is_big_np.sum())
    rest_sample_cnt -= int(counts[is_big_np].sum())
    is_big = is_big_np.tolist()
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    upper_bounds = [float("inf")] * max_bin
    lower_bounds = [float("inf")] * max_bin

    bin_cnt = 0
    lower_bounds[0] = dv[0]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= cn[i]
        cur_cnt += cn[i]
        if (is_big[i] or cur_cnt >= mean_bin_size
                or (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = dv[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = dv[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    for i in range(bin_cnt - 1):
        val = _get_double_upper_bound((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or not _check_double_equal(bin_upper_bound[-1], val):
            bin_upper_bound.append(val)
    bin_upper_bound.append(float("inf"))
    return bin_upper_bound


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_sample_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """bin.cpp FindBinWithZeroAsOneBin: dedicated zero bin in the middle."""
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(counts[left_mask].sum())
    cnt_zero = int(counts[zero_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())

    left_idx = np.nonzero(~left_mask)[0]
    left_cnt = int(left_idx[0]) if len(left_idx) else len(distinct_values)

    bin_upper_bound: List[float] = []
    if left_cnt > 0:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bin_upper_bound = greedy_find_bin(distinct_values[:left_cnt], counts[:left_cnt],
                                          left_max_bin, left_cnt_data, min_data_in_bin)
        bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    right_idx = np.nonzero(right_mask)[0]
    if len(right_idx):
        right_start = int(right_idx[0])
        right_max_bin = max_bin - 1 - len(bin_upper_bound)
        check(right_max_bin > 0, "not enough bins for positive values")
        right_bounds = greedy_find_bin(distinct_values[right_start:],
                                       counts[right_start:], right_max_bin,
                                       right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(float("inf"))
    return bin_upper_bound


class BinMapper:
    """Per-feature value -> bin mapping (bin.h:61-209)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MissingType.NONE
        self.bin_type: int = BinType.NUMERICAL
        self.is_trivial: bool = True
        self.sparse_rate: float = 0.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.categorical_2_bin: Dict[int, int] = {}
        self.bin_2_categorical: List[int] = []
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0

    # ------------------------------------------------------------------ fit
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: int = BinType.NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        """BinMapper::FindBin (bin.cpp:210-420).

        ``values`` are the *sampled non-trivial* values; ``total_sample_cnt``
        includes rows whose value was 0 (not stored by the sampler).
        """
        values = np.asarray(values, dtype=np.float64)
        na_mask = np.isnan(values)
        na_cnt = int(na_mask.sum())
        values = values[~na_mask]
        num_sample_values = len(values) + na_cnt

        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NAN if na_cnt > 0 else MissingType.NONE
        if self.missing_type != MissingType.NAN:
            na_cnt = 0

        self.bin_type = bin_type
        self.default_bin = 0
        # rows not captured in `values` and not NaN are implicit zeros
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)

        values = np.sort(values, kind="stable")
        if len(values):
            # group ulp-adjacent values (CheckDoubleEqualOrdered): a new
            # group starts where v[i] > nextafter(v[i-1], +inf); each
            # group's representative is its LAST (largest) member — a
            # vectorized replay of the reference's sequential merge walk
            new_group = values[1:] > np.nextafter(values[:-1], np.inf)
            last_of_group = np.nonzero(np.append(new_group, True))[0]
            first_of_group = np.concatenate([[0], last_of_group[:-1] + 1])
            dv = values[last_of_group].astype(np.float64)
            gid = np.concatenate([[0], np.cumsum(new_group)])
            ct = np.bincount(gid, minlength=len(dv)).astype(np.int64)
            firsts = values[first_of_group]
            # the implicit-zero entry lands exactly where the sequential
            # walk placed it: before the first strictly-positive group when
            # preceded by a strictly-negative one (inserted even with count
            # 0), at the front/back only when zero_cnt > 0
            pos_groups = np.nonzero(firsts > 0.0)[0]
            j = int(pos_groups[0]) if len(pos_groups) else -1
            if j == 0:
                if zero_cnt > 0:
                    dv = np.insert(dv, 0, 0.0)
                    ct = np.insert(ct, 0, zero_cnt)
            elif j > 0:
                if dv[j - 1] < 0.0:
                    dv = np.insert(dv, j, 0.0)
                    ct = np.insert(ct, j, zero_cnt)
            elif dv[-1] < 0.0 and zero_cnt > 0:
                dv = np.append(dv, 0.0)
                ct = np.append(ct, zero_cnt)
        else:
            dv = np.asarray([0.0], dtype=np.float64)
            ct = np.asarray([zero_cnt], dtype=np.int64)
        self.min_val = float(dv[0]) if len(dv) else 0.0
        self.max_val = float(dv[-1]) if len(dv) else 0.0

        if bin_type == BinType.NUMERICAL:
            if self.missing_type == MissingType.ZERO:
                bounds = find_bin_with_zero_as_one_bin(dv, ct, max_bin,
                                                       total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MissingType.NONE
            elif self.missing_type == MissingType.NONE:
                bounds = find_bin_with_zero_as_one_bin(dv, ct, max_bin,
                                                       total_sample_cnt, min_data_in_bin)
            else:
                bounds = find_bin_with_zero_as_one_bin(dv, ct, max_bin - 1,
                                                       total_sample_cnt - na_cnt,
                                                       min_data_in_bin)
                bounds.append(float("nan"))
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            # default (zero) bin index
            self.default_bin = self.value_to_bin(0.0)
            cnt_in_bin = np.zeros(self.num_bin, dtype=np.int64)
            if len(dv):
                # first bin whose upper bound covers the value ("advance
                # while dv > bound"), capped at the last bin — NaN bounds
                # (missing bin) sort last so searchsorted stays valid
                idx = np.minimum(
                    np.searchsorted(self.bin_upper_bound, dv, side="left"),
                    self.num_bin - 1)
                np.add.at(cnt_in_bin, idx, ct)
            if self.missing_type == MissingType.NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            check(self.num_bin <= max_bin, "num_bin exceeds max_bin")
        else:
            self._find_bin_categorical(dv, ct, max_bin, total_sample_cnt,
                                       na_cnt, min_data_in_bin)
            cnt_in_bin = self._cat_cnt_in_bin

        # trivial / sparse-rate bookkeeping (bin.cpp tail)
        if self.num_bin <= 1:
            self.is_trivial = True
        else:
            self.is_trivial = False
        if not self.is_trivial and min_split_data > 0:
            if _need_filter(cnt_in_bin, total_sample_cnt, min_split_data, self.bin_type):
                self.is_trivial = True
        if not self.is_trivial:
            self.sparse_rate = float(cnt_in_bin[self.default_bin]) / max(total_sample_cnt, 1)
        else:
            self.sparse_rate = 1.0

    def _find_bin_categorical(self, dv: np.ndarray, ct: np.ndarray, max_bin: int,
                              total_sample_cnt: int, na_cnt: int,
                              min_data_in_bin: int) -> None:
        """Categorical path of FindBin (bin.cpp:300-360)."""
        dvi: List[int] = []
        cti: List[int] = []
        for v, c in zip(dv, ct):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                Log.warning("Met negative value in categorical features, "
                            "will convert it to NaN")
            elif dvi and iv == dvi[-1]:
                cti[-1] += int(c)
            else:
                dvi.append(iv)
                cti.append(int(c))
        self.num_bin = 0
        rest_cnt = total_sample_cnt - na_cnt
        self.categorical_2_bin = {}
        self.bin_2_categorical = []
        cnt_in_bin: List[int] = []
        if rest_cnt > 0:
            if dvi and dvi[-1] // 100 > len(dvi):
                Log.warning("Met categorical feature which contains sparse values. "
                            "Consider renumbering to consecutive integers started from zero")
            order = np.argsort(-np.asarray(cti), kind="stable")
            dvi = [dvi[i] for i in order]
            cti = [cti[i] for i in order]
            # avoid first bin is zero
            if dvi and dvi[0] == 0:
                # swap with most frequent nonzero if exists
                if len(dvi) > 1:
                    dvi[0], dvi[1] = dvi[1], dvi[0]
                    cti[0], cti[1] = cti[1], cti[0]
            # keep at most max_bin - 1 (reserve bin 0), drop until 99% coverage
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            used_cnt = 0
            max_cat = max_bin - 1
            self.bin_2_categorical = []
            cnt_in_bin = [0]
            for i, (v, c) in enumerate(zip(dvi, cti)):
                if i >= max_cat or (used_cnt >= cut_cnt and i > 1):
                    break
                self.bin_2_categorical.append(v)
                self.categorical_2_bin[v] = i + 1
                cnt_in_bin.append(c)
                used_cnt += c
            self.num_bin = len(self.bin_2_categorical) + 1
            cnt_in_bin[0] = total_sample_cnt - used_cnt
        self._cat_cnt_in_bin = np.asarray(cnt_in_bin if cnt_in_bin else [total_sample_cnt],
                                          dtype=np.int64)
        self.missing_type = MissingType.NAN if na_cnt > 0 else self.missing_type
        self.default_bin = 0

    # ------------------------------------------------------------- transform
    def value_to_bin(self, value: float) -> int:
        """ValueToBin (bin.h:457-493)."""
        if self.bin_type == BinType.CATEGORICAL:
            iv = int(value) if np.isfinite(value) else -1
            return self.categorical_2_bin.get(iv, 0)
        if np.isnan(value):
            if self.missing_type == MissingType.NAN:
                return self.num_bin - 1
            value = 0.0
        n_numeric = self.num_bin - (1 if self.missing_type == MissingType.NAN else 0)
        bounds = self.bin_upper_bound
        lo, hi = 0, n_numeric - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin over a column."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BinType.CATEGORICAL:
            out = np.zeros(len(values), dtype=np.int32)
            if self.categorical_2_bin:
                keys = np.fromiter(self.categorical_2_bin.keys(), dtype=np.int64)
                vals = np.fromiter(self.categorical_2_bin.values(), dtype=np.int32)
                iv = np.where(np.isfinite(values), values, -1).astype(np.int64)
                sorter = np.argsort(keys)
                pos = np.searchsorted(keys[sorter], iv)
                pos = np.clip(pos, 0, len(keys) - 1)
                hit = keys[sorter[pos]] == iv
                out = np.where(hit, vals[sorter[pos]], 0).astype(np.int32)
            return out
        has_nan_bin = self.missing_type == MissingType.NAN
        n_numeric = self.num_bin - (1 if has_nan_bin else 0)
        bounds = self.bin_upper_bound[:max(n_numeric - 1, 0)]
        if len(values) >= 65536:
            from ..native import bin_numeric_native
            nb = bin_numeric_native(values, bounds,
                                    self.num_bin - 1 if has_nan_bin else -1)
            if nb is not None:
                return nb
        nan_mask = np.isnan(values)
        safe = np.where(nan_mask, 0.0, values)
        bins = np.searchsorted(bounds, safe, side="left").astype(np.int32)
        # searchsorted 'left': first idx where bounds[idx] >= v, i.e. v <= bound
        if has_nan_bin:
            bins = np.where(nan_mask, self.num_bin - 1, bins)
        return bins

    def bin_to_value(self, bin_idx: int) -> float:
        """BinToValue: representative (upper bound) of a bin."""
        if self.bin_type == BinType.CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx - 1]) if bin_idx > 0 else 0.0
        return float(self.bin_upper_bound[bin_idx])

    # ----------------------------------------------------------- persistence
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "bin_type": self.bin_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_upper_bound": self.bin_upper_bound.tolist(),
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": int(self.default_bin),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.bin_type = int(d["bin_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(v) for v in d["bin_2_categorical"]]
        m.categorical_2_bin = {v: i + 1 for i, v in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m


def _need_filter(cnt_in_bin: np.ndarray, total_cnt: int, filter_cnt: int,
                 bin_type: int) -> bool:
    """bin.cpp NeedFilter: no bin boundary leaves >= filter_cnt on both sides."""
    if bin_type == BinType.NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += int(cnt_in_bin[i])
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    if len(cnt_in_bin) <= 2:
        for i in range(len(cnt_in_bin) - 1):
            if cnt_in_bin[i] >= filter_cnt and total_cnt - cnt_in_bin[i] >= filter_cnt:
                return False
        return True
    return False
