"""EFB — Exclusive Feature Bundling (host-side grouping).

TPU-native re-design of the reference's bundling (src/io/dataset.cpp:67-177
FindGroups/FastFeatureBundling, include/LightGBM/feature_group.h:35-50).
Mutually-exclusive sparse features share one stored uint8 column; each
sub-feature owns a bin range inside the column. This is the framework's path
to sparse data: bundles densify sparse columns into the single dense
[N, num_columns] matrix the TPU histogram kernels want.

Encoding per bundled column (bin_offsets_ analog):
  value 0                      -> every sub-feature at its default bin
  value in [off_k, off_k+nb_k) -> sub-feature k at bin (value - off_k),
                                   everyone else at their default bin
Offsets start at 1 and each range is the sub-feature's full bin count, so
decode is one subtract + range check (core/grow.py go_left) and histogram
expansion is a static gather (core/histogram.py expand_hist). A sub-feature's
default-bin histogram entry is reconstructed from leaf totals, the
Dataset::FixHistogram idea (dataset.h:411-412).

The grouping itself is greedy conflict-bounded graph coloring like the
reference: features are processed in descending nonzero count; a feature
joins the first bundle whose accumulated conflict count (rows where both the
bundle and the feature are non-default, measured on a row sample) stays
within max_conflict_rate, and whose total bin count stays <= 256 (uint8).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

MAX_BUNDLE_BINS = 256  # uint8 storage


def find_bundles(nz_sample_rows: Sequence[np.ndarray], sample_n: int,
                 num_bins: Sequence[int], max_conflict_rate: float,
                 sparse_threshold: float = 0.8,
                 max_search_groups: int = 100) -> List[List[int]]:
    """Group features into exclusive bundles.

    Args:
      nz_sample_rows: per feature, sorted sampled-row indices where the
        feature is non-default (nonzero).
      sample_n: number of sampled rows the indices refer to.
      num_bins: per feature bin count (bundle capacity accounting).
      max_conflict_rate: allowed fraction of sampled rows where two bundled
        features collide (0 = strictly exclusive).
      sparse_threshold: a feature is a bundle candidate only when its
        zero-rate is >= sparse_threshold (the reference's sparse feature
        criterion); denser features stay un-bundled — they gain nothing and
        conflict everywhere.
      max_search_groups: cap on bundles probed per feature (keeps grouping
        O(F * max_search_groups * sample)).

    Returns: list of bundles (each a list of original feature indices) in
      stored-column order; singletons included.
    """
    f = len(nz_sample_rows)
    nz_counts = np.array([len(r) for r in nz_sample_rows], dtype=np.int64)
    budget = int(max_conflict_rate * sample_n)

    dense = [j for j in range(f)
             if sample_n > 0
             and nz_counts[j] > (1.0 - sparse_threshold) * sample_n]
    dense_set = set(dense)
    sparse_feats = [j for j in range(f) if j not in dense_set]
    # densest first: big features anchor bundles, tiny ones fill gaps
    sparse_feats.sort(key=lambda j: -nz_counts[j])

    bundles: List[List[int]] = []
    occupancy: List[np.ndarray] = []      # bool[sample_n] per bundle
    conflicts: List[int] = []             # accumulated conflicts per bundle
    bins_used: List[int] = []             # 1 (shared zero) + sum of nb

    for j in sparse_feats:
        rows = nz_sample_rows[j]
        mine = np.zeros(sample_n, dtype=bool)
        mine[rows] = True
        placed = False
        for gi in range(min(len(bundles), max_search_groups)):
            if bins_used[gi] + num_bins[j] > MAX_BUNDLE_BINS:
                continue
            clash = int(np.count_nonzero(occupancy[gi] & mine))
            if conflicts[gi] + clash <= budget:
                bundles[gi].append(j)
                occupancy[gi] |= mine
                conflicts[gi] += clash
                bins_used[gi] += int(num_bins[j])
                placed = True
                break
        if not placed:
            bundles.append([j])
            occupancy.append(mine)
            conflicts.append(0)
            bins_used.append(1 + int(num_bins[j]))

    # drop the bundle machinery for bundles that stayed singletons: they are
    # stored raw (offset 0, identity encoding), as are dense features
    out = [b for b in bundles if len(b) > 1]
    singles = sorted(dense + [b[0] for b in bundles if len(b) == 1])
    out.extend([j] for j in singles)
    return out


def bundle_offsets(bundle: List[int],
                   num_bins: Sequence[int]) -> Tuple[List[int], int]:
    """Per-sub-feature bin offsets inside a bundled column and the column's
    total encoded bin count. Singletons use identity encoding (offset 0)."""
    if len(bundle) == 1:
        return [0], int(num_bins[bundle[0]])
    offsets = []
    pos = 1                                # bin 0 = shared all-defaults
    for j in bundle:
        offsets.append(pos)
        pos += int(num_bins[j])
    return offsets, pos
