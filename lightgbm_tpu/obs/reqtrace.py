"""Request-scoped tracing: span trees with tail-based sampling.

Every admitted serving request (and every streamed training iteration)
can carry a trace — a tree of host-side spans recording where that one
request spent its time: queue wait, the QoS virtual-time pick, the
micro-batch it was coalesced into, device dispatch vs the
``block_until_ready`` wait.  Spans are buffered per trace and only
emitted when the ROOT span finishes, because the sampling policy is
tail-based: it needs the final duration and status before it can decide.

Sampling policy (``RequestTracer``):

- always keep traces slower than ``obs_trace_slow_ms``;
- always keep traces that end in ``shed`` or ``error``;
- probabilistically keep ``obs_trace_sample`` of the rest, decided by a
  deterministic hash of ``(seed, trace_id)`` so a replayed event stream
  makes the same decisions (pinned by tests/test_merge_traces.py).

Kept spans are emitted as ``span`` records on the shared
:class:`~lightgbm_tpu.obs.trace.EventStream` — they ring-mirror into the
flight recorder and merge across processes with
``tools/merge_events.py`` like every other event.

Propagation: the ``x-lgbm-trace`` header carries ``<trace_id>`` or
``<trace_id>-<parent_span_id>``; the serving front-end honors it at
admission so fleet replicas and ``tools/load_test.py`` keep one trace id
across process hops.

Tracing off is the shared :data:`NULL_REQ_SPAN` / :data:`NULL_TRACER` —
every call site collapses to attribute lookups on a slotless singleton,
and the compiled programs never see any of this (host-side only).
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from . import registry as _registry

TRACE_HEADER = "x-lgbm-trace"

_HEX = set("0123456789abcdef")

# id minting is on the per-request hot path (every admitted request mints
# a trace id + several span ids even when the trace will be dropped), so
# no os.urandom syscall per id: one random base per process, mixed with
# an atomic counter through the splitmix64 multiplier — unique within a
# process, collision-unlikely across processes, and cheap
_ID_BASE = int.from_bytes(os.urandom(8), "big")
_ID_COUNT = itertools.count(1)
_MIX = 0x9E3779B97F4A7C15


def new_trace_id() -> str:
    return "%016x" % ((_ID_BASE ^ (next(_ID_COUNT) * _MIX))
                      & 0xFFFFFFFFFFFFFFFF)


def new_span_id() -> str:
    return "%08x" % ((_ID_BASE ^ (next(_ID_COUNT) * _MIX)) & 0xFFFFFFFF)


def parse_trace_header(value) -> Optional[Tuple[str, Optional[str]]]:
    """``"<trace_id>"`` or ``"<trace_id>-<parent_span_id>"`` ->
    ``(trace_id, parent_span_id_or_None)``; malformed headers return None
    (the request simply starts a fresh trace — a bad client header must
    never fail admission)."""
    if not value:
        return None
    parts = str(value).strip().lower().split("-")
    tid = parts[0]
    if not tid or len(tid) > 32 or not set(tid) <= _HEX:
        return None
    parent = None
    if len(parts) > 1 and parts[1]:
        cand = parts[1]
        if len(cand) <= 32 and set(cand) <= _HEX:
            parent = cand
    return (tid, parent)


def format_trace_header(span) -> str:
    """Header value that makes ``span`` the parent on the next hop."""
    return "%s-%s" % (span.trace_id, span.span_id)


def keep_decision(trace_id: str, sample: float, seed: int = 0) -> bool:
    """Deterministic probabilistic keep for the non-slow, non-error tail:
    hash ``(seed, trace_id)`` into [0, 1) and compare against ``sample``.
    Pure function of its inputs so replica processes and replays agree."""
    s = float(sample)
    if s >= 1.0:
        return True
    if s <= 0.0:
        return False
    h = zlib.crc32(("%d:%s" % (int(seed), trace_id)).encode("ascii"))
    return (h & 0xFFFFFFFF) / 4294967296.0 < s


class _NullReqSpan:
    """The shared do-nothing span handed out when tracing is off.  One
    instance for the whole process; ``child`` returns itself so arbitrary
    trees of instrumentation cost a method call and nothing else."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    name = ""
    status = "ok"
    dur_ms = 0.0

    def child(self, name, **fields):
        return self

    def annotate(self, **fields):
        return None

    def end(self, status="ok", **fields):
        return None

    def finish(self, status="ok", **fields):
        return None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def __bool__(self):
        return False


NULL_REQ_SPAN = _NullReqSpan()


class ReqSpan:
    """One node of a request's span tree.

    Roots are minted by :meth:`RequestTracer.start_trace`; children by
    :meth:`child`.  ``end()`` buffers the span on its root; nothing is
    serialized or emitted until the root's ``finish()`` runs the
    tail-based sampling decision.  Cross-thread safe: the batching worker ends spans
    created on submitter threads (buffer appends go through the root's
    lock)."""

    __slots__ = ("_tracer", "_root", "trace_id", "span_id", "parent_id",
                 "name", "fields", "status", "dur_ms", "_t0", "_wall0",
                 "_done", "_buf", "_lock", "_batch", "_dependent",
                 "_emitted")

    def __init__(self, tracer, root, trace_id, span_id, parent_id, name,
                 fields, dependent=False):
        self._tracer = tracer
        self._root = root                      # None => this IS a root
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.fields = dict(fields)
        self.status = "ok"
        self.dur_ms = 0.0
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._done = False
        self._dependent = dependent
        self._emitted = False
        self._batch = None
        if root is None:
            self._buf: List["ReqSpan"] = []
            self._lock = threading.Lock()
        else:
            self._buf = None
            self._lock = None

    def __bool__(self):
        return True

    # ------------------------------------------------------------- tree
    def child(self, name: str, **fields) -> "ReqSpan":
        root = self._root if self._root is not None else self
        return ReqSpan(self._tracer, root, self.trace_id, new_span_id(),
                       self.span_id, name, fields)

    def annotate(self, **fields) -> None:
        self.fields.update(fields)

    # --------------------------------------------------------- lifecycle
    def end(self, status: str = "ok", **fields) -> None:
        """Close the span and buffer it on its root.  Only the SPAN goes
        in the buffer — the flat record dict is materialized lazily in
        ``_record()``, so the ~99% of traces the sampler drops never pay
        for serialization."""
        if self._done:
            return
        self._done = True
        self.dur_ms = (time.perf_counter() - self._t0) * 1000.0
        self.status = str(status)
        if fields:
            self.fields.update(fields)
        root = self._root if self._root is not None else self
        with root._lock:
            root._buf.append(self)

    def _record(self) -> Dict:
        rec = dict(self.fields)
        rec.update(trace=self.trace_id, span_id=self.span_id,
                   parent=self.parent_id, name=self.name,
                   t0=round(self._wall0, 6),
                   dur_ms=round(self.dur_ms, 3), status=self.status)
        return rec

    def finish(self, status: str = "ok", **fields) -> None:
        """End the span; on a root this also runs the keep/drop decision
        and emits the buffered tree when kept."""
        self.end(status, **fields)
        if self._root is None and not self._dependent:
            self._tracer._finish(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.finish("error" if exc_type is not None else "ok")
        return False


class RequestTracer:
    """Mints trace roots, buffers span trees, applies tail-based sampling
    at root finish, and emits kept spans on the EventStream."""

    enabled = True

    def __init__(self, events=None, slow_ms: float = 250.0,
                 sample: float = 0.01, seed: int = 0, registry=None,
                 keep_recent: int = 64):
        self.events = events
        self.slow_ms = float(slow_ms)
        self.sample = float(sample)
        self.seed = int(seed)
        # bounded summaries of kept traces, newest last — lets smokes and
        # tests inspect the verdicts without re-reading the event file
        self.recent = collections.deque(maxlen=int(keep_recent))
        reg = registry if registry is not None else _registry.get_registry()
        self._started = reg.counter(
            "lgbm_trace_started_total", "Trace roots minted")
        self._kept = reg.counter(
            "lgbm_trace_kept_total", "Traces kept by tail-based sampling")
        self._kept_slow = reg.counter(
            "lgbm_trace_kept_slow_total",
            "Traces kept because dur_ms >= obs_trace_slow_ms")
        self._kept_bad = reg.counter(
            "lgbm_trace_kept_bad_total",
            "Traces kept because they ended in shed/error")
        self._span_count = reg.counter(
            "lgbm_trace_spans_total", "Spans emitted from kept traces")

    # ------------------------------------------------------------- mint
    def start_trace(self, name: str, ctx=None, **fields) -> ReqSpan:
        """Root span for one request/iteration.  ``ctx`` is an inbound
        ``x-lgbm-trace`` header value (or a pre-parsed ``(trace_id,
        parent_span_id)`` tuple) — honoring it keeps one trace id across
        fleet hops."""
        if isinstance(ctx, str):
            ctx = parse_trace_header(ctx)
        tid, parent = ctx if ctx else (new_trace_id(), None)
        self._started.inc()
        return ReqSpan(self, None, tid, new_span_id(), parent, name, fields)

    def batch_span(self, name: str, members, **fields) -> ReqSpan:
        """One batch span linked from N coalesced request spans.

        The span rides the first member's trace (its request span is the
        parent) and records every member as a ``links`` entry; every
        member's root is annotated with the batch span's id.  The batch
        subtree is buffered on its own and emitted once if ANY member
        trace is kept, so a slow straggler's trace still shows the batch
        that carried it even when the batch's own trace is dropped."""
        members = [m for m in members if isinstance(m, ReqSpan)]
        if not members:
            return NULL_REQ_SPAN
        first = members[0]
        links = ["%s-%s" % (m.trace_id, m.span_id) for m in members]
        sp = ReqSpan(self, None, first.trace_id, new_span_id(),
                     first.span_id, name, dict(fields, links=links),
                     dependent=True)
        ref = "%s-%s" % (sp.trace_id, sp.span_id)
        for m in members:
            m.annotate(batch=ref)
            root = m._root if m._root is not None else m
            root._batch = sp
        return sp

    # ------------------------------------------------------------ flush
    def _finish(self, root: ReqSpan) -> None:
        slow = root.dur_ms >= self.slow_ms
        bad = root.status != "ok"
        keep = slow or bad or keep_decision(root.trace_id, self.sample,
                                            self.seed)
        if slow:
            self._kept_slow.inc()
        if bad:
            self._kept_bad.inc()
        if not keep:
            return
        self._kept.inc()
        spans: List[ReqSpan] = []
        batch = root._batch
        if batch is not None:
            with batch._lock:
                if not batch._emitted:
                    batch._emitted = True
                    spans.extend(batch._buf)
        with root._lock:
            spans.extend(root._buf)
        recs = [s._record() for s in spans]
        self._span_count.inc(len(recs))
        if self.events is not None:
            for rec in recs:
                self.events.write("span", **rec)
        self.recent.append({
            "trace": root.trace_id, "name": root.name,
            "dur_ms": round(root.dur_ms, 3), "status": root.status,
            "reason": ("slow" if slow else
                       ("status" if bad else "sample")),
            "spans": len(recs),
            # the flat span records themselves (parent links intact) so
            # /traces can answer "which stage ate the latency" without
            # re-reading the event file
            "records": recs})

    def recent_traces(self) -> List[Dict]:
        """Summaries (+ span records) of recently KEPT traces, newest
        last — the serving ``/traces`` body."""
        return list(self.recent)


class NullRequestTracer:
    """Tracing disabled: every mint returns the shared no-op span."""

    enabled = False
    recent: collections.deque = collections.deque(maxlen=1)

    def start_trace(self, name, ctx=None, **fields):
        return NULL_REQ_SPAN

    def batch_span(self, name, members, **fields):
        return NULL_REQ_SPAN

    def recent_traces(self):
        return []


NULL_TRACER = NullRequestTracer()
