"""Lightweight stats HTTP endpoint for training-time scraping.

A daemon-threaded ``ThreadingHTTPServer`` that exposes the process-wide
metrics registry while a training run is live:

- ``GET /metrics``  -> Prometheus text exposition (0.0.4)
- ``GET /stats``    -> JSON snapshot of every registered series
- ``GET /healthz``  -> ``{"status": "ok"|"anomalous", "anomalies": N}``
- ``GET /roofline`` -> per-phase roofline attribution (obs/costmodel.py):
  extracted FLOPs/bytes per entry point joined with span wall times
- ``GET /metrics/cluster`` / ``GET /stats/cluster`` -> the federated
  cluster view (obs/distributed.py): every process's metrics merged,
  served from the cache the once-per-block allgather refreshes — a
  scrape never triggers a collective.  Single-process (or before
  ``StatsServer.set_cluster`` wires a provider) these are exactly the
  local ``/metrics`` / ``/stats`` bodies.
- ``GET /slo``      -> SLO burn-rate judgment (obs/slo.py): every
  declared objective's fast/slow-window burn rate and burning flag, or
  ``{"status": "disabled"}`` when no SLO engine is wired here.
- ``GET /drift``    -> per-model train/serve drift status (obs/drift.py):
  every registered DriftMonitor's PSI/JS per feature + score sketch, or
  ``{"status": "no_profile"}`` when nothing monitors drift here.  The
  per-feature numbers also live in the registry as ``lgbm_drift_*``
  gauges, so the cluster routes federate them automatically.

Enabled via ``obs_stats_port`` (>= 0; 0 binds an OS-assigned port whose
number is exported in ``StatsServer.port`` and logged).  A busy port is
not fatal: the constructor catches ``EADDRINUSE`` and falls back to an
ephemeral port with a warning — a stale scraper or a second trainer on
the same host must never kill training startup.  The server binds
127.0.0.1 only — it is a diagnostics tap, not a service surface — and
shares nothing mutable with the training loop beyond the thread-safe
registry, so scrapes never block an iteration.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..log import Log
from .registry import MetricsRegistry, get_registry


class _Handler(BaseHTTPRequestHandler):
    server_version = "lgbm-obs/0.1"

    # class attributes bound by StatsServer.start()
    registry: MetricsRegistry = None
    anomaly_counter = None
    cluster = None   # DistributedObs (or None): set via set_cluster()
    slo = None       # SloEngine (or None): set via set_slo()

    def log_message(self, fmt, *args):  # quiet: route through our logger
        Log.debug("obs.server: " + fmt % args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        try:
            if self.path == "/metrics":
                body = self.registry.prometheus_text().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/metrics/cluster":
                text = (self.cluster.cluster_prometheus()
                        if self.cluster is not None
                        else self.registry.prometheus_text())
                self._send(200, text.encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/stats/cluster":
                snap = (self.cluster.cluster_stats()
                        if self.cluster is not None
                        else self.registry.snapshot())
                self._send(200, json.dumps(snap, sort_keys=True).encode(),
                           "application/json")
            elif self.path == "/stats":
                body = json.dumps(self.registry.snapshot(),
                                  sort_keys=True).encode()
                self._send(200, body, "application/json")
            elif self.path == "/healthz":
                n = (int(self.anomaly_counter.value)
                     if self.anomaly_counter is not None else 0)
                body = json.dumps({
                    "status": "ok" if n == 0 else "anomalous",
                    "anomalies": n,
                }).encode()
                self._send(200, body, "application/json")
            elif self.path == "/drift":
                # lazy import mirrors /roofline: the route reads the
                # process-wide monitor registry, populated by serving (or
                # anything that register_monitor()s)
                from .drift import drift_snapshot
                self._send(200, json.dumps(drift_snapshot(),
                                           sort_keys=True).encode(),
                           "application/json")
            elif self.path == "/slo":
                body = (self.slo.status() if self.slo is not None
                        else {"status": "disabled", "slos": {}})
                self._send(200, json.dumps(body, sort_keys=True).encode(),
                           "application/json")
            elif self.path == "/roofline":
                # lazy import: costmodel itself is jax-free at module
                # scope, but keep the server importable even if it ever
                # is not
                from .costmodel import roofline_snapshot
                body = json.dumps(
                    roofline_snapshot(registry=self.registry),
                    sort_keys=True).encode()
                self._send(200, body, "application/json")
            else:
                self._send(404, b'{"error": "not found"}',
                           "application/json")
        except Exception as e:  # never kill the scrape thread
            try:
                self._send(500, json.dumps({"error": str(e)}).encode(),
                           "application/json")
            except Exception:
                pass


class StatsServer:
    """Own one bound socket + serving thread; ``stop()`` is idempotent."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None else get_registry()
        handler = type("BoundStatsHandler", (_Handler,), {
            "registry": self._registry,
            "anomaly_counter": self._registry.counter(
                "lgbm_train_health_anomalies_total",
                "Non-finite grad/hess or gain anomalies detected in "
                "training."),
        })
        try:
            self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        except OSError as e:
            # EADDRINUSE (or any bind failure) on a diagnostics port must
            # not kill training startup — fall back to an OS-assigned
            # port and say where we actually landed
            Log.warning("obs: stats port %d unavailable (%s); falling "
                        "back to an ephemeral port" % (int(port), e))
            self._httpd = ThreadingHTTPServer((host, 0), handler)
        self._httpd.daemon_threads = True
        self._handler = handler
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def set_cluster(self, provider) -> None:
        """Wire the ``/metrics/cluster`` + ``/stats/cluster`` routes to a
        DistributedObs (anything with ``cluster_prometheus()`` /
        ``cluster_stats()``).  Without a provider the routes serve the
        local registry — the single-process degenerate case."""
        self._handler.cluster = provider

    def set_slo(self, engine) -> None:
        """Wire ``/slo`` to an obs.slo.SloEngine (anything with
        ``status()``); without one the route reports disabled."""
        self._handler.slo = engine

    def start(self) -> "StatsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="lgbm-obs-stats", daemon=True)
        self._thread.start()
        Log.info("obs: stats endpoint on http://%s:%d (metrics/stats/"
                 "healthz/roofline/drift)" % (self.host, self.port))
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()
