"""TrainingObs: the per-booster observability facade.

Built once in ``GBDT._setup_train`` from the config knobs and handed to
the boosting loop, which drives it at three intensities:

- ``observability=none``  (level 0): every hook is a no-op and the
  health branch stays out of the compiled program — the training step is
  byte-identical to an uninstrumented build.
- ``observability=basic`` (level 1): the fused 64-iteration block path is
  kept; one sync + span per block, per-iteration events derived from the
  block, health vectors checked per block, HBM gauge per block.  Target
  overhead < 3% (bench.py measures it).
- ``observability=full``  (level 2): the engine falls back to true
  per-iteration dispatch — real spans around every iteration, health
  flagged within one iteration, optional Perfetto capture window, HBM
  accounting every iteration.

Health monitoring is orthogonal: ``health_monitor=auto`` enables it
whenever observability is on, and ``callback.health_monitor()`` can arm
it (rebuilding the compiled step if needed) even at
``observability=none``.
"""
from __future__ import annotations

from typing import Optional

from ..log import Log
from .health import HealthMonitor
from .registry import get_registry
from .reqtrace import NULL_REQ_SPAN, NULL_TRACER, RequestTracer
from .server import StatsServer
from .slo import SloEngine
from .trace import EventStream, PerfettoWindow, Tracer, _NULL_SPAN

LEVELS = {"none": 0, "basic": 1, "full": 2}


def resolve_health_action(config) -> str:
    """``health_monitor=auto`` means: warn when observability is on,
    nothing when it is off (zero device-side cost by default)."""
    action = getattr(config, "health_monitor", "auto")
    if action == "auto":
        return "warn" if getattr(config, "observability", "none") != "none" \
            else "none"
    return action


class TrainingObs:
    """Observability state for one booster; cheap when disabled."""

    def __init__(self, level: int = 0, health_action: str = "none",
                 events: Optional[EventStream] = None,
                 perfetto: Optional[PerfettoWindow] = None,
                 stats: Optional[StatsServer] = None,
                 checkpoint_dir: str = "", checkpoint_keep: int = 3,
                 flight=None):
        self.level = level
        self.registry = get_registry()
        self.events = events
        self.tracer = Tracer(enabled=level > 0, registry=self.registry,
                             events=events, metric="lgbm_train_span_seconds")
        self.perfetto = perfetto
        self.stats = stats
        self.dist = None          # DistributedObs, wired by from_config
        self.flight = flight      # FlightRecorder (obs/distributed.py)
        if flight is not None:
            flight.install()
        self._checkpoint_dir = checkpoint_dir
        self._checkpoint_keep = checkpoint_keep
        self.monitor: Optional[HealthMonitor] = None
        if health_action != "none":
            self._make_monitor(health_action)
        self._c_iters = self.registry.counter(
            "lgbm_train_iterations_total", "Boosting iterations completed.")
        self._s_iter = self.registry.summary(
            "lgbm_train_iteration_seconds",
            "Per-iteration wall time (derived from block time when fused).")
        self._g_wave_s = self.registry.gauge(
            "lgbm_train_seconds_per_wave",
            "Mean wall time per frontier wave (sharded-collective step) "
            "over the last synced dispatch.")
        self._g_hbm = self.registry.gauge(
            "lgbm_train_device_bytes_in_use",
            "Live device memory (allocator bytes_in_use; live-array sum "
            "as fallback).")
        self._c_rows = self.registry.counter(
            "lgbm_train_rows_total",
            "Training rows processed (rows x iterations completed) — the "
            "train_slo_rows_per_sec throughput source.")
        # request-scoped tracing of the training loop (obs/reqtrace.py):
        # one root per streamed iteration, per-wave children; the same
        # tail-sampling machinery the serving path uses
        self.reqtrace = NULL_TRACER
        self.slo: Optional[SloEngine] = None

    # ------------------------------------------------------------ setup
    @classmethod
    def disabled(cls) -> "TrainingObs":
        return cls(level=0, health_action="none")

    @classmethod
    def from_config(cls, config) -> "TrainingObs":
        level = LEVELS.get(getattr(config, "observability", "none"), 0)
        # distributed identity first: the event stream stamps process/host
        # onto every record and the flight recorder names its dump by
        # process index, so both need it before construction
        dist_mode = getattr(config, "obs_distributed", "auto")
        pidx, pcount, phost = 0, 1, ""
        dist_on = False
        if level > 0 and dist_mode != "off":
            from .distributed import process_env
            pidx, pcount, phost = process_env()
            dist_on = pcount > 1 or dist_mode == "on"
        events = None
        flight = None
        if level > 0 and getattr(config, "obs_event_file", ""):
            if getattr(config, "obs_flight_recorder", 0) > 0:
                from .distributed import FlightRecorder
                flight = FlightRecorder(
                    config.obs_event_file, process_index=pidx,
                    size=config.obs_flight_recorder)
            static = {"process": pidx, "host": phost} if dist_on else None
            events = EventStream(config.obs_event_file,
                                 static_fields=static, ring=flight)
            if flight is not None:
                flight._on_dump = lambda reason: events.flush(fsync=True)
        perfetto = None
        if (level >= 2 and getattr(config, "obs_perfetto_dir", "")
                and getattr(config, "obs_perfetto_iters", 0) > 0):
            perfetto = PerfettoWindow(config.obs_perfetto_dir,
                                      getattr(config, "obs_perfetto_start", 0),
                                      config.obs_perfetto_iters)
        stats = None
        port = getattr(config, "obs_stats_port", -1)
        if level > 0 and port >= 0:
            try:
                stats = StatsServer(port).start()
            except OSError as e:
                Log.warning("obs: could not bind stats port %d: %s"
                            % (port, e))
        obs = cls(level=level,
                  health_action=resolve_health_action(config),
                  events=events, perfetto=perfetto, stats=stats,
                  checkpoint_dir=getattr(config, "checkpoint_dir", ""),
                  checkpoint_keep=getattr(config, "checkpoint_keep", 3),
                  flight=flight)
        if dist_on:
            from .distributed import DistributedObs
            obs.dist = DistributedObs(
                registry=obs.registry, monitor=obs.monitor,
                process_index=pidx, process_count=pcount, hostname=phost,
                warn_skew=getattr(config, "obs_straggler_warn_skew", 2.0))
            if stats is not None:
                stats.set_cluster(obs.dist)
        if level > 0 and getattr(config, "obs_trace", False):
            obs.reqtrace = RequestTracer(
                events=events,
                slow_ms=getattr(config, "obs_trace_slow_ms", 250.0),
                sample=getattr(config, "obs_trace_sample", 0.01),
                seed=getattr(config, "seed", 0))
        floor = getattr(config, "train_slo_rows_per_sec", 0.0)
        if level > 0 and floor > 0:
            obs.slo = SloEngine(
                fast_window_s=getattr(config, "slo_fast_window_s", 300.0),
                slow_window_s=getattr(config, "slo_slow_window_s", 3600.0),
                burn_warn=getattr(config, "slo_burn_warn", 2.0),
                monitor=obs.monitor)
            obs.slo.add_throughput_slo(
                "train_throughput", "lgbm_train_rows_total", floor,
                description="training rows/sec floor "
                            "(train_slo_rows_per_sec)")
            obs.slo.start(getattr(config, "slo_tick_s", 5.0))
            if stats is not None:
                stats.set_slo(obs.slo)
        return obs

    def _make_monitor(self, action: str) -> None:
        self.monitor = HealthMonitor(action=action, registry=self.registry,
                                     events=self.events,
                                     on_abort=self._abort_checkpoint,
                                     on_fatal=self._fatal_dump)
        if self.dist is not None:
            self.dist.monitor = self.monitor

    def _fatal_dump(self, report) -> None:
        self.crash_flush("health:%s" % getattr(report, "kind", "anomaly"))

    def _abort_checkpoint(self, booster, report) -> None:
        if booster is None or not self._checkpoint_dir:
            return
        from ..checkpoint.manager import CheckpointManager
        mgr = CheckpointManager(self._checkpoint_dir,
                                keep_last_n=self._checkpoint_keep)
        path = mgr.save(booster)
        Log.warning("health: checkpoint-and-abort wrote %s" % path)

    # ------------------------------------------------------------ state
    @property
    def enabled(self) -> bool:
        return self.level > 0

    @property
    def per_iteration(self) -> bool:
        """full mode: the loop must dispatch one iteration at a time."""
        return self.level >= 2

    @property
    def health_enabled(self) -> bool:
        return self.monitor is not None and self.monitor.action != "none"

    def arm_health(self, action: str) -> bool:
        """Enable/retarget health monitoring (callback.health_monitor).
        Returns True when the compiled step must be rebuilt because the
        device-side health branch was previously off."""
        rebuild = not self.health_enabled and action != "none"
        if self.monitor is None:
            if action != "none":
                self._make_monitor(action)
        else:
            self.monitor.action = action
        return rebuild

    # ------------------------------------------------------------ hooks
    def span(self, name: str, sync=None, **fields):
        if self.level == 0:
            return _NULL_SPAN
        return self.tracer.span(name, sync=sync, **fields)

    def event(self, name: str, **fields) -> None:
        if self.events is not None:
            self.events.write(name, **fields)

    def perfetto_step(self, lo: int, hi: int) -> None:
        if self.perfetto is not None:
            self.perfetto.step(lo, hi)

    def trace_iter(self, iteration: int, **fields):
        """Root span for one training iteration (streamed path).  Returns
        the shared no-op span when request tracing is off, so the caller
        threads it unconditionally; finish() runs the tail-sampling
        keep/drop like any serving request."""
        if not self.reqtrace.enabled:
            return NULL_REQ_SPAN
        return self.reqtrace.start_trace("train_iter",
                                         iteration=int(iteration), **fields)

    def account_rows(self, rows: int) -> None:
        """Rows processed by one completed dispatch — the throughput-SLO
        source (rows x iterations, so a 5-iteration block over 1M rows
        accounts 5M)."""
        if rows > 0:
            self._c_rows.inc(int(rows))

    def dispatch_done(self, start_iter: int, count: int, dur_s: float,
                      health_rows=None, busy_s=None, wait_s=None,
                      **fields) -> None:
        """Account one synced dispatch covering ``count`` iterations.

        ``busy_s``/``wait_s``: the host/device wall-time split the
        training loop measured around this dispatch (host: feature
        sampling + dispatch until the async call returned; device: the
        ``block_until_ready`` wait).  Feeds the distributed per-block
        attribution + straggler allgather when more than one process
        participates."""
        self._c_iters.inc(count)
        per_iter = dur_s / max(count, 1)
        for _ in range(count):
            self._s_iter.observe(per_iter)
        waves = 0.0
        if health_rows is not None:
            waves = float(sum(r[3] for r in health_rows))
            if waves > 0:
                self._g_wave_s.set(dur_s / waves)
        if self.events is not None:
            kind = "iteration" if count == 1 else "block"
            if busy_s is not None:
                fields = dict(fields, host_s=round(float(busy_s), 6))
            if wait_s is not None:
                fields = dict(fields, device_s=round(float(wait_s), 6))
            self.events.write(kind, iteration=start_iter, count=count,
                              dur_s=round(dur_s, 6),
                              iter_s=round(per_iter, 6), **fields)
        if self.dist is not None:
            b = float(busy_s) if busy_s is not None else 0.0
            w = float(wait_s) if wait_s is not None \
                else max(float(dur_s) - b, 0.0)
            self.dist.on_block(start_iter, count, b, w, waves)

    def check_health(self, health_rows, start_iter: int,
                     booster=None) -> None:
        if self.monitor is not None:
            self.monitor.check(health_rows, start_iter, booster=booster)

    def record_hbm(self) -> None:
        if self.level == 0:
            return
        try:
            import jax
            dev = jax.devices()[0]
            stats = getattr(dev, "memory_stats", lambda: None)()
            if stats and "bytes_in_use" in stats:
                self._g_hbm.set(stats["bytes_in_use"])
                return
            self._g_hbm.set(sum(a.nbytes for a in jax.live_arrays()))
        except Exception:
            pass

    def crash_flush(self, reason: str):
        """The crash path: fsync the event stream, dump the flight
        recorder.  Called from the HealthMonitor fatal hook, the
        checkpoint callback's SIGTERM latch, and (via the recorder's own
        hooks) SIGTERM/unhandled-exception.  Safe to call repeatedly —
        the dump latches on first use."""
        if self.events is not None:
            try:
                self.events.flush(fsync=True)
            except Exception:
                pass
        if self.flight is not None:
            return self.flight.dump(reason)
        return None

    def finish(self) -> None:
        """End-of-training flush; the stats server stays up so callers
        (CI smoke, notebooks) can scrape final state before exit."""
        if self.perfetto is not None:
            self.perfetto.close()
        if self.slo is not None:
            self.slo.stop()
        if self.events is not None:
            self.events.write(
                "train_done",
                iterations=int(self._c_iters.value),
                anomalies=(self.monitor.anomaly_count()
                           if self.monitor is not None else 0))
        if self.flight is not None:
            # a completed run keeps its ring but disarms the global
            # SIGTERM/excepthook seams — post-training crashes belong to
            # the embedding application, not this booster
            self.flight.uninstall()
