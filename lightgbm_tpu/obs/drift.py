"""Train/serve drift detection: data profiles, PSI/JS, DriftMonitor.

The refit-trigger half of the model-observability layer (the training
half is obs/modelstats.py):

- ``DataProfile`` — per-feature bin-occupancy histograms captured over a
  ``BinnedDataset``'s ALREADY-binned int matrix (one bincount pass per
  feature; the data is quantized, so this is nearly free).  Each profiled
  feature carries its full ``BinMapper`` dict, so the serving side bins
  raw request values through the EXACT training quantization
  (``values_to_bins``) — no re-derived edges that could drift on their
  own.  JSON-serializable: persisted in checkpoint snapshot meta and
  carried by the serving ``ModelBundle``.
- ``psi`` / ``js_divergence`` — the two standard distribution-shift
  scores over matched bin counts, epsilon-smoothed so empty bins never
  produce infinities.
- ``DecayedSketch`` — an exponentially-decayed histogram of the model's
  raw score stream (edges anchored on the first observation window), so
  score-distribution shift is visible even when no single feature moves.
- ``DriftMonitor`` — the serving-side accumulator: ``observe`` bins each
  predict batch's raw rows against the profile, ``evaluate`` exports
  ``lgbm_drift_*`` gauges (federated across hosts by the PR 9
  ``/metrics/cluster`` merge like any other registry series), routes
  warn-only reports through ``HealthMonitor.note_drift`` past the
  ``obs_drift_warn_psi`` threshold, and fires ``on_drift`` subscriber
  hooks on every ok->warn transition — the seam ``CheckpointWatcher``
  (serving/registry.py ``arm_drift_refit``) uses as the future
  continuous-refit trigger.

No profile is always a legal state (models predate this layer): every
status surface returns an explicit ``"no_profile"`` rather than warning
or refusing.
"""
from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..log import Log
from .registry import MetricsRegistry, get_registry

PROFILE_VERSION = 1

# epsilon-smoothing for proportions: empty bins must not blow PSI/JS up
# to inf — the conventional small-floor treatment
_EPS = 1e-4


# --------------------------------------------------------------------------
# distribution-shift scores
# --------------------------------------------------------------------------
def _proportions(counts) -> np.ndarray:
    c = np.asarray(counts, np.float64).clip(min=0.0)
    p = c + _EPS
    return p / p.sum()


def psi(expected_counts, actual_counts) -> float:
    """Population Stability Index over matched bin counts.

    0 for identical distributions; conventional reading: < 0.1 stable,
    0.1-0.25 moderate shift, > 0.25 major shift (docs/Observability.md)."""
    p = _proportions(expected_counts)
    q = _proportions(actual_counts)
    return float(np.sum((q - p) * np.log(q / p)))


def js_divergence(expected_counts, actual_counts) -> float:
    """Jensen-Shannon divergence (natural log; bounded by ln 2)."""
    p = _proportions(expected_counts)
    q = _proportions(actual_counts)
    m = 0.5 * (p + q)
    kl = lambda a, b: float(np.sum(a * np.log(a / b)))  # noqa: E731
    return 0.5 * kl(p, m) + 0.5 * kl(q, m)


def psi_buckets(train_counts, k: int = 10) -> np.ndarray:
    """Fine-bin -> equal-mass-bucket aggregation map for PSI scoring.

    PSI over raw fine bins is dominated by sampling noise — for
    identical distributions its expectation is ~``(B-1) * (1/N_e +
    1/N_a)``, which at 255 bins swamps any real threshold.  The
    conventional remedy (and the industry convention for PSI) is ~10
    equal-population buckets of the REFERENCE distribution; this returns
    ``agg[fine_bin] -> bucket`` built from cumulative training mass.
    Features with <= k bins keep their bins 1:1."""
    c = np.asarray(train_counts, np.float64).clip(min=0.0)
    tot = c.sum()
    if tot <= 0 or len(c) <= k:
        return np.arange(len(c), dtype=np.int64)
    cum = np.cumsum(c) - c                     # train mass before each bin
    agg = np.minimum(np.floor(cum * k / tot).astype(np.int64), k - 1)
    _, agg = np.unique(agg, return_inverse=True)  # consecutive bucket ids
    return agg.astype(np.int64)


# --------------------------------------------------------------------------
# training data profile
# --------------------------------------------------------------------------
class DataProfile:
    """Per-feature bin-occupancy histograms of the training data.

    ``features`` is a list of dicts: ``index`` (ORIGINAL feature index),
    ``name``, ``mapper`` (the feature's ``BinMapper.to_dict()``) and
    ``counts`` (length ``num_bin`` occupancy of the training rows)."""

    def __init__(self, features: List[Dict], num_data: int = 0):
        self.features = features
        self.num_data = int(num_data)

    def __len__(self) -> int:
        return len(self.features)

    @classmethod
    def from_binned_dataset(cls, ds) -> "DataProfile":
        """Profile a ``BinnedDataset`` from its stored int matrix.

        Decoding mirrors ``core.grow.decode_bundle_value`` — EFB bundle
        offsets and joint-coded pair columns unpack to each feature's own
        bin — so the counts are exactly the histogram the grower sees."""
        (feat_col, feat_offset, _bundled, pack_div, pack_mod,
         _partner) = ds.feature_layout()
        xb = np.asarray(ds.X_binned)
        feats: List[Dict] = []
        for i in range(ds.num_features):
            j = ds.real_feature_index(i)
            m = ds.bin_mappers[j]
            v = xb[:, int(feat_col[i])].astype(np.int64)
            if int(pack_mod[i]) > 0:
                v = (v // max(int(pack_div[i]), 1)) % int(pack_mod[i])
            v = v - int(feat_offset[i])
            v = np.where((v >= 0) & (v < m.num_bin), v, m.default_bin)
            counts = np.bincount(v, minlength=m.num_bin)
            feats.append({
                "index": int(j),
                "name": (ds.feature_names[j] if j < len(ds.feature_names)
                         else "Column_%d" % j),
                "mapper": m.to_dict(),
                "counts": [int(c) for c in counts],
            })
        return cls(feats, num_data=int(ds.num_data))

    @classmethod
    def from_binned_chunks(cls, ds) -> "DataProfile":
        """Profile a ``StreamedDataset`` chunk-by-chunk.

        Bin counts are additive over row partitions, so accumulating the
        same per-feature decode (bundle offset, joint-pack unpack, clamp
        to default_bin) per chunk yields bit-identical counts to
        ``from_binned_dataset`` on the concatenated matrix — asserted in
        tests/test_stream.py. Under a sharded ingest (``shard_comm`` set)
        every rank profiles only its local chunks and the integer count
        vectors are summed over the host allgather — integer addition is
        associative, so the merged profile matches the single-process
        profile bit-identically (COLLECTIVE: all ranks must call this in
        lockstep; training_state capture does)."""
        (feat_col, feat_offset, _bundled, pack_div, pack_mod,
         _partner) = ds.feature_layout()
        nfeat = ds.num_features
        mappers = [ds.bin_mappers[ds.real_feature_index(i)]
                   for i in range(nfeat)]
        counts = [np.zeros(m.num_bin, np.int64) for m in mappers]
        for xb in ds.chunks:
            xb = np.asarray(xb)
            for i in range(nfeat):
                m = mappers[i]
                v = xb[:, int(feat_col[i])].astype(np.int64)
                if int(pack_mod[i]) > 0:
                    v = (v // max(int(pack_div[i]), 1)) % int(pack_mod[i])
                v = v - int(feat_offset[i])
                v = np.where((v >= 0) & (v < m.num_bin), v, m.default_bin)
                counts[i] += np.bincount(v, minlength=m.num_bin)
        comm = getattr(ds, "shard_comm", None)
        if comm is not None:
            gathered = comm.allgather(
                [np.asarray(c, np.int64) for c in counts])
            counts = [np.sum([np.asarray(g[i], np.int64)
                              for g in gathered], axis=0)
                      for i in range(nfeat)]
        feats: List[Dict] = []
        for i in range(nfeat):
            j = ds.real_feature_index(i)
            feats.append({
                "index": int(j),
                "name": (ds.feature_names[j] if j < len(ds.feature_names)
                         else "Column_%d" % j),
                "mapper": mappers[i].to_dict(),
                "counts": [int(c) for c in counts[i]],
            })
        return cls(feats, num_data=int(ds.num_data))

    # ----------------------------------------------------- serialization
    def to_json_dict(self) -> Dict:
        return {"version": PROFILE_VERSION, "num_data": self.num_data,
                "features": self.features}

    @classmethod
    def from_json_dict(cls, d: Optional[Dict]) -> Optional["DataProfile"]:
        """Tolerant inverse: None/malformed input -> None (pre-profile
        snapshots and model files must keep loading unchanged)."""
        if not isinstance(d, dict) or "features" not in d:
            return None
        try:
            feats = [dict(f) for f in d["features"]]
            return cls(feats, num_data=int(d.get("num_data", 0)))
        except Exception as e:  # noqa: BLE001 - corrupt profile != fatal
            Log.warning("drift: ignoring unreadable data profile (%s)" % e)
            return None


# --------------------------------------------------------------------------
# decayed score sketch
# --------------------------------------------------------------------------
class DecayedSketch:
    """Exponentially-decayed histogram + moments of a scalar stream.

    Edges anchor on the first ``anchor`` observations (serving score
    ranges are unknown until traffic arrives); after anchoring, each
    batch decays all prior mass by ``decay ** batch_rows`` so the sketch
    tracks the RECENT distribution."""

    def __init__(self, num_bins: int = 32, decay: float = 0.999,
                 anchor: int = 256):
        self.num_bins = int(num_bins)
        self.decay = float(decay)
        self._anchor = max(int(anchor), 2)
        self._seed: List[float] = []
        self.edges: Optional[np.ndarray] = None    # interior edges [B-1]
        self.counts: Optional[np.ndarray] = None   # decayed mass [B]
        self._sum = 0.0
        self._sumsq = 0.0
        self._weight = 0.0
        self.rows = 0

    def _anchor_edges(self) -> None:
        vals = np.asarray(self._seed, np.float64)
        lo, hi = float(vals.min()), float(vals.max())
        if hi <= lo:
            hi = lo + 1.0
        span = hi - lo
        # 10% margin: scores drifting slightly past the seed range should
        # land in edge bins, not all pile into the overflow slots
        self.edges = np.linspace(lo - 0.1 * span, hi + 0.1 * span,
                                 self.num_bins - 1)
        self.counts = np.zeros(self.num_bins, np.float64)
        self._seed = []
        self._add(vals)

    def _add(self, vals: np.ndarray) -> None:
        idx = np.searchsorted(self.edges, vals)
        np.add.at(self.counts, idx, 1.0)
        self._sum += float(vals.sum())
        self._sumsq += float((vals * vals).sum())
        self._weight += len(vals)

    def observe(self, values) -> None:
        vals = np.asarray(values, np.float64).ravel()
        vals = vals[np.isfinite(vals)]
        if not len(vals):
            return
        self.rows += len(vals)
        if self.edges is None:
            self._seed.extend(float(v) for v in vals)
            if len(self._seed) >= self._anchor:
                self._anchor_edges()
            return
        d = self.decay ** len(vals)
        self.counts *= d
        self._sum *= d
        self._sumsq *= d
        self._weight *= d
        self._add(vals)

    def summary(self) -> Dict:
        if self.edges is None:
            vals = np.asarray(self._seed, np.float64)
            mean = float(vals.mean()) if len(vals) else 0.0
            std = float(vals.std()) if len(vals) else 0.0
            return {"rows": self.rows, "anchored": False,
                    "mean": mean, "std": std}
        w = max(self._weight, 1e-12)
        mean = self._sum / w
        var = max(self._sumsq / w - mean * mean, 0.0)
        return {"rows": self.rows, "anchored": True,
                "mean": mean, "std": math.sqrt(var),
                "counts": [round(float(c), 3) for c in self.counts],
                "edges": [float(e) for e in self.edges]}


# --------------------------------------------------------------------------
# serving-side monitor
# --------------------------------------------------------------------------
class DriftMonitor:
    """Online train/serve drift scorer for one served model.

    ``observe(X)`` bins each predict batch's raw rows through the stored
    training quantization and accumulates per-feature occupancy;
    ``evaluate()`` (called automatically every ``eval_every`` observed
    rows) computes PSI/JS per feature against the training profile and
    exports ``lgbm_drift_*`` gauges.  Crossing ``warn_psi`` routes a
    warn-only report through ``HealthMonitor.note_drift`` and fires every
    ``on_drift`` subscriber once per ok->warn transition."""

    def __init__(self, profile: Optional[DataProfile], model_id: str = "",
                 warn_psi: float = 0.25, min_rows: int = 256,
                 decay: float = 0.999, eval_every: int = 256,
                 buckets: int = 10,
                 registry: Optional[MetricsRegistry] = None,
                 monitor=None, events=None):
        from ..io.binning import BinMapper
        self.profile = profile
        self.model_id = str(model_id)
        self.warn_psi = float(warn_psi)
        self.min_rows = int(min_rows)
        self.eval_every = max(int(eval_every), 1)
        self._lock = threading.Lock()
        self._reg = registry if registry is not None else get_registry()
        self._monitor = monitor
        self._events = events
        self._hooks: List[Callable] = []
        self._warned = False
        self.rows = 0
        self._rows_at_eval = 0
        self.scores = DecayedSketch(decay=decay)
        self._feats: List[Dict] = []
        if profile is not None:
            for f in profile.features:
                counts = np.asarray(f["counts"], np.float64)
                # PSI/JS score over equal-mass buckets of the TRAINING
                # distribution (see psi_buckets) — fine bins stay only as
                # the digitization alphabet
                agg = psi_buckets(counts, int(buckets))
                nb = int(agg.max()) + 1 if len(agg) else 1
                self._feats.append({
                    "index": int(f["index"]),
                    "name": str(f.get("name", "Column_%d" % f["index"])),
                    "mapper": BinMapper.from_dict(f["mapper"]),
                    "agg": agg,
                    "train": np.bincount(agg, weights=counts,
                                         minlength=nb),
                    "serve": np.zeros(nb, np.float64),
                    "psi": 0.0, "js": 0.0,
                })
        mlbl = {"model": self.model_id}
        self._g_rows = self._reg.gauge(
            "lgbm_drift_rows", "Rows observed by the drift monitor.", mlbl)
        self._g_psi_max = self._reg.gauge(
            "lgbm_drift_psi_max",
            "Largest per-feature PSI vs the training profile.", mlbl)
        self._g_score_mean = self._reg.gauge(
            "lgbm_drift_score_mean",
            "Decayed mean of the served score stream.", mlbl)
        self._feat_gauges: Dict[str, tuple] = {}

    # ------------------------------------------------------------ wiring
    @property
    def has_profile(self) -> bool:
        return self.profile is not None and len(self._feats) > 0

    def on_drift(self, hook: Callable) -> None:
        """Subscribe ``hook(report_dict)`` to ok->warn transitions — the
        refit-trigger seam (CheckpointWatcher.arm_drift_refit)."""
        self._hooks.append(hook)

    # ------------------------------------------------------------ ingest
    def observe(self, X, scores=None) -> None:
        """Fold one predict batch: ``X`` raw float rows [n, num_features]
        (the serving hot path's input), ``scores`` the model outputs."""
        if scores is not None:
            self.scores.observe(np.asarray(scores, np.float64))
        if not self.has_profile:
            return
        X = np.asarray(X)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        with self._lock:
            self.rows += X.shape[0]
            for f in self._feats:
                j = f["index"]
                if j >= X.shape[1]:
                    continue
                bins = f["mapper"].values_to_bins(
                    np.asarray(X[:, j], np.float64))
                bidx = f["agg"][np.clip(bins, 0, len(f["agg"]) - 1)]
                np.add.at(f["serve"], bidx, 1.0)
            due = self.rows - self._rows_at_eval >= self.eval_every
        if due:
            self.evaluate()

    # ------------------------------------------------------------ scoring
    def _feat_gauge(self, name: str):
        g = self._feat_gauges.get(name)
        if g is None:
            lbl = {"model": self.model_id, "feature": name}
            g = (self._reg.gauge(
                    "lgbm_drift_psi",
                    "Per-feature PSI of serving traffic vs the training "
                    "profile.", lbl),
                 self._reg.gauge(
                    "lgbm_drift_js",
                    "Per-feature Jensen-Shannon divergence vs the "
                    "training profile.", lbl))
            self._feat_gauges[name] = g
        return g

    def evaluate(self) -> Dict:
        """Score the accumulated occupancy, export gauges, route warns.
        Returns the status dict (same shape as ``status()``)."""
        if not self.has_profile:
            return self.status()
        with self._lock:
            self._rows_at_eval = self.rows
            enough = self.rows >= self.min_rows
            for f in self._feats:
                if f["serve"].sum() <= 0:
                    continue
                f["psi"] = psi(f["train"], f["serve"])
                f["js"] = js_divergence(f["train"], f["serve"])
            worst = max((f["psi"] for f in self._feats), default=0.0)
            feats = [(f["name"], f["psi"], f["js"]) for f in self._feats]
        self._g_rows.set(self.rows)
        self._g_psi_max.set(worst)
        sc = self.scores.summary()
        self._g_score_mean.set(sc.get("mean", 0.0))
        for name, p, j in feats:
            gp, gj = self._feat_gauge(name)
            gp.set(p)
            gj.set(j)
        if enough and worst >= self.warn_psi and not self._warned:
            self._warned = True
            self._fire(worst)
        elif self._warned and worst < 0.5 * self.warn_psi:
            # re-arm after clear recovery so a later second shift still
            # warns (half-threshold hysteresis avoids flapping)
            self._warned = False
        return self.status()

    def _fire(self, worst_psi: float) -> None:
        with self._lock:
            top = sorted(self._feats, key=lambda f: -f["psi"])[:3]
            names = ", ".join("%s=%.3f" % (f["name"], f["psi"])
                              for f in top)
        report = {"model": self.model_id, "max_psi": float(worst_psi),
                  "threshold": self.warn_psi, "rows": self.rows,
                  "top_features": names}
        if self._monitor is not None:
            try:
                self._monitor.note_drift(self.model_id, names,
                                         float(worst_psi), self.warn_psi,
                                         rows=self.rows)
            except Exception as e:  # noqa: BLE001
                Log.warning("drift: health routing failed: %s" % e)
        else:
            Log.warning(
                "drift: model %s serving traffic drifted from its training "
                "profile (max PSI %.3f >= %.3f over %d rows; %s)"
                % (self.model_id, worst_psi, self.warn_psi, self.rows,
                   names))
        if self._events is not None:
            try:
                self._events.write("drift", **report)
            except Exception:  # noqa: BLE001
                pass
        for hook in list(self._hooks):
            try:
                hook(report)
            except Exception as e:  # noqa: BLE001
                Log.warning("drift: on_drift hook failed: %s" % e)

    # ------------------------------------------------------------ export
    def status(self) -> Dict:
        """JSON view for the ``/drift`` routes and ``/healthz`` field."""
        if not self.has_profile:
            return {"status": "no_profile", "model": self.model_id,
                    "rows": self.rows,
                    "score_sketch": self.scores.summary()}
        with self._lock:
            worst = max((f["psi"] for f in self._feats), default=0.0)
            feats = {f["name"]: {"psi": round(f["psi"], 6),
                                 "js": round(f["js"], 6),
                                 "rows": int(f["serve"].sum())}
                     for f in self._feats}
        warn = self.rows >= self.min_rows and worst >= self.warn_psi
        return {"status": "warn" if warn else "ok",
                "model": self.model_id, "rows": self.rows,
                "max_psi": round(worst, 6), "warn_psi": self.warn_psi,
                "features": feats,
                "score_sketch": self.scores.summary()}


# --------------------------------------------------------------------------
# process-wide monitor registry (the /drift route's data source)
# --------------------------------------------------------------------------
_MONITORS: Dict[str, DriftMonitor] = {}
_MON_LOCK = threading.Lock()


def register_monitor(mon: DriftMonitor) -> DriftMonitor:
    """Publish a monitor under its model id so every stats surface
    (training StatsServer ``/drift``, serving ``/drift``) sees it."""
    with _MON_LOCK:
        _MONITORS[mon.model_id] = mon
    return mon


def unregister_monitor(model_id: str) -> None:
    with _MON_LOCK:
        _MONITORS.pop(str(model_id), None)


def get_monitor(model_id: str) -> Optional[DriftMonitor]:
    with _MON_LOCK:
        return _MONITORS.get(str(model_id))


def drift_snapshot() -> Dict:
    """Aggregate ``/drift`` body: every registered monitor's status plus
    the worst overall verdict (``warn`` > ``ok`` > ``no_profile``)."""
    with _MON_LOCK:
        mons = list(_MONITORS.values())
    models = {m.model_id: m.status() for m in mons}
    statuses = [s["status"] for s in models.values()]
    if "warn" in statuses:
        overall = "warn"
    elif "ok" in statuses:
        overall = "ok"
    else:
        overall = "no_profile"
    return {"status": overall, "models": models}
