"""Process-wide metrics registry: counters, gauges, summaries, histograms.

One registry serves the whole process — training spans, health monitors,
compile-cache accounting and the serving path all register here, so a
single Prometheus scrape (serving ``/metrics/prometheus`` or the training
stats endpoint) sees everything.  Metrics are keyed by ``(name, labels)``
and get-or-create is idempotent: calling ``counter("x")`` twice returns
the same object, which is what lets serving/metrics.py and profiling.py
share series without import-order coupling.

Thread safety: the registry map has its own lock and every metric guards
its state with one; all mutators are O(1) (summaries append to a bounded
deque), so hot paths never contend on a global lock.

This module deliberately imports no jax/numpy at module scope — the
serving server and the stats endpoint must be importable in processes
that never touch a device.
"""
from __future__ import annotations

import bisect
import collections
import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

_QUANTILES = (0.5, 0.9, 0.99)


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral floats print as integers so the
    exposition (and the golden test pinning it) stays stable."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping (backslash, newline, quote) for
    exposition emitted OUTSIDE this module — e.g. the fleet's hand-built
    per-replica rows, where a hostile replica/model name must not be able
    to smuggle extra labels or break a scrape."""
    return _escape_label(v)


def _escape_help(v: str) -> str:
    # HELP escaping per the 0.0.4 text format: backslash then line feed
    # (label-value quoting does NOT apply here)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_suffix(labels: Tuple[Tuple[str, str], ...],
                  extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = tuple(labels) + tuple(extra)
    if not items:
        return ""
    return "{%s}" % ",".join('%s="%s"' % (k, _escape_label(v))
                             for k, v in items)


class _Metric:
    """Shared plumbing: identity, lock, label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = threading.Lock()

    @property
    def label_dict(self) -> Dict[str, str]:
        return dict(self.labels)


class Counter(_Metric):
    """Monotonic counter.  ``inc`` only; negative increments are clamped."""

    kind = "counter"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            return
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self):
        return [(self.name, self.labels, self.value)]


class Gauge(_Metric):
    """Settable instantaneous value."""

    kind = "gauge"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self):
        return [(self.name, self.labels, self.value)]


class Summary(_Metric):
    """Bounded-window distribution exposed as a Prometheus summary:
    ``{quantile="..."}`` series over the last ``window`` observations plus
    lifetime ``_sum`` / ``_count``.  A windowed summary is the right tool
    for serving latency (and span durations) — it answers "p99 lately",
    not "p99 since process start"."""

    kind = "summary"

    def __init__(self, name, help, labels, window: int = 4096):
        super().__init__(name, help, labels)
        self._window = collections.deque(maxlen=window)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            v = float(value)
            self._window.append(v)
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def values(self) -> List[float]:
        """Copy of the current observation window (oldest first)."""
        with self._lock:
            return list(self._window)

    @property
    def total(self) -> float:
        """Lifetime sum of observations (the ``_sum`` series)."""
        with self._lock:
            return self._sum

    def quantiles(self) -> Dict[float, float]:
        # copy under the lock, sort OUTSIDE it: the O(n log n) sort over
        # the 4096-sample window must not stall hot-path observe() calls
        # while a scrape serializes (tests/test_obs_export.py hammers
        # this exact interleaving)
        with self._lock:
            data = list(self._window)
        data.sort()
        if not data:
            return {q: 0.0 for q in _QUANTILES}
        out = {}
        for q in _QUANTILES:
            # nearest-rank on the sorted window; matches latency_summary's
            # numpy percentile to within one sample
            idx = min(len(data) - 1, max(0, int(round(q * (len(data) - 1)))))
            out[q] = data[idx]
        return out

    def samples(self):
        qs = self.quantiles()
        with self._lock:
            s, c = self._sum, self._count
        rows = [(self.name, self.labels + (("quantile", "%g" % q),), qs[q])
                for q in _QUANTILES]
        rows.append((self.name + "_sum", self.labels, s))
        rows.append((self.name + "_count", self.labels, c))
        return rows


class Histogram(_Metric):
    """Prometheus histogram: cumulative ``_bucket{le="..."}`` counts over
    fixed bounds plus lifetime ``_sum`` / ``_count``.  Unlike Summary's
    windowed quantiles — which cannot be aggregated after the fact —
    bucket counts sum across processes and scrape intervals, which is
    what serving request latency needs once more than one serving
    process feeds a dashboard.  Bounds are configurable per metric and
    fixed at registration (the first caller wins, like ``help``)."""

    kind = "histogram"

    # seconds-scale defaults (Prometheus client convention); latency-in-ms
    # callers pass their own bounds
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0)

    def __init__(self, name, help, labels,
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(float(b) for b in
                              (buckets if buckets is not None
                               else self.DEFAULT_BUCKETS)))
        if not bounds:
            raise ValueError("histogram %r needs at least one bucket bound"
                             % name)
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # trailing +Inf bucket
        self._sum = 0.0

    @property
    def bounds(self) -> Tuple[float, ...]:
        return self._bounds

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            # le is inclusive: the first bound >= v owns the observation
            self._counts[bisect.bisect_left(self._bounds, v)] += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def total(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile from the bucket counts — the same
        linear-interpolation-within-the-owning-bucket estimate Prometheus'
        ``histogram_quantile`` makes (lower edge 0 for the first bucket;
        observations in the +Inf bucket clamp to the last finite bound).
        Coarse by construction, but aggregatable — unlike a windowed
        quantile — which is why serving's per-bucket latency view rides
        it (docs/Serving.md).

        Hardened edge cases (the serving p99 SLO gate in
        tools/load_test.py consumes this and must never see NaN/None):
        an empty histogram returns 0.0; a non-finite ``q`` raises instead
        of propagating NaN through the comparisons; observations that
        only ever landed in the first bucket interpolate within
        ``[0, bounds[0]]``; everything in the +Inf overflow bucket clamps
        to the last finite bound; a non-finite bucket bound clamps to the
        bucket's lower edge."""
        qf = float(q)
        if qf != qf or qf in (float("inf"), float("-inf")):
            raise ValueError("histogram quantile q must be finite, got %r"
                             % q)
        qf = min(max(qf, 0.0), 1.0)
        with self._lock:
            counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = qf * total
        cum = 0.0
        lo = 0.0
        for bound, c in zip(self._bounds, counts):
            if c > 0 and cum + c >= rank:
                frac = min(max((rank - cum) / c, 0.0), 1.0)
                if bound - lo != bound - lo or bound == float("inf"):
                    return lo          # non-finite bound: clamp, not NaN
                return lo + (bound - lo) * frac
            cum += c
            lo = bound
        # every observation sits in the +Inf overflow bucket: the last
        # finite bound is the best (and only finite) answer
        return self._bounds[-1]

    def bucket_counts(self) -> Tuple[Tuple[float, ...], Tuple[int, ...]]:
        """``(bounds, per-bucket counts)`` copy — counts are NON-cumulative
        and the trailing entry is the +Inf overflow bucket.  The SLO
        engine reads bad-fractions from this."""
        with self._lock:
            return self._bounds, tuple(self._counts)

    def samples(self):
        with self._lock:
            counts = list(self._counts)
            s = self._sum
        rows = []
        cum = 0
        for bound, c in zip(self._bounds, counts):
            cum += c
            rows.append((self.name + "_bucket",
                         self.labels + (("le", "%g" % bound),), cum))
        cum += counts[-1]
        rows.append((self.name + "_bucket",
                     self.labels + (("le", "+Inf"),), cum))
        rows.append((self.name + "_sum", self.labels, s))
        rows.append((self.name + "_count", self.labels, cum))
        return rows


class MetricsRegistry:
    """Get-or-create registry over ``(name, labels)`` keyed metrics."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "summary": Summary,
              "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, _Metric] = {}
        self._help: Dict[str, str] = {}
        # constant labels appended to EVERY exported sample (exposition
        # time only — call sites and stored metric keys never see them).
        # Distributed training sets process=<index>/host=<name> here so
        # per-process scrapes federate without relabeling (ISSUE 10);
        # empty by default, which keeps the golden exposition byte-stable.
        self._global_labels: Tuple[Tuple[str, str], ...] = ()

    def set_global_labels(self, labels: Optional[Dict[str, str]]) -> None:
        """Install constant labels injected into every exported sample
        (``prometheus_text`` and ``snapshot``).  Pass None/{} to clear."""
        with self._lock:
            self._global_labels = tuple(sorted(
                (str(k), str(v)) for k, v in (labels or {}).items()))

    def global_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._global_labels)

    def _get(self, kind: str, name: str, help: str,
             labels: Optional[Dict[str, str]], **kw) -> _Metric:
        lbl = tuple(sorted((str(k), str(v))
                           for k, v in (labels or {}).items()))
        key = (name, lbl)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._KINDS[kind](name, help, lbl, **kw)
                self._metrics[key] = m
                self._help.setdefault(name, help)
            elif m.kind != kind:
                raise ValueError("metric %r already registered as %s, "
                                 "requested %s" % (name, m.kind, kind))
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get("gauge", name, help, labels)

    def summary(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None,
                window: int = 4096) -> Summary:
        return self._get("summary", name, help, labels, window=window)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get("histogram", name, help, labels, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # ------------------------------------------------------------ export
    def collect(self):
        """Point-in-time sample gather: ``(global_labels, [(name, kind,
        help, [(sample_name, labels, value), ...]), ...])`` sorted by
        family name then label set.  Every lock (registry map, each
        metric's state) is released before this returns — serialization
        (Prometheus text, JSON) happens on the caller's time, never while
        a hot path waits to observe.  Both exposition routes and the
        StatsServer build their bodies from this."""
        with self._lock:
            extra = self._global_labels
        families: Dict[str, List[_Metric]] = {}
        for m in self.metrics():
            families.setdefault(m.name, []).append(m)
        out = []
        for name in sorted(families):
            group = families[name]
            rows = []
            for m in sorted(group, key=lambda m: m.labels):
                rows.extend(m.samples())
            out.append((name, group[0].kind, self._help.get(name, ""), rows))
        return extra, out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4.  Families sorted by
        name, series by label string — the output is deterministic for a
        given registry state (the golden test pins it)."""
        extra, families = self.collect()
        lines = []
        for name, kind, help_txt, rows in families:
            if help_txt:
                lines.append("# HELP %s %s" % (name, _escape_help(help_txt)))
            lines.append("# TYPE %s %s" % (name, kind))
            for sample_name, labels, value in rows:
                lines.append("%s%s %s"
                             % (sample_name, _label_suffix(labels, extra),
                                _fmt_value(value)))
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict:
        """Flat JSON view: ``name{k="v"}`` -> value (summaries expand to
        quantile/sum/count keys)."""
        extra, families = self.collect()
        out: Dict[str, float] = {}
        for _, _, _, rows in families:
            for sample_name, labels, value in rows:
                out[sample_name + _label_suffix(labels, extra)] = value
        return {"ts": round(time.time(), 3), "metrics": out}

    def write_jsonl(self, path_or_fh) -> Dict:
        """Append one snapshot as a JSON line; returns the snapshot."""
        snap = self.snapshot()
        line = json.dumps(snap, sort_keys=True) + "\n"
        if hasattr(path_or_fh, "write"):
            path_or_fh.write(line)
            path_or_fh.flush()
        else:
            with open(path_or_fh, "a") as fh:
                fh.write(line)
        return snap


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem shares."""
    return _REGISTRY
