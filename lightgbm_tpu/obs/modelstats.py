"""Model statistics: split-gain introspection + streaming importance.

Two halves, mirroring obs/health.py's device/host split:

Device side — a per-tree ``f32[F, MS_WIDTH]`` accumulator piggy-backed on
the frontier grower's wave loop (``_FrontierState.mstats``), scatter-added
from values every wave ALREADY computed: the committed lanes' feature
indices and top-k gains both derive from the per-wave psum'd histograms,
so the accumulator adds ZERO collectives (tests/test_modelstats.py pins
psums/wave with modelstats ON) and, being an ``Optional`` carry leaf that
is ``None`` when off, leaves the compiled program byte-identical when
``obs_modelstats`` is not set.

Host side — ``ModelStats`` ingests the fetched accumulators (or, on
growth paths without the piggy-back, recomputes from the materialized
HostTrees) exactly at flush time, so its cumulative state tracks the KEPT
model list even across device-detected stops.  It streams:

- ``lgbm_model_split_count/gain_total/gain_max{feature=}`` gauges,
- ``lgbm_model_leaf_value`` / ``lgbm_model_leaf_depth`` summaries,
- ``lgbm_model_trees`` / ``lgbm_model_gain_mass`` / new-leaf gauges,
- per-iteration ``model_iter`` EventStream records (the learning-curve
  companion to engine.train's ``lgbm_eval_metric`` gauges),

and answers ``importance("split"|"gain")`` with reference-LightGBM
semantics (per ORIGINAL feature index; gains summed over every committed
split) — tested for exact agreement with ``GBDT.feature_importance``'s
host-side recomputation.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .registry import MetricsRegistry, get_registry

# layout of the device accumulator: f32[F_inner, MS_WIDTH] per grown tree
MS_COUNT = 0      # committed splits on the feature
MS_GAIN_SUM = 1   # total committed split gain
MS_GAIN_MAX = 2   # max committed split gain
MS_WIDTH = 3


def init_mstats(num_features: int):
    """Zero accumulator seeded into the frontier state (root wave)."""
    import jax.numpy as jnp
    return jnp.zeros((int(num_features), MS_WIDTH), jnp.float32)


def update_mstats(mstats, feature, gain, valid):
    """Scatter one wave's committed splits into the accumulator.

    ``feature``/``gain``/``valid`` are the wave's ``[kw]`` top-k lanes
    (inner feature index, ranked gain, commit mask) — values the wave step
    computed anyway from the psum'd histograms, so the update is two
    scatter-adds and a scatter-max with no new sweeps or collectives.
    Invalid lanes route to row ``F`` and drop.
    """
    import jax.numpy as jnp
    f = mstats.shape[0]
    idx = jnp.where(valid, feature.astype(jnp.int32), f)
    g = jnp.where(valid, gain, 0.0)
    m = mstats.at[idx, MS_COUNT].add(valid.astype(jnp.float32), mode="drop")
    m = m.at[idx, MS_GAIN_SUM].add(g, mode="drop")
    m = m.at[idx, MS_GAIN_MAX].max(g, mode="drop")
    return m


def leaf_depths(ht) -> np.ndarray:
    """Per-leaf depths of a HostTree, replayed from the split order.

    Node ``t`` splits leaf ``split_leaf[t]``; the left child keeps the
    parent's leaf index and the right child becomes leaf ``t + 1`` (the
    numbering _replay_leaves_binned routes by), so one pass over the
    nodes in commit order reconstructs every leaf's final depth."""
    nl = int(getattr(ht, "num_leaves_actual", ht.num_leaves))
    depth = np.zeros(max(nl, 1), np.int32)
    for t in range(nl - 1):
        leaf = int(ht.split_leaf[t])
        if leaf < 0:
            continue
        d = depth[leaf] + 1
        depth[leaf] = d
        depth[t + 1] = d
    return depth[:max(nl, 1)]


class ModelStats:
    """Cumulative training-side model statistics (host half).

    ``inner_to_real`` maps the device accumulator's inner (stored)
    feature indices to original dataset indices — the same map
    ``_extract_host_tree`` applies to split features — so device-fed and
    tree-fed statistics land in the same per-feature slots."""

    def __init__(self, num_features: int,
                 feature_names: Optional[List[str]] = None,
                 inner_to_real=None,
                 registry: Optional[MetricsRegistry] = None,
                 events=None):
        self.num_features = int(num_features)
        self.feature_names = (list(feature_names) if feature_names
                              else ["Column_%d" % i
                                    for i in range(self.num_features)])
        self._inner_to_real = (np.asarray(inner_to_real, np.int64)
                               if inner_to_real is not None else None)
        self.split_count = np.zeros(self.num_features, np.float64)
        self.gain_total = np.zeros(self.num_features, np.float64)
        self.gain_max = np.zeros(self.num_features, np.float64)
        self.trees = 0
        self.iterations = 0
        self._events = events
        reg = registry if registry is not None else get_registry()
        self._reg = reg
        self._g_trees = reg.gauge(
            "lgbm_model_trees", "Materialized trees in the model so far.")
        self._g_gain_mass = reg.gauge(
            "lgbm_model_gain_mass",
            "Cumulative split gain across all features and trees.")
        self._g_new_leaves = reg.gauge(
            "lgbm_model_new_leaves_last",
            "Leaves grown by the most recent materialized iteration.")
        self._s_leaf_value = reg.summary(
            "lgbm_model_leaf_value",
            "Leaf output values of materialized trees (post-shrinkage).")
        self._s_leaf_depth = reg.summary(
            "lgbm_model_leaf_depth",
            "Leaf depths of materialized trees.")
        self._feat_gauges = {}

    # ------------------------------------------------------------ ingest
    def _real_index(self, inner: int) -> int:
        if self._inner_to_real is None:
            return inner if inner < self.num_features else -1
        if inner >= len(self._inner_to_real):
            return -1   # mesh feature padding: never splits, never counted
        return int(self._inner_to_real[inner])

    def ingest_device(self, rows) -> float:
        """Fold one KEPT iteration's device accumulators ``[K, F_inner,
        MS_WIDTH]`` into the cumulative per-feature state; returns the
        iteration's gain mass."""
        rows = np.asarray(rows, np.float64)
        if rows.ndim == 2:
            rows = rows[None]
        agg = rows.sum(axis=0)                     # [F, W] count/gain sums
        mx = rows[..., MS_GAIN_MAX].max(axis=0)    # [F]
        for i in np.nonzero(agg[:, MS_COUNT] > 0)[0]:
            j = self._real_index(int(i))
            if j < 0:
                continue
            self.split_count[j] += agg[i, MS_COUNT]
            self.gain_total[j] += agg[i, MS_GAIN_SUM]
            self.gain_max[j] = max(self.gain_max[j], float(mx[i]))
        return float(agg[:, MS_GAIN_SUM].sum())

    def _ingest_tree_splits(self, ht) -> float:
        """Host fallback (exact/mesh growth paths): fold one materialized
        tree's committed splits from its arrays.  ``split_feature`` is
        already in ORIGINAL index space here."""
        mass = 0.0
        for i in range(int(getattr(ht, "num_leaves_actual",
                                   ht.num_leaves)) - 1):
            if ht.split_leaf[i] < 0:
                continue
            j = int(ht.split_feature[i])
            g = float(ht.split_gain[i])
            if 0 <= j < self.num_features:
                self.split_count[j] += 1
                self.gain_total[j] += g
                self.gain_max[j] = max(self.gain_max[j], g)
            mass += g
        return mass

    def ingest_iteration(self, host_trees, iteration: int,
                         device_rows=None) -> None:
        """One KEPT iteration's class trees at materialize time.

        ``device_rows`` is the frontier piggy-back's ``[K, F_inner,
        MS_WIDTH]`` fetch when available; without it (exact mode, mesh
        learners) the split statistics recompute from the trees."""
        new_leaves = 0
        for ht in host_trees:
            self.trees += 1
            nl = int(getattr(ht, "num_leaves_actual", ht.num_leaves))
            new_leaves += nl
            for d in leaf_depths(ht):
                self._s_leaf_depth.observe(float(d))
            for v in np.asarray(ht.leaf_value[:max(nl, 1)], np.float64):
                self._s_leaf_value.observe(float(v))
        if device_rows is not None:
            gain_mass = self.ingest_device(device_rows)
        else:
            gain_mass = sum(self._ingest_tree_splits(ht)
                            for ht in host_trees)
        self.iterations += 1
        self._publish(new_leaves)
        if self._events is not None:
            self._events.write("model_iter", iteration=int(iteration),
                               trees=self.trees,
                               new_leaves=int(new_leaves),
                               gain_iter=round(float(gain_mass), 6),
                               gain_mass=round(float(self.gain_total.sum()),
                                               6))

    # ------------------------------------------------------------ export
    def _gauges_for(self, j: int):
        g = self._feat_gauges.get(j)
        if g is None:
            name = (self.feature_names[j] if j < len(self.feature_names)
                    else "Column_%d" % j)
            lbl = {"feature": name}
            g = (self._reg.gauge("lgbm_model_split_count",
                                 "Committed splits per feature.", lbl),
                 self._reg.gauge("lgbm_model_gain_total",
                                 "Total committed split gain per feature.",
                                 lbl),
                 self._reg.gauge("lgbm_model_gain_max",
                                 "Largest committed split gain per feature.",
                                 lbl))
            self._feat_gauges[j] = g
        return g

    def _publish(self, new_leaves: int) -> None:
        self._g_trees.set(self.trees)
        self._g_gain_mass.set(float(self.gain_total.sum()))
        self._g_new_leaves.set(new_leaves)
        for j in np.nonzero(self.split_count > 0)[0]:
            gc, gt, gm = self._gauges_for(int(j))
            gc.set(float(self.split_count[j]))
            gt.set(float(self.gain_total[j]))
            gm.set(float(self.gain_max[j]))

    def importance(self, importance_type: str = "split") -> np.ndarray:
        """Streaming feature importance over ORIGINAL feature indices —
        reference semantics (``GBDT.feature_importance`` recomputes the
        same quantity from the tree dump; tests pin agreement)."""
        src = (self.split_count if importance_type == "split"
               else self.gain_total)
        return np.array(src, np.float64)
