"""Training health: device-side flag vector + host-side dispatch.

The device side is ``health_vec`` — a fixed-length f32 vector the jitted
training step computes from values it ALREADY has in registers:

- non-finite grad/hess: ``sum(g) + sum(h)`` is two cheap reductions over
  arrays the histogram sweep is about to read anyway; any NaN/Inf in
  either tensor poisons the scalar (NaN survives masking because
  ``NaN * 0 == NaN``), so one isfinite on the sum catches a single bad
  row.  No new dataset sweeps.
- zero-positive-gain wave ("stump"): reuses the grower's ``any_split``
  scalar — the iteration produced a tree with no split.
- frontier gain health: the wave loop piggy-backs a 2-scalar accumulator
  (waves executed, non-finite committed gain) on state it already
  carries; gains derive from the per-wave psum'd histograms, so the
  per-wave collective count is UNCHANGED (tests/test_obs.py pins this).

The host side is ``HealthMonitor``: it inspects the fetched vectors once
per dispatch (per iteration, or per fused block) and dispatches the
configured action — ``warn`` (log + count), ``abort``
(checkpoint-then-raise) or ``raise``.  Stump iterations are counted and
logged but never escalate: a converged model legitimately stops
splitting, while non-finite values never legitimately appear.
"""
from __future__ import annotations

from typing import List, Optional

from ..log import LightGBMError, Log
from .registry import MetricsRegistry, get_registry

# layout of the device health vector (f32[HEALTH_VEC_LEN] per iteration)
HEALTH_NONFINITE = 0        # 1.0 when grad/hess contain NaN/Inf
HEALTH_STUMP = 1            # 1.0 when the iteration grew no split
HEALTH_NONFINITE_GAIN = 2   # 1.0 when a committed frontier gain was NaN/Inf
HEALTH_WAVES = 3            # frontier waves executed (sum over trees)
HEALTH_VEC_LEN = 4

_ACTIONS = ("none", "warn", "abort", "raise")


def health_vec(grad, hess, any_split, grower_health=None):
    """Build the device health vector inside the jitted training step.

    ``grower_health``: optional f32[K, 2] per-class-tree (waves,
    nonfinite_gain) from the frontier grower, or None when the grower
    does not report (exact mode, mesh path)."""
    import jax.numpy as jnp

    total = jnp.sum(grad) + jnp.sum(hess)
    nonfinite = (~jnp.isfinite(total)).astype(jnp.float32)
    stump = (~any_split).astype(jnp.float32)
    if grower_health is None:
        waves = jnp.float32(0.0)
        bad_gain = jnp.float32(0.0)
    else:
        waves = jnp.sum(grower_health[..., 0])
        bad_gain = jnp.max(grower_health[..., 1])
    return jnp.stack([nonfinite, stump, bad_gain, waves])


class HealthReport:
    """One detected anomaly (or stump note) at a concrete iteration."""

    __slots__ = ("iteration", "kind", "message")

    def __init__(self, iteration: int, kind: str, message: str):
        self.iteration = iteration
        self.kind = kind
        self.message = message

    def __repr__(self):
        return "HealthReport(iter=%d, kind=%r)" % (self.iteration, self.kind)


class HealthMonitor:
    """Host-side inspector for fetched health vectors."""

    def __init__(self, action: str = "warn",
                 registry: Optional[MetricsRegistry] = None,
                 events=None, on_abort=None, on_fatal=None):
        if action not in _ACTIONS:
            raise LightGBMError("unknown health_monitor action %r "
                                "(expected one of %s)"
                                % (action, "/".join(_ACTIONS)))
        self.action = action
        self.reports: List[HealthReport] = []
        self._events = events
        self._on_abort = on_abort
        # invoked right before the monitor raises (abort AND raise):
        # TrainingObs hooks the flight-recorder dump + event fsync here
        # so the crash artifacts exist before the exception unwinds
        self._on_fatal = on_fatal
        reg = registry if registry is not None else get_registry()
        self._c_anomaly = reg.counter(
            "lgbm_train_health_anomalies_total",
            "Non-finite grad/hess or gain anomalies detected in training.")
        self._c_stump = reg.counter(
            "lgbm_train_stump_iterations_total",
            "Iterations that grew a tree with no split.")
        self._g_waves = reg.gauge(
            "lgbm_train_frontier_waves_last",
            "Frontier waves executed by the most recent iteration.")
        self._c_straggler = reg.counter(
            "lgbm_train_straggler_reports_total",
            "Straggler-skew reports routed through the health monitor "
            "(warn-only; stragglers never escalate).")
        self._c_drift = reg.counter(
            "lgbm_drift_reports_total",
            "Train/serve drift reports routed through the health monitor "
            "(warn-only; drift never escalates).")
        self._c_slo_burn = reg.counter(
            "lgbm_slo_burn_reports_total",
            "SLO budget-burn reports routed through the health monitor "
            "(warn-only; a burning budget never escalates).")

    def anomaly_count(self) -> int:
        return int(self._c_anomaly.value)

    def note_straggler(self, iteration: int, process: int, skew: float,
                       threshold: float) -> HealthReport:
        """Record a straggler-skew crossing from distributed obs.  Like
        stump iterations, stragglers warn and count but NEVER escalate —
        a slow peer is an infrastructure symptom, not a reason to abort
        an otherwise-healthy optimization."""
        r = HealthReport(
            int(iteration), "straggler_wave",
            "process %d is a straggler at iteration %d: block wall-time "
            "skew %.2fx >= warn threshold %.2fx"
            % (int(process), int(iteration), float(skew), float(threshold)))
        self.reports.append(r)
        self._c_straggler.inc()
        if self._events is not None:
            self._events.write("health", iteration=r.iteration, kind=r.kind,
                               message=r.message, process=int(process),
                               skew=round(float(skew), 4))
        Log.warning("health: %s" % r.message)
        return r

    def note_drift(self, model_id: str, features: str, max_psi: float,
                   threshold: float, rows: int = 0) -> HealthReport:
        """Record a train/serve drift crossing from obs.drift.  Like
        stragglers, drift warns and counts but NEVER escalates — shifted
        traffic is a refit trigger, not a reason to kill a server that is
        still answering correctly for its training distribution."""
        r = HealthReport(
            0, "data_drift",
            "model %s: serving traffic drifted from the training profile "
            "(max PSI %.3f >= warn threshold %.3f over %d rows; %s)"
            % (model_id, float(max_psi), float(threshold), int(rows),
               features))
        self.reports.append(r)
        self._c_drift.inc()
        if self._events is not None:
            self._events.write("health", iteration=0, kind=r.kind,
                               message=r.message, model=str(model_id),
                               max_psi=round(float(max_psi), 4))
        Log.warning("health: %s" % r.message)
        return r

    def note_slo_burn(self, slo: str, fast_burn: float, slow_burn: float,
                      observed: float, objective: float,
                      kind: str = "") -> HealthReport:
        """Record an SLO flipping to burning (obs/slo.py).  Like drift,
        a burning error budget warns and counts but NEVER escalates — it
        is the arming signal for the refit/hot-roll loop, not a reason to
        kill a process that is still serving."""
        r = HealthReport(
            0, "slo_burn",
            "SLO %s is burning its error budget: fast-window burn %.2fx, "
            "slow-window burn %.2fx (observed %.4g vs %s objective %.4g)"
            % (str(slo), float(fast_burn), float(slow_burn),
               float(observed), str(kind) or "the", float(objective)))
        self.reports.append(r)
        self._c_slo_burn.inc()
        if self._events is not None:
            self._events.write("health", iteration=0, kind=r.kind,
                               message=r.message, slo=str(slo),
                               fast_burn=round(float(fast_burn), 4),
                               slow_burn=round(float(slow_burn), 4))
        Log.warning("health: %s" % r.message)
        return r

    def check(self, health_rows, start_iter: int, booster=None
              ) -> List[HealthReport]:
        """Inspect fetched vectors (``[B, HEALTH_VEC_LEN]`` host floats for
        iterations ``start_iter..start_iter+B-1``) and dispatch the
        configured action.  Raises from inside when the action demands."""
        new: List[HealthReport] = []
        for off, row in enumerate(health_rows):
            it = start_iter + off
            self._g_waves.set(float(row[HEALTH_WAVES]))
            if row[HEALTH_STUMP] > 0:
                self._c_stump.inc()
                new.append(HealthReport(
                    it, "zero_gain_wave",
                    "iteration %d grew no split (all gains <= 0)" % it))
            if row[HEALTH_NONFINITE] > 0:
                new.append(HealthReport(
                    it, "nonfinite_gradient",
                    "non-finite gradient/hessian at iteration %d" % it))
            if row[HEALTH_NONFINITE_GAIN] > 0:
                new.append(HealthReport(
                    it, "nonfinite_gain",
                    "non-finite split gain committed at iteration %d" % it))
        self.reports.extend(new)
        anomalies = [r for r in new if r.kind != "zero_gain_wave"]
        for r in new:
            if self._events is not None:
                self._events.write("health", iteration=r.iteration,
                                   kind=r.kind, message=r.message)
            if r.kind == "zero_gain_wave":
                Log.debug("health: %s" % r.message)
            else:
                self._c_anomaly.inc()
                Log.warning("health: %s" % r.message)
        if anomalies and self.action in ("abort", "raise"):
            first = anomalies[0]
            if self.action == "abort" and self._on_abort is not None:
                try:
                    self._on_abort(booster, first)
                except Exception as e:
                    Log.warning("health abort checkpoint failed: %s" % e)
            if self._on_fatal is not None:
                try:
                    self._on_fatal(first)
                except Exception as e:
                    Log.warning("health fatal hook failed: %s" % e)
            raise LightGBMError(
                "training aborted by health monitor: %s" % first.message)
        return new
