"""lightgbm_tpu.obs — unified runtime telemetry.

One low-overhead observability layer shared by training, checkpointing and
serving:

- ``registry``: a process-wide, thread-safe counter/gauge/summary/histogram
  registry with Prometheus text exposition and JSON snapshots.
  serving/metrics.py and profiling.py's compile-cache counters are both
  backed by it.
- ``costmodel``: XLA cost-model extraction (FLOPs / bytes / memory per
  compiled entry point via AOT ``cost_analysis``) and per-phase roofline
  attribution against the detected chip's peaks — feeds ``GET /roofline``,
  bench's ``mfu_estimate`` and the perf gate.
- ``perfgate``: deterministic semantic perf counters + baseline comparison
  (``PERF_COUNTERS.json``, ``tools/perf_gate.py``).
- ``trace``: host-side span timers (device sync only at span close), a
  JSON-lines event stream, and an on-demand ``jax.profiler`` Perfetto
  capture helper for a configurable iteration window.
- ``health``: host dispatch for device-side health flags (non-finite
  grad/hess, zero-positive-gain waves) that the training step piggy-backs
  on existing reductions — warn, checkpoint-and-abort, or raise.
- ``reqtrace``: request-scoped span trees with tail-based sampling —
  one trace per admitted serving request (propagated across fleet hops
  via the ``x-lgbm-trace`` header) or per streamed training iteration,
  emitted as ``span`` events on the shared EventStream.
- ``slo``: declarative SLOs (latency/availability/throughput) judged as
  Google-SRE multi-window burn rates over registry metrics; ``/slo`` on
  both StatsServers, ``lgbm_slo_*`` gauges, warn-only HealthMonitor
  routing.
- ``server``: an optional lightweight stats HTTP endpoint during training
  (Prometheus text + JSON snapshot + healthz + federated cluster routes).
- ``distributed``: multi-process telemetry — metric federation (global
  ``process=``/``host=`` labels, once-per-block snapshot allgather served
  from ``/metrics/cluster`` + ``/stats/cluster``), per-block comm/compute
  attribution with straggler-skew detection, and a crash-dumping flight
  recorder (``<obs_event_file>.<process>.crash.jsonl``).
- ``runtime``: ``TrainingObs``, the per-booster facade built from the
  ``observability=none|basic|full`` config knob that the boosting loop
  drives.

Everything is off by default (``observability=none``) and the instrumented
code paths collapse to no-ops so the training loop's compiled program is
byte-identical when telemetry is disabled.
"""
from .health import (HEALTH_NONFINITE, HEALTH_NONFINITE_GAIN,  # noqa: F401
                     HEALTH_STUMP, HEALTH_VEC_LEN, HEALTH_WAVES,
                     HealthMonitor, HealthReport, health_vec)
from .costmodel import (CHIP_PEAKS, CostModel, detect_peaks,  # noqa: F401
                        get_cost_model, roofline_snapshot)
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, Summary, get_registry)
from .reqtrace import (NULL_REQ_SPAN, NULL_TRACER,  # noqa: F401
                       NullRequestTracer, ReqSpan, RequestTracer,
                       TRACE_HEADER, format_trace_header, keep_decision,
                       new_trace_id, parse_trace_header)
from .runtime import TrainingObs, resolve_health_action  # noqa: F401
from .server import StatsServer  # noqa: F401
from .slo import SloEngine, SloSpec  # noqa: F401
from .trace import (EventStream, Tracer, perfetto_trace,  # noqa: F401
                    span)
from .distributed import (DistributedObs, FlightRecorder,  # noqa: F401
                          merge_prometheus_texts, process_env,
                          straggler_skew)
