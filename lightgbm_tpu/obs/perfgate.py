"""Deterministic perf-counter regression gate (tools/perf_gate.py's core).

Wall-clock benchmarks cannot gate CI — a noisy shared runner swamps any
real regression. This gate compares SEMANTIC performance counters
instead: numbers that are fully determined by the algorithm and the
compiler, independent of host speed, measured on a small fixed synthetic
workload:

- the wave-width ladder and clamped max width the frontier grower
  dispatches (bucketing policy);
- waves / dataset sweeps / occupancy-weighted slot sweeps per grown tree
  (profiling.frontier_tree_stats — the O(depth) sweep guarantee);
- backend compiles after warmup (the zero-recompile invariant: a second
  fused block at the same length must compile NOTHING);
- the device health-vector width (the fused block's per-iteration
  telemetry contract);
- the per-wave psum count of the sharded frontier grower (jaxpr string
  count under an 8-device virtual mesh — one collective per wave);
- XLA cost-model FLOPs / bytes per compiled entry point (train block +
  every ladder bucket, obs/costmodel.py) — these DO drift across XLA
  releases, so they carry relative tolerances; everything structural is
  exact;
- the serving hot path's fingerprint: the SoA traversal's static depth
  and bucket ladder (exact) plus per-bucket predict FLOPs / bytes
  (serving/traversal.py — a regression here is a serving latency
  regression the wall-clock-free gate can still see).

The committed baseline (PERF_COUNTERS.json) declares every counter with
its tolerance: ``{"value": v, "tol": t, "mode": "exact"|"rel"|"min"}``
(``min`` carries a ``floor`` instead of a tolerance — one-sided, for
ratios that must never regress below a promised multiple, like the
packed-bin bytes reduction). A
regression — a grower suddenly sweeping twice per wave, a recompile
sneaking into the steady state, a bucketing change silently widening
every wave — fails the gate with a readable diff naming the counter and
both values. Intentional changes re-baseline with
``python tools/perf_gate.py --write-baseline`` (docs/Observability.md).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

# the gate's fixed workload: small enough that measuring is seconds on
# CPU, structured enough (depth-4 frontier ladder, fused block, flush)
# that every counter above is exercised
DEFAULT_WORKLOAD: Dict[str, Any] = {
    "rows": 2048,
    "features": 8,
    "num_leaves": 15,
    "max_depth": 4,
    "iters": 3,
    "seed": 0,
    "backend": "cpu",
}


# the packed-bin pipeline's headline claim, pinned as a one-sided gate:
# nibble pair coding + word packing must keep the frontier sweep's
# cost-model bytes at >= this multiple of the plain-uint8 sweep's
# (docs/Performance.md "Packed bins & fused wave")
PACKING_BYTES_FLOOR = 1.5


def default_spec(name: str) -> Dict[str, Any]:
    """Tolerance policy for a counter name: XLA cost-model numbers drift
    across compiler releases (fusion decisions change flop/byte
    accounting), structural counters must not move at all. ``min``
    counters are one-sided: the measured value may improve freely but
    must never drop below the declared floor."""
    if name.startswith("packing_bytes_ratio_"):
        return {"mode": "min", "tol": 0, "floor": PACKING_BYTES_FLOOR}
    if name.startswith("costmodel_flops_"):
        return {"mode": "rel", "tol": 0.25}
    if name.startswith("costmodel_bytes_"):
        return {"mode": "rel", "tol": 0.5}
    return {"mode": "exact", "tol": 0}


# ------------------------------------------------------------ measurement
def _psum_per_wave(param_overrides: Optional[Dict[str, Any]] = None
                   ) -> Optional[float]:
    """Per-wave collective count of the sharded frontier grower under
    the 8-device mesh — the shared analysis/jaxpr_audit.py entry and
    equation walk (one construction; the audit baseline and
    tests/test_obs.py pin the same program). None when fewer than 8
    devices exist — the gate CLI re-execs itself with a virtual-device
    flag to guarantee them.  ``param_overrides`` forwards to the audit
    entry: the gate measures the observability-on branch too, pinning
    that distributed telemetry never adds a collective."""
    import jax

    from ..analysis import jaxpr_audit

    entry = jaxpr_audit.sharded_frontier_fn(param_overrides=param_overrides)
    if entry is None:
        return None
    fn, args, params = entry
    jaxpr = jax.make_jaxpr(fn)(*args)
    total = jaxpr_audit.count_collectives(jaxpr).get("psum", 0)
    waves = len(bucketing_ladder(params.num_leaves, params.max_depth))
    # normalize by ladder width count so the counter reads "collectives
    # per compiled wave branch", stable under ladder changes
    return float(total) / max(waves, 1)


def _wave_collectives(param_overrides: Optional[Dict[str, Any]] = None,
                      num_features: int = 16,
                      num_devices: int = 8
                      ) -> Optional[Tuple[float, float]]:
    """(collective op count, received f32 payload elements) of ONE growth
    wave of the sharded frontier grower — the static comm-volume contract
    of each parallel learner (parallel/learners.py). The growth loop is
    the only ``while`` whose body holds collectives (the hist chunk loops
    have none), so its body's schedule IS the per-wave schedule. Payload
    counts f32 elements RECEIVED per device: psum = operand size,
    reduce_scatter = operand / P, all_gather = P * operand. int32 vote
    traffic is excluded (it is negligible by design and the op count pins
    it). None when fewer than ``num_devices`` devices exist."""
    import numpy as np

    import jax

    from ..analysis import jaxpr_audit

    entry = jaxpr_audit.sharded_frontier_fn(param_overrides=param_overrides,
                                            num_features=num_features)
    if entry is None:
        return None
    fn, args, _ = entry
    jaxpr = jax.make_jaxpr(fn)(*args)
    wave_body = None
    for eqn in jaxpr_audit.iter_eqns(jaxpr):
        if eqn.primitive.name != "while":
            continue
        for sub in jaxpr_audit._sub_jaxprs(eqn):
            if any(e.primitive.name in jaxpr_audit.COLLECTIVE_PRIMITIVES
                   for e in jaxpr_audit.iter_eqns(sub)):
                wave_body = sub
                break
        if wave_body is not None:
            break
    if wave_body is None:
        return 0.0, 0.0
    ops = 0
    payload = 0.0
    for e in jaxpr_audit.iter_eqns(wave_body):
        if e.primitive.name not in jaxpr_audit.COLLECTIVE_PRIMITIVES:
            continue
        ops += 1
        aval = e.invars[0].aval
        if str(getattr(aval, "dtype", "")) != "float32":
            continue
        elems = float(np.prod(aval.shape)) if aval.shape else 1.0
        if e.primitive.name in ("reduce_scatter", "psum_scatter"):
            payload += elems / num_devices
        elif e.primitive.name == "all_gather":
            payload += elems * num_devices
        else:
            payload += elems
    return float(ops), payload


def bucketing_ladder(num_leaves: int, max_depth: int) -> List[int]:
    from .. import bucketing
    return [int(w) for w in bucketing.wave_width_ladder(num_leaves,
                                                        max_depth)]


def measure(workload: Optional[Dict[str, Any]] = None
            ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Train the gate workload and read every counter. Returns
    ``(counters, workload)``. Deterministic by construction: fixed seed,
    fixed shapes, semantic counters only — two runs on the same code +
    jax produce identical values (pinned by tests/test_costmodel.py)."""
    import jax
    import numpy as np

    import lightgbm_tpu as lgb
    from .. import bucketing
    from ..profiling import (backend_compile_count, frontier_tree_stats,
                             install_compile_hook)

    wl = dict(DEFAULT_WORKLOAD)
    wl.update(workload or {})
    install_compile_hook()
    rng = np.random.RandomState(int(wl["seed"]))
    X = rng.randn(int(wl["rows"]), int(wl["features"])).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1,
         "num_leaves": int(wl["num_leaves"]),
         "max_depth": int(wl["max_depth"]),
         "tree_growth": "frontier", "observability": "none",
         "seed": int(wl["seed"])},
        lgb.Dataset(X, label=y), num_boost_round=int(wl["iters"]))
    b = bst._impl
    models = b.models                       # force the flush
    counters: Dict[str, Any] = {}

    ladder = bucketing_ladder(int(wl["num_leaves"]), int(wl["max_depth"]))
    counters["frontier_ladder"] = ladder
    counters["frontier_max_width"] = float(bucketing.frontier_max_width(
        int(wl["num_leaves"]), int(wl["max_depth"])))
    stats = frontier_tree_stats(models[0], b.grow_params)
    counters["waves_per_tree"] = stats["waves"]
    counters["dataset_sweeps_per_tree"] = stats["sweeps_per_tree"]
    counters["slot_sweeps_per_tree"] = stats["slot_sweeps_per_tree"]
    counters["wave_occupancy"] = round(stats["wave_occupancy"], 6)

    # the fused block's telemetry contract: health rows are [block, W]
    from .health import health_vec
    counters["health_vec_width"] = float(jax.eval_shape(
        health_vec,
        jax.ShapeDtypeStruct((8,), jax.numpy.float32),
        jax.ShapeDtypeStruct((8,), jax.numpy.float32),
        jax.ShapeDtypeStruct((), jax.numpy.bool_)).shape[0])

    # zero-recompile invariant: a second fused block at the same length
    # must reuse the first block's executable (measured BEFORE cost
    # extraction, whose own one-time AOT compiles are accounted apart)
    c0 = backend_compile_count()
    b.train_many(int(wl["iters"]))
    counters["compiles_after_warmup"] = float(backend_compile_count() - c0)

    costs = b.extract_cost_model(force=True)
    for name in sorted(costs):
        counters["costmodel_flops_" + name] = float(costs[name]["flops"])
        counters["costmodel_bytes_" + name] = float(
            costs[name]["bytes_accessed"])

    counters.update(_serving_counters(bst, int(wl["features"])))

    psum = _psum_per_wave()
    if psum is not None:
        counters["psum_per_wave_branch"] = psum
    # same program with the device health branch (the only compiled-code
    # obs surface) enabled: distributed telemetry is host-metadata-only,
    # so the per-wave collective count must be IDENTICAL to the plain
    # branch — a new psum here means someone put a collective on the
    # telemetry path
    psum_obs = _psum_per_wave(param_overrides={"obs_health": True})
    if psum_obs is not None:
        counters["psum_per_wave_branch_obs"] = psum_obs
    # per-wave collective schedule of each parallel learner (16-feature
    # variant so the data learner's psum_scatter tiles over 8 devices):
    # op count + f32 elements RECEIVED per device per wave. These pin the
    # comm-volume win statically — voting's wave payload is the 2*top_k
    # elected columns per slot (here 4 of 16 features), data_rs is the
    # 1/P histogram shard plus the packed record gather, serial is the
    # full F*B*3 psum.
    for suffix, overrides in (("serial", None),
                              ("data_rs", {"frontier_rs": True}),
                              ("voting", {"voting_top_k": 2})):
        wave = _wave_collectives(param_overrides=overrides)
        if wave is not None:
            counters["wave_collectives_" + suffix] = wave[0]
            counters["wave_payload_f32_" + suffix] = wave[1]
    counters.update(_stream_counters(wl))
    counters.update(_stream_dist_counters(wl))
    counters.update(_packing_counters())
    counters.update(_refit_counters(bst, wl))
    return counters, wl


def _refit_counters(bst, wl: Dict[str, Any]) -> Dict[str, Any]:
    """Structure-preserving refit (fleet/refit.py): the compiled-program
    contract of the continuous-training loop. A Refitter's first cycle
    compiles a BOUNDED set of programs (the leaf-id traversal + the
    scan-over-iterations core — tree-count-independent); a second cycle
    on a fresh window of the SAME shapes must compile NOTHING (the
    objective's device arrays are jit arguments, so new data hits the
    cache). Both are exact: a new compile here means someone broke the
    per-cycle reuse the fleet refit worker depends on."""
    import numpy as np

    from ..fleet.refit import Refitter
    from ..profiling import backend_compile_count

    rng = np.random.RandomState(int(wl["seed"]) + 1)
    nf = int(wl["features"])

    def window():
        X = rng.randn(512, nf).astype(np.float32)
        return X, (X[:, 0] - X[:, 1] > 0).astype(np.float32)

    r = Refitter(bst)
    counters: Dict[str, Any] = {}
    X, y = window()
    c0 = backend_compile_count()
    r.refit(X, y)
    counters["refit_programs_first_cycle"] = float(
        backend_compile_count() - c0)
    X, y = window()
    c1 = backend_compile_count()
    r.refit(X, y)
    counters["refit_compiles_second_cycle"] = float(
        backend_compile_count() - c1)
    return counters


def _packing_counters() -> Dict[str, Any]:
    """The packed-bin traffic win (tpu_bin_packing=nibble), pinned via
    XLA cost analysis: bytes per frontier-sweep call on a pair-coded
    word-packed matrix (C/2 joint columns of 256 bins, int32 words)
    vs the plain uint8 matrix (C columns of 16 bins), at a fixed
    8192 x 16 probe. Rows are 8192, NOT the 2048-row gate workload:
    the scatter path's per-row i32 index/update traffic is column-
    proportional, so the ratio needs enough rows for the column
    halving to dominate the fixed [W, C, B, 3] output tensor (which
    GROWS 8x under pair coding and would swamp a small probe).
    ``mode="min"`` counters: the ratio may improve, never regress
    below PACKING_BYTES_FLOOR."""
    import jax
    import jax.numpy as jnp

    from ..core.binpack import words_per_row
    from ..core.histogram import build_histogram_frontier
    from .costmodel import get_cost_model

    cm = get_cost_model()
    rows, feats = 8192, 16
    sds = jax.ShapeDtypeStruct
    per_row = (sds((rows,), jnp.int32),        # slot
               sds((rows,), jnp.float32),      # grad
               sds((rows,), jnp.float32),      # hess
               sds((rows,), jnp.float32))      # mask
    counters: Dict[str, Any] = {}
    for w in (1, 8):
        plain = cm.analyze(
            "packprobe_plain_w%d" % w, build_histogram_frontier,
            sds((rows, feats), jnp.uint8), *per_row,
            num_bins=16, num_slots=w, row_chunk=4096, impl="scatter")
        packed = cm.analyze(
            "packprobe_packed_w%d" % w, build_histogram_frontier,
            sds((rows, words_per_row(feats // 2)), jnp.int32), *per_row,
            num_bins=256, num_slots=w, row_chunk=4096, impl="scatter",
            packed_cols=feats // 2)
        counters["packing_bytes_ratio_w%d" % w] = round(
            plain["bytes_accessed"] / max(packed["bytes_accessed"], 1.0),
            4)
    return counters


def _stream_counters(wl: Dict[str, Any]) -> Dict[str, Any]:
    """Out-of-core training counters (lightgbm_tpu.stream).

    Three contracts pinned, all chunk-count-structural:

    - ``stream_compile_chunk_invariance``: compiling the SAME workload at
      2 vs 4 chunks must build the identical program set (the per-chunk
      kernels are fixed-shape and the wave width is fixed, so chunk count
      only changes how often each program runs) — the difference of the
      two fresh-booster compile counts is exactly 0;
    - ``stream_compiles_after_warmup``: further streamed iterations on a
      warm booster compile NOTHING (exact 0);
    - ``stream_sweeps_per_tree``: dataset sweeps per grown tree (one root
      sweep + one per wave — the O(depth) sweep guarantee carried over
      from the in-memory frontier grower).

    A throwaway single-chunk run first absorbs every once-per-process
    compile (shared jitted helpers) so the two measured runs see only
    their own program sets."""
    import numpy as np

    import lightgbm_tpu as lgb
    from ..profiling import backend_compile_count, install_compile_hook

    install_compile_hook()
    rows = int(wl["rows"])
    rng = np.random.RandomState(int(wl["seed"]))
    X = rng.randn(rows, int(wl["features"])).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)

    def run(num_chunks: int):
        params = {"objective": "binary", "verbosity": -1,
                  "num_leaves": int(wl["num_leaves"]),
                  "max_depth": int(wl["max_depth"]),
                  "tree_growth": "frontier", "observability": "none",
                  "seed": int(wl["seed"]),
                  "data_stream_chunk_rows": rows // num_chunks}
        c0 = backend_compile_count()
        bst = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=int(wl["iters"]))
        _ = bst._impl.models                 # force the flush
        return bst._impl, float(backend_compile_count() - c0)

    counters: Dict[str, Any] = {}
    run(1)                                   # throwaway warm run
    _, compiles2 = run(2)
    b4, compiles4 = run(4)
    counters["stream_compile_chunk_invariance"] = compiles4 - compiles2
    c0 = backend_compile_count()
    b4.train_many(int(wl["iters"]))
    counters["stream_compiles_after_warmup"] = \
        float(backend_compile_count() - c0)
    counters["stream_sweeps_per_tree"] = round(
        b4._stream.sweeps / max(b4._stream_grower.trees_grown, 1), 6)
    # fused last-chunk+commit dispatch: per wave the grower issues
    # wave_begin + one kernel per chunk (the final one carrying the
    # commit), so dispatches/wave - chunks == 1 exactly, invariant in
    # chunk count — a regression to a standalone commit reads 2 here
    g4 = b4._stream_grower
    counters["stream_dispatch_overhead_per_wave"] = round(
        g4.wave_dispatches / max(g4.waves, 1) - b4._stream.num_chunks, 6)
    return counters


def _stream_dist_counters(wl: Dict[str, Any]) -> Dict[str, Any]:
    """Chunks-x-chips counters (mesh-mode StreamFrontierGrower,
    stream/grow_stream.py): the comm and compile contracts of
    DISTRIBUTED out-of-core training, measured on a single-process mesh
    so the gate needs no multi-process launch (tools/dist_train_smoke.py
    covers the real 2-process run).

    - ``stream_dist_wave_collectives_{data,voting}``: collective ops in
      ONE traced growth wave (jaxpr_audit.streamed_sharded_fn) — exactly
      one int32 psum (the replicated continue flag that replaced the
      host bool sync) plus the in-memory learner's schedule, so data_rs
      reads 3 and voting 4;
    - ``stream_dist_wave_payload_f32_{data,voting}``: f32 elements
      received per device per wave — the flag is int32, so these must
      EQUAL the in-memory ``wave_payload_f32_*`` pins (streaming adds
      zero collective payload per wave, the PR's headline contract);
    - ``stream_dist_compile_chunk_invariance``: same workload trained
      under a 2-shard mesh at 1 vs 2 chunks per shard builds the same
      number of programs (difference exactly 0);
    - ``stream_dist_compiles_after_warmup``: further streamed mesh
      iterations on a warm booster compile NOTHING (exact 0)."""
    import numpy as np

    import jax

    import lightgbm_tpu as lgb
    from ..analysis import jaxpr_audit
    from ..profiling import backend_compile_count, install_compile_hook

    counters: Dict[str, Any] = {}
    num_devices = 8
    for suffix, ov in (("data", {"frontier_rs": True}),
                       ("voting", {"voting_top_k": 2})):
        entry = jaxpr_audit.streamed_sharded_fn(num_devices=num_devices,
                                                param_overrides=ov)
        if entry is None:
            continue
        fn, args, _ = entry
        jaxpr = jax.make_jaxpr(fn)(*args)
        ops = 0
        payload = 0.0
        # one_wave IS one wave (no outer loop), so the whole program's
        # schedule is the per-wave schedule; payload rules mirror
        # _wave_collectives (elements RECEIVED per device, f32 only)
        for e in jaxpr_audit.iter_eqns(jaxpr):
            if e.primitive.name not in jaxpr_audit.COLLECTIVE_PRIMITIVES:
                continue
            ops += 1
            aval = e.invars[0].aval
            if str(getattr(aval, "dtype", "")) != "float32":
                continue
            elems = float(np.prod(aval.shape)) if aval.shape else 1.0
            if e.primitive.name in ("reduce_scatter", "psum_scatter"):
                payload += elems / num_devices
            elif e.primitive.name == "all_gather":
                payload += elems * num_devices
            else:
                payload += elems
        counters["stream_dist_wave_collectives_" + suffix] = float(ops)
        counters["stream_dist_wave_payload_f32_" + suffix] = payload

    if len(jax.devices()) < 2:
        return counters
    install_compile_hook()
    rows = int(wl["rows"])
    rng = np.random.RandomState(int(wl["seed"]))
    X = rng.randn(rows, int(wl["features"])).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)

    def run(chunks_per_shard: int):
        params = {"objective": "binary", "verbosity": -1,
                  "num_leaves": int(wl["num_leaves"]),
                  "max_depth": int(wl["max_depth"]),
                  "tree_growth": "frontier", "observability": "none",
                  "seed": int(wl["seed"]), "tree_learner": "data",
                  "mesh_shape": [2], "num_machines": 2,
                  "data_stream_chunk_rows": rows // (2 * chunks_per_shard)}
        c0 = backend_compile_count()
        bst = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=int(wl["iters"]))
        _ = bst._impl.models                 # force the flush
        return bst._impl, float(backend_compile_count() - c0)

    # throwaway 1-chunk warm run absorbs every once-per-process compile;
    # the two measured runs then see only their own per-chunk-shape
    # program sets, whose cardinality must match (as _stream_counters)
    run(1)
    _, compiles2 = run(2)
    b4, compiles4 = run(4)
    counters["stream_dist_compile_chunk_invariance"] = \
        compiles4 - compiles2
    c0 = backend_compile_count()
    b4.train_many(int(wl["iters"]))
    counters["stream_dist_compiles_after_warmup"] = \
        float(backend_compile_count() - c0)
    return counters


def _serving_counters(bst, num_features: int) -> Dict[str, Any]:
    """Serving traversal counters on the gate booster: the static
    traversal depth and bucket ladder (structural, exact) plus XLA
    FLOPs / bytes for every bucket's compiled predict — the serving hot
    path's cost fingerprint (serving/traversal.py). AOT-only: predictors
    are built but never executed, so nothing here perturbs the
    compiles_after_warmup counter measured above."""
    import jax

    from ..serving.predictor import ServingEngine, bucket_sizes
    from .costmodel import get_cost_model

    eng = ServingEngine(max_batch=64, min_bucket=32)
    bundle = eng.registry.register_booster("gate", bst)
    _, depth = bundle.flat_for()
    counters: Dict[str, Any] = {
        "predict_traversal_depth": float(depth),
        "predict_bucket_ladder": [int(b) for b in
                                  bucket_sizes(eng.min_bucket,
                                               eng.max_batch)],
    }
    cm = get_cost_model()
    iters = bundle.effective_iterations(None)
    for bucket in bucket_sizes(eng.min_bucket, eng.max_batch):
        entry = eng._predictor(bundle, bucket, False, iters)
        costs = cm.analyze(
            "perfgate_predict_b%d" % bucket, entry._fn,
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                entry._trees),
            jax.ShapeDtypeStruct((bucket, num_features), jax.numpy.float32),
            extra_key="perfgate")
        counters["costmodel_flops_predict_b%d" % bucket] = \
            float(costs["flops"])
        counters["costmodel_bytes_predict_b%d" % bucket] = \
            float(costs["bytes_accessed"])
    return counters


# ------------------------------------------------------------ baseline IO
def make_baseline(counters: Dict[str, Any],
                  workload: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "schema": SCHEMA_VERSION,
        "workload": dict(workload),
        "counters": {
            name: dict(default_spec(name), value=value)
            for name, value in sorted(counters.items())
        },
    }


def write_baseline(path: str, baseline: Dict[str, Any]) -> None:
    with open(path, "w") as fh:
        json.dump(baseline, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


# ------------------------------------------------------------ comparison
def compare(baseline: Dict[str, Any], measured: Dict[str, Any]
            ) -> Tuple[List[Dict[str, Any]], str]:
    """Check measured counters against a baseline's declared tolerances.
    Returns ``(violations, table)`` — ``violations`` empty means the
    gate passes; ``table`` is an aligned human-readable diff of every
    counter (printed by the CLI on pass AND fail, so CI logs always
    show what was checked)."""
    specs = baseline.get("counters", {})
    rows: List[Tuple[str, str, str, str, str]] = []
    violations: List[Dict[str, Any]] = []
    for name in sorted(specs):
        spec = specs[name]
        want = spec.get("value")
        mode = spec.get("mode", "exact")
        tol = float(spec.get("tol", 0))
        have = measured.get(name)
        if have is None:
            status = "MISSING"
            violations.append({"counter": name, "baseline": want,
                               "measured": None,
                               "reason": "counter not measured"})
        elif mode == "min":
            floor = float(spec.get("floor", want))
            ok = float(have) >= floor
            status = "ok (>= %s floor)" % _fmt(floor) if ok else \
                "FAIL (< %s floor)" % _fmt(floor)
            if not ok:
                violations.append({
                    "counter": name, "baseline": floor, "measured": have,
                    "reason": "value %.4f below floor %.4f"
                    % (float(have), floor)})
        elif mode == "rel":
            denom = max(abs(float(want)), 1e-12)
            drift = abs(float(have) - float(want)) / denom
            ok = drift <= tol
            status = "ok (%.1f%% drift)" % (drift * 100) if ok else \
                "FAIL (%.1f%% > %.0f%% tol)" % (drift * 100, tol * 100)
            if not ok:
                violations.append({
                    "counter": name, "baseline": want, "measured": have,
                    "reason": "drift %.3f exceeds rel tol %.3f"
                    % (drift, tol)})
        else:
            ok = have == want
            status = "ok" if ok else "FAIL (exact)"
            if not ok:
                violations.append({
                    "counter": name, "baseline": want, "measured": have,
                    "reason": "exact counter changed"})
        rows.append((name, mode, _fmt(want), _fmt(have), status))
    extra = sorted(set(measured) - set(specs))
    for name in extra:
        # new counters are informational, not failures: the baseline
        # declares the contract, re-baselining admits new counters
        rows.append((name, "-", "-", _fmt(measured[name]),
                     "new (not in baseline)"))
    widths = [max(len(r[i]) for r in rows + [_HDR]) for i in range(5)]
    lines = [_fmt_row(_HDR, widths),
             _fmt_row(tuple("-" * w for w in widths), widths)]
    lines += [_fmt_row(r, widths) for r in rows]
    return violations, "\n".join(lines) + "\n"


_HDR = ("counter", "mode", "baseline", "measured", "status")


def _fmt(v: Any) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    if isinstance(v, list):
        return json.dumps(v)
    return str(v)


def _fmt_row(r: Tuple[str, ...], widths: List[int]) -> str:
    return "  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
