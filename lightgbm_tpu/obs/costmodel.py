"""XLA cost-model extraction + roofline attribution (performance accounting).

bench.py's old ``flops_per_visit = 3*256*2*2.0`` MFU formula was a guess.
This module replaces it with XLA's own accounting: every compiled entry
point (the fused train block, each frontier wave-width bucket's histogram
sweep, each serving predict bucket, the materialize flush) is AOT-lowered
and compiled once, and its static costs — FLOPs, bytes accessed, peak /
temp / output memory — are read from ``Compiled.cost_analysis()`` +
``Compiled.memory_analysis()``.  Combined with measured wall time (span
summaries from obs/trace.py, or explicit probe timings) that yields
per-phase roofline attribution: achieved FLOP/s, achieved B/s, arithmetic
intensity, and ``mfu`` / ``membw_util`` against the detected chip's peaks.
Both GPU GBDT papers (arXiv:1706.08359, 1806.11248) argue from exactly
this accounting — histogram accumulation is memory-bound, so achieved
bytes/s against the roofline is the number that matters.

Extraction discipline (pinned by tests/test_costmodel.py):

- it is PULL-based: nothing in the training loop triggers it, so
  ``observability=none`` runs emit zero costmodel work;
- AOT lowering shares nothing with the executing program — extraction
  never recompiles or alters a training/serving executable (their jaxprs
  are byte-identical before/after, and dispatching them after extraction
  adds zero backend compiles);
- the first extraction of a program pays its own one AOT compile (the
  ``__call__`` and AOT executable caches are disjoint in this jax); every
  repeat is served from the in-process cache, and when a persistent
  compile cache is configured (``compile_cache_dir``) the extracted
  numbers are ALSO persisted next to it (``costmodel_cache.json``), so a
  warm process does no jax work at all — not even tracing.

On CPU there is no meaningful peak to normalize by, so rooflines report
achieved rates without a utilization ratio (``detect_peaks`` -> None).

This module imports jax only inside functions — the stats server route
(``GET /roofline``) must stay importable in processes that never touch a
device.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..log import Log
from .registry import MetricsRegistry, get_registry

# ------------------------------------------------------------ chip peaks
# Public per-chip peaks: bf16 matmul FLOP/s and HBM bandwidth (bytes/s).
# This extends (and now owns) bench.py's old _PEAKS table; bench imports
# it from here so the roofline denominator has one definition.
CHIP_PEAKS: Dict[str, Dict[str, float]] = {
    "v4": {"flops_per_s": 275e12, "hbm_bytes_per_s": 1.228e12},
    "v5e": {"flops_per_s": 197e12, "hbm_bytes_per_s": 0.819e12},
    "v5p": {"flops_per_s": 459e12, "hbm_bytes_per_s": 2.765e12},
    "v6e": {"flops_per_s": 918e12, "hbm_bytes_per_s": 1.640e12},
    "trillium": {"flops_per_s": 918e12, "hbm_bytes_per_s": 1.640e12},
}


def normalize_device_kind(kind: str) -> str:
    """Normalize a PJRT ``device_kind`` string to something the peaks
    table can be matched against ('TPU v5 lite' -> 'tpuv5e')."""
    k = str(kind or "").lower().replace(" ", "").replace("_", "")
    return k.replace("v6lite", "v6e").replace("v5lite", "v5e")


def detect_peaks(device_kind: Optional[str] = None
                 ) -> Optional[Dict[str, float]]:
    """Peak FLOP/s + HBM B/s for the chip generation running this
    process (or for an explicit ``device_kind`` string).  Returns None
    on CPU / unknown hosts: a roofline there reports achieved rates
    only, never a utilization ratio against somebody else's peak."""
    if device_kind is None:
        try:
            import jax
            device_kind = getattr(jax.devices()[0], "device_kind", "")
        except Exception:  # noqa: BLE001 - diagnostics must not raise
            return None
    kind = normalize_device_kind(device_kind)
    if not kind or "cpu" in kind:
        return None
    for key, peaks in CHIP_PEAKS.items():
        if key in kind:
            return dict(peaks)
    # a TPU whose generation we do not know: conservative v5e numbers
    if "tpu" in kind:
        return dict(CHIP_PEAKS["v5e"])
    return None


# ------------------------------------------------------------ extraction
def costs_from_compiled(compiled) -> Dict[str, float]:
    """Normalize ``Compiled.cost_analysis()`` + ``memory_analysis()``
    into one flat dict.  cost_analysis returns a list of one dict on
    this jax (older APIs returned the dict bare); memory_analysis has no
    ``peak_memory_in_bytes`` here, so peak is derived as
    argument + output + temp - alias."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}

    def _pos(key):
        try:
            v = float(ca.get(key, 0.0))
        except (TypeError, ValueError):
            return 0.0
        return v if v > 0.0 else 0.0     # -1 marks "not implemented"

    out = {"flops": _pos("flops"),
           "bytes_accessed": _pos("bytes accessed"),
           "transcendentals": _pos("transcendentals")}
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 - optional on some backends
        ma = None
    if ma is not None:
        arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
        outb = float(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
        alias = float(getattr(ma, "alias_size_in_bytes", 0) or 0)
        peak = float(getattr(ma, "peak_memory_in_bytes", 0) or 0)
        out.update(
            argument_bytes=arg, output_bytes=outb, temp_bytes=tmp,
            alias_bytes=alias,
            peak_bytes=peak if peak > 0 else max(arg + outb + tmp - alias,
                                                 0.0),
            generated_code_bytes=float(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0))
    return out


def _leaf_signature(leaf) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return "%s[%s]" % (dtype, ",".join(map(str, shape)))
    return repr(leaf)


class CostModel:
    """Per-process store of per-entry static costs.

    ``analyze(name, fn, *args, **kwargs)`` AOT-lowers + compiles the jit
    function on the given arg shapes (``jax.ShapeDtypeStruct`` mirrors
    work — no real arrays needed), extracts its costs, registers them as
    gauges (``lgbm_costmodel_*{entry=name}``) and caches the result by
    (name, backend, jax version, arg signature) — in memory always, and
    on disk next to jax's persistent compile cache when one is
    configured.  A cache hit does zero jax work.
    """

    DISK_CACHE_NAME = "costmodel_cache.json"

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 cache_dir: Optional[str] = None):
        self.registry = registry if registry is not None else get_registry()
        self._cache_dir = cache_dir
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, float]] = {}
        self._by_key: Dict[str, Dict[str, float]] = {}
        self._c_extract = self.registry.counter(
            "lgbm_costmodel_extractions_total",
            "Cost-model extraction requests (including cache hits).")
        self._c_compiles = self.registry.counter(
            "lgbm_costmodel_aot_compiles_total",
            "AOT compiles the cost model actually paid (cache misses).")

    # ------------------------------------------------------------ cache
    def _disk_path(self) -> str:
        d = self._cache_dir
        if not d:
            try:
                import jax
                d = jax.config.jax_compilation_cache_dir or ""
            except Exception:  # noqa: BLE001
                d = ""
        return os.path.join(d, self.DISK_CACHE_NAME) if d else ""

    def _disk_load(self) -> Dict[str, Dict[str, float]]:
        path = self._disk_path()
        if not path or not os.path.exists(path):
            return {}
        try:
            with open(path) as fh:
                data = json.load(fh)
            return data if isinstance(data, dict) else {}
        except Exception:  # noqa: BLE001 - a bad cache means no cache
            return {}

    def _disk_store(self, key: str, name: str,
                    costs: Dict[str, float]) -> None:
        path = self._disk_path()
        if not path:
            return
        try:
            data = self._disk_load()
            data[key] = {"entry": name, "costs": costs}
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(data, fh, sort_keys=True)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 - persistence is best-effort
            pass

    def _key(self, name: str, args, kwargs, extra_key: str) -> str:
        import jax
        leaves = jax.tree_util.tree_leaves((args, tuple(sorted(
            (k, v) for k, v in kwargs.items()))))
        sig = ";".join(_leaf_signature(x) for x in leaves)
        raw = "|".join((name, jax.version.__version__,
                        jax.default_backend(), extra_key, sig))
        return hashlib.sha256(raw.encode()).hexdigest()[:32]

    # ------------------------------------------------------------ public
    def analyze(self, name: str, fn, *args, extra_key: str = "",
                **kwargs) -> Dict[str, float]:
        """Extract (or recall) the static costs of ``fn`` at these arg
        shapes and publish them under entry label ``name``.  ``fn`` must
        be a jit-wrapped callable (has ``.lower``); static kwargs pass
        through to it.  Never raises past jax errors: a failed lowering
        propagates so callers see real mistakes, but cache/IO problems
        degrade silently."""
        self._c_extract.inc()
        key = self._key(name, args, kwargs, extra_key)
        with self._lock:
            hit = self._by_key.get(key)
        if hit is None:
            disk = self._disk_load().get(key)
            if disk and isinstance(disk.get("costs"), dict):
                hit = {k: float(v) for k, v in disk["costs"].items()}
        if hit is None:
            compiled = fn.lower(*args, **kwargs).compile()
            self._c_compiles.inc()
            hit = costs_from_compiled(compiled)
            self._disk_store(key, name, hit)
        with self._lock:
            self._by_key[key] = hit
            self._entries[name] = hit
        self._publish(name, hit)
        return dict(hit)

    def record(self, name: str, costs: Dict[str, float]) -> None:
        """Register externally-computed costs under ``name`` (used by
        callers that already hold a Compiled object)."""
        costs = {k: float(v) for k, v in costs.items()}
        with self._lock:
            self._entries[name] = costs
        self._publish(name, costs)

    def _publish(self, name: str, costs: Dict[str, float]) -> None:
        lbl = {"entry": name}
        for field, metric, help_txt in (
                ("flops", "lgbm_costmodel_flops",
                 "XLA cost-analysis FLOPs per call of this entry point."),
                ("bytes_accessed", "lgbm_costmodel_bytes_accessed",
                 "XLA cost-analysis bytes accessed per call."),
                ("peak_bytes", "lgbm_costmodel_peak_bytes",
                 "Peak device memory of the compiled executable."),
                ("temp_bytes", "lgbm_costmodel_temp_bytes",
                 "Temp-buffer bytes of the compiled executable."),
                ("output_bytes", "lgbm_costmodel_output_bytes",
                 "Output bytes of the compiled executable.")):
            if field in costs:
                self.registry.gauge(metric, help_txt,
                                    labels=lbl).set(costs[field])

    def entries(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def get(self, name: str) -> Optional[Dict[str, float]]:
        with self._lock:
            e = self._entries.get(name)
            return dict(e) if e is not None else None


_COSTMODEL = CostModel()


def get_cost_model() -> CostModel:
    """The process-wide cost model (parallel to obs.registry's
    get_registry): boosters, serving and the tools all publish here so
    one ``/roofline`` scrape sees every extracted entry point."""
    return _COSTMODEL


# ------------------------------------------------------------ roofline
def roofline_row(name: str, costs: Dict[str, float], seconds: float,
                 calls: float,
                 peaks: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """One per-phase attribution row: static per-call costs x measured
    wall time -> achieved rates (+ utilization when peaks are known).
    ``seconds`` is total wall time over ``calls`` dispatches; rows with
    no timing (calls == 0) carry static costs only."""
    flops = float(costs.get("flops", 0.0))
    byts = float(costs.get("bytes_accessed", 0.0))
    row: Dict[str, Any] = {
        "phase": name,
        "calls": float(calls),
        "seconds": round(float(seconds), 6),
        "flops_per_call": flops,
        "bytes_per_call": byts,
    }
    if byts > 0:
        row["arithmetic_intensity"] = round(flops / byts, 6)
    if "peak_bytes" in costs:
        row["peak_bytes"] = float(costs["peak_bytes"])
    if seconds > 0 and calls > 0:
        row["flops_per_s"] = round(flops * calls / seconds, 3)
        row["bytes_per_s"] = round(byts * calls / seconds, 3)
        if peaks:
            pf = float(peaks.get("flops_per_s", 0.0))
            pb = float(peaks.get("hbm_bytes_per_s", 0.0))
            if pf > 0:
                row["mfu"] = round(row["flops_per_s"] / pf, 8)
            if pb > 0:
                row["membw_util"] = round(row["bytes_per_s"] / pb, 8)
            if pf > 0 and pb > 0 and byts > 0:
                # below the ridge point the phase cannot saturate the
                # MXUs no matter how well it is scheduled
                ridge = pf / pb
                row["bound"] = ("memory" if flops / byts < ridge
                                else "compute")
    return row


def roofline_table(wall_times: Dict[str, Tuple[float, float]],
                   cost_model: Optional[CostModel] = None,
                   peaks: Optional[Dict[str, float]] = None,
                   include_static_only: bool = True) -> List[Dict[str, Any]]:
    """Join extracted entries with ``{name: (seconds, calls)}`` wall
    times.  Entries without a timing still appear (static costs only)
    unless ``include_static_only`` is False."""
    cm = cost_model if cost_model is not None else get_cost_model()
    rows = []
    for name, costs in sorted(cm.entries().items()):
        seconds, calls = wall_times.get(name, (0.0, 0.0))
        if calls <= 0 and not include_static_only:
            continue
        rows.append(roofline_row(name, costs, seconds, calls, peaks))
    return rows


def span_wall_times(registry: Optional[MetricsRegistry] = None,
                    metric: str = "lgbm_train_span_seconds"
                    ) -> Dict[str, Tuple[float, float]]:
    """Lifetime (sum_seconds, count) per span name from the tracer's
    summary series — the wall-time side of the roofline join for phases
    that run inside real training (train_block, materialize)."""
    reg = registry if registry is not None else get_registry()
    out: Dict[str, Tuple[float, float]] = {}
    for m in reg.metrics():
        if m.name != metric or m.kind != "summary":
            continue
        span = m.label_dict.get("span")
        if not span:
            continue
        out[span] = (float(m.total), float(m.count))
    return out


def roofline_snapshot(registry: Optional[MetricsRegistry] = None,
                      cost_model: Optional[CostModel] = None,
                      extra_wall_times: Optional[
                          Dict[str, Tuple[float, float]]] = None
                      ) -> Dict[str, Any]:
    """The ``GET /roofline`` payload: detected peaks + one attribution
    row per extracted entry point, joined with whatever span wall-times
    the registry holds.  Entries that have no matching span (probe-only
    phases like the wave-width buckets) report static costs only, unless
    the caller supplies their timings via ``extra_wall_times``
    (``{name: (seconds, calls)}`` — perf_report passes the phase probe's
    standalone per-call times this way)."""
    peaks = detect_peaks()
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "")
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - scrape must answer regardless
        kind, backend = "", ""
    wall = span_wall_times(registry)
    if extra_wall_times:
        wall.update(extra_wall_times)
    rows = roofline_table(wall, cost_model=cost_model, peaks=peaks)
    return {
        "ts": round(time.time(), 3),
        "backend": backend,
        "device_kind": kind,
        "peaks": peaks,      # None on CPU: achieved rates only
        "rows": rows,
    }


def roofline_markdown(snapshot: Dict[str, Any]) -> str:
    """Render a roofline snapshot as a markdown table (perf_report)."""
    lines = ["| phase | calls | seconds | GFLOP/call | MB/call | "
             "GFLOP/s | GB/s | intensity | mfu | membw_util |",
             "|---|---|---|---|---|---|---|---|---|---|"]
    for r in snapshot.get("rows", []):
        def _g(key, scale, fmt="%.3f"):
            v = r.get(key)
            return (fmt % (v / scale)) if isinstance(v, (int, float)) else "-"
        lines.append("| %s | %d | %s | %s | %s | %s | %s | %s | %s | %s |" % (
            r.get("phase", "?"), int(r.get("calls", 0)),
            ("%.4f" % r["seconds"]) if r.get("seconds") else "-",
            _g("flops_per_call", 1e9), _g("bytes_per_call", 1e6),
            _g("flops_per_s", 1e9), _g("bytes_per_s", 1e9),
            ("%.4f" % r["arithmetic_intensity"])
            if "arithmetic_intensity" in r else "-",
            ("%.6f" % r["mfu"]) if "mfu" in r else "-",
            ("%.6f" % r["membw_util"]) if "membw_util" in r else "-"))
    if snapshot.get("peaks") is None:
        lines.append("")
        lines.append("_CPU backend: achieved rates only — no utilization "
                     "ratio is reported against a TPU peak._")
    return "\n".join(lines) + "\n"
