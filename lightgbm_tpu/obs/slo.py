"""Declarative SLOs evaluated as multi-window burn rates.

An SLO here is a budgeted objective over metrics the registry already
collects — no new instrumentation on any hot path:

- ``latency``: fraction of requests over a latency threshold, read from
  a Prometheus :class:`~lightgbm_tpu.obs.registry.Histogram`'s cumulative
  bucket counts (summed across label sets, so per-sink serving series
  aggregate correctly);
- ``availability``: errors + shed + timeouts vs total requests, read
  from Counters;
- ``throughput``: a rows/sec floor for training, read from a Counter's
  rate.

Evaluation follows the Google-SRE multi-window burn-rate recipe: the
engine keeps a timestamped ring of raw source samples and derives the
bad-fraction over a fast window (default 5m) and a slow window (default
1h); ``burn = bad_fraction / error_budget`` where the budget is
``1 - objective``.  An SLO is *burning* when both windows exceed
``slo_burn_warn`` — the fast window makes the alarm responsive, the slow
window keeps a brief blip from tripping it (early in a process's life
both windows clamp to the available history, so a sustained breach still
flips within one fast window — pinned by ``tools/slo_smoke.py``).

Results are exported three ways: ``lgbm_slo_*`` gauges on the same
registry (federated by the PR-9 cluster merge like any other metric), a
JSON ``status()`` document served as ``/slo`` on both StatsServers, and
a warn-only route through :class:`~lightgbm_tpu.obs.health.HealthMonitor`
(``note_slo_burn``) plus an optional ``on_burn`` callback — the seam a
fleet uses to arm the drift→refit→hot-roll loop off a burning budget.
"""
from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import registry as _registry
from .registry import Counter, Gauge, Histogram

_EPS = 1e-9


class SloSpec:
    """One declarative objective.  ``kind`` is ``latency`` |
    ``availability`` | ``throughput``; ``objective`` is the good-fraction
    target for the budgeted kinds (e.g. 0.99 => 1% error budget) and the
    rows/sec floor for ``throughput``."""

    def __init__(self, name: str, kind: str, objective: float,
                 source: str = "", bad_sources: Sequence[str] = (),
                 threshold_ms: float = 0.0, description: str = ""):
        self.name = str(name)
        self.kind = str(kind)
        self.objective = float(objective)
        self.source = str(source)
        self.bad_sources = tuple(bad_sources)
        self.threshold_ms = float(threshold_ms)
        self.description = str(description)

    def budget(self) -> float:
        """Error budget as a fraction; throughput floors have none."""
        if self.kind == "throughput":
            return 0.0
        return max(1.0 - self.objective, _EPS)

    def describe(self) -> Dict:
        doc = {"kind": self.kind, "objective": self.objective,
               "source": self.source, "description": self.description}
        if self.kind == "latency":
            doc["threshold_ms"] = self.threshold_ms
        if self.bad_sources:
            doc["bad_sources"] = list(self.bad_sources)
        return doc


def _histogram_totals(reg, name: str, threshold: float) -> Tuple[float, float]:
    """``(total, over_threshold)`` summed across every Histogram series
    named ``name`` regardless of labels.  ``le`` is inclusive, so when
    the threshold falls inside a bucket the whole bucket counts as bad —
    a conservative rounding that can only over-report burn."""
    total = over = 0.0
    for m in reg.metrics():
        if m.name != name or not isinstance(m, Histogram):
            continue
        bounds, counts = m.bucket_counts()
        t = float(sum(counts))
        i = bisect.bisect_left(bounds, threshold)
        if i < len(bounds) and bounds[i] == threshold:
            good = float(sum(counts[:i + 1]))
        else:
            good = float(sum(counts[:i]))
        total += t
        over += t - good
    return total, over


def _counter_total(reg, name: str) -> float:
    return float(sum(m.value for m in reg.metrics()
                     if m.name == name and isinstance(m, (Counter, Gauge))))


class SloEngine:
    """Samples SLO sources into a time ring and judges burn rates.

    Thread-safe; ``tick()`` is cheap (a registry scan) and is driven
    either by ``start(period_s)``'s daemon thread or synchronously by
    ``status()`` (so an ``/slo`` scrape is always fresh)."""

    def __init__(self, registry=None, fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0, burn_warn: float = 2.0,
                 monitor=None, on_burn: Optional[Callable] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.registry = (registry if registry is not None
                         else _registry.get_registry())
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_warn = float(burn_warn)
        self.monitor = monitor
        self.on_burn = on_burn
        self._time = time_fn
        self._specs: List[SloSpec] = []
        # ring of (t, {slo_name: (bad, total)}) raw cumulative samples
        self._history: collections.deque = collections.deque()
        self._burning: Dict[str, bool] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- declare
    def add_latency_slo(self, name: str, histogram: str,
                        threshold_ms: float, objective: float = 0.99,
                        description: str = "") -> SloSpec:
        spec = SloSpec(name, "latency", objective, source=histogram,
                       threshold_ms=threshold_ms, description=description)
        self._add(spec)
        return spec

    def add_availability_slo(self, name: str, requests: str,
                             bad: Sequence[str], objective: float,
                             description: str = "") -> SloSpec:
        spec = SloSpec(name, "availability", objective, source=requests,
                       bad_sources=bad, description=description)
        self._add(spec)
        return spec

    def add_throughput_slo(self, name: str, counter: str,
                           floor_per_s: float,
                           description: str = "") -> SloSpec:
        spec = SloSpec(name, "throughput", floor_per_s, source=counter,
                       description=description)
        self._add(spec)
        return spec

    def _add(self, spec: SloSpec) -> None:
        with self._lock:
            self._specs.append(spec)
            self._burning.setdefault(spec.name, False)

    def specs(self) -> List[SloSpec]:
        with self._lock:
            return list(self._specs)

    # ----------------------------------------------------------- sample
    def _sample(self, spec: SloSpec) -> Tuple[float, float]:
        """Cumulative ``(bad, total)`` right now.  For throughput the
        'total' is the cumulative row count and 'bad' is unused."""
        if spec.kind == "latency":
            total, over = _histogram_totals(self.registry, spec.source,
                                            spec.threshold_ms)
            return over, total
        if spec.kind == "availability":
            bad = sum(_counter_total(self.registry, n)
                      for n in spec.bad_sources)
            good = _counter_total(self.registry, spec.source)
            return bad, good + bad
        return 0.0, _counter_total(self.registry, spec.source)

    def tick(self, now: Optional[float] = None) -> None:
        """Record one raw sample of every source into the ring."""
        t = self._time() if now is None else float(now)
        with self._lock:
            sample = {s.name: self._sample(s) for s in self._specs}
            self._history.append((t, sample))
            horizon = t - self.slow_window_s - 1.0
            while len(self._history) > 2 and self._history[1][0] < horizon:
                self._history.popleft()

    # ------------------------------------------------------------ judge
    def _window_delta(self, name: str, window_s: float,
                      now: float) -> Tuple[float, float, float]:
        """``(d_bad, d_total, dt)`` between the newest sample and the
        newest sample at least ``window_s`` old (clamped to the oldest
        available — early-life windows judge whatever history exists)."""
        cur_t, cur = self._history[-1]
        cutoff = now - window_s
        past_t, past = self._history[0]
        for t, s in reversed(self._history):
            if t <= cutoff:
                past_t, past = t, s
                break
        cb, ct = cur.get(name, (0.0, 0.0))
        pb, pt = past.get(name, (0.0, 0.0))
        return cb - pb, ct - pt, max(cur_t - past_t, 0.0)

    def _judge(self, spec: SloSpec, window_s: float,
               now: float) -> Dict[str, float]:
        d_bad, d_total, dt = self._window_delta(spec.name, window_s, now)
        if spec.kind == "throughput":
            # no rows EVER means the trainer hasn't started (compile
            # warmup, setup) — a floor judges a running trainer, so hold
            # the verdict until the counter first moves
            _, cum_total = self._history[-1][1].get(spec.name, (0.0, 0.0))
            rate = d_total / dt if dt > _EPS else 0.0
            floor = spec.objective
            burn = (floor / max(rate, _EPS)) \
                if dt > _EPS and floor > 0 and cum_total > 0 else 0.0
            return {"burn": burn, "value": rate, "window_s": dt}
        bad_frac = d_bad / d_total if d_total > _EPS else 0.0
        return {"burn": bad_frac / spec.budget(), "value": bad_frac,
                "window_s": dt}

    def evaluate(self, now: Optional[float] = None) -> Dict:
        """Judge every SLO over both windows, refresh the ``lgbm_slo_*``
        gauges, and route newly-burning budgets warn-only through the
        HealthMonitor / ``on_burn`` hook.  Never raises on the hot path."""
        t = self._time() if now is None else float(now)
        flips = []
        with self._lock:
            if not self._history:
                return {"slos": {}, "burn_warn": self.burn_warn,
                        "fast_window_s": self.fast_window_s,
                        "slow_window_s": self.slow_window_s}
            out: Dict[str, Dict] = {}
            for spec in self._specs:
                fast = self._judge(spec, self.fast_window_s, t)
                slow = self._judge(spec, self.slow_window_s, t)
                burning = (fast["burn"] >= self.burn_warn
                           and slow["burn"] >= self.burn_warn)
                was = self._burning.get(spec.name, False)
                self._burning[spec.name] = burning
                doc = spec.describe()
                doc.update(fast_burn=round(fast["burn"], 4),
                           slow_burn=round(slow["burn"], 4),
                           observed=round(fast["value"], 6),
                           fast_span_s=round(fast["window_s"], 3),
                           slow_span_s=round(slow["window_s"], 3),
                           burning=burning)
                out[spec.name] = doc
                if burning and not was:
                    flips.append((spec, fast["burn"], slow["burn"],
                                  fast["value"]))
                lbl = {"slo": spec.name}
                self.registry.gauge(
                    "lgbm_slo_burn_rate", "SLO burn rate (fast window)",
                    labels=dict(lbl, window="fast")).set(fast["burn"])
                self.registry.gauge(
                    "lgbm_slo_burn_rate", "SLO burn rate (slow window)",
                    labels=dict(lbl, window="slow")).set(slow["burn"])
                self.registry.gauge(
                    "lgbm_slo_burning",
                    "1 when both burn windows exceed slo_burn_warn",
                    labels=lbl).set(1.0 if burning else 0.0)
                self.registry.gauge(
                    "lgbm_slo_value",
                    "Observed bad-fraction (or rows/sec) over the fast "
                    "window", labels=lbl).set(fast["value"])
            status = {"slos": out, "burn_warn": self.burn_warn,
                      "fast_window_s": self.fast_window_s,
                      "slow_window_s": self.slow_window_s}
        # warn routing OUTSIDE the engine lock: the monitor writes events
        # and logs, and a callback may do arbitrary work
        for spec, fast_burn, slow_burn, observed in flips:
            if self.monitor is not None:
                try:
                    self.monitor.note_slo_burn(
                        spec.name, fast_burn=fast_burn,
                        slow_burn=slow_burn, observed=observed,
                        objective=spec.objective, kind=spec.kind)
                except Exception:
                    pass
            if self.on_burn is not None:
                try:
                    self.on_burn(spec.name, fast_burn=fast_burn,
                                 slow_burn=slow_burn, observed=observed)
                except Exception:
                    pass
        return status

    def burning(self, name: str) -> bool:
        with self._lock:
            return self._burning.get(name, False)

    def status(self) -> Dict:
        """Fresh sample + judgment — the ``/slo`` response body."""
        self.tick()
        return self.evaluate()

    # ------------------------------------------------------------ drive
    def start(self, period_s: float = 5.0) -> "SloEngine":
        """Background ticker; idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.tick()
                    self.evaluate()
                except Exception:
                    pass            # judging must never kill the process

        self.tick()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="lgbm-slo")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
