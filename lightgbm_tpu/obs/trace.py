"""Spans, event streams and Perfetto capture for the real training loop.

The span API is host-side: a ``with tracer.span("hist_build")`` block
times wall clock and only touches the device at span CLOSE, where it can
``block_until_ready`` the arrays handed to it — one sync per span, never
per op, so the async dispatch pipeline inside a span stays intact.  When
tracing is disabled the span object is a shared no-op constant and the
``with`` costs two trivial method calls.

Events are JSON-lines (one object per line, ``ts`` + ``event`` keys
always present), append-only and flushed per write so a preempted run
keeps everything it logged.

Perfetto capture rides ``jax.profiler.start_trace/stop_trace``; the
trace lands under ``<dir>/plugins/profile/...`` and loads in
ui.perfetto.dev or TensorBoard.  Capture is process-global in jax, so
the helper refuses to nest instead of crashing mid-train.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Optional

from ..log import Log
from .registry import MetricsRegistry, get_registry


class EventStream:
    """Thread-safe JSON-lines sink (a file path or an open handle).

    Every record carries ``ts`` (wall clock) and ``seq`` — a per-stream
    monotonic counter assigned under the write lock.  ``seq`` is what
    ``tools/merge_events.py`` tie-breaks on when zipping streams from
    hosts with skewed clocks: wall time orders ACROSS streams, the
    monotonic counter orders WITHIN one.  ``static_fields`` (e.g.
    ``process``/``host`` in distributed runs) are stamped onto every
    record; ``ring`` is an optional flight recorder (anything with
    ``append``) that sees each record after it is written.
    """

    def __init__(self, path_or_fh, static_fields: Optional[Dict] = None,
                 ring=None):
        self._lock = threading.Lock()
        self._static = dict(static_fields or {})
        self._ring = ring
        self._seq = 0
        if hasattr(path_or_fh, "write"):
            self._fh = path_or_fh
            self._owns = False
        else:
            self._fh = open(path_or_fh, "a")
            self._owns = True

    def write(self, event: str, **fields) -> Dict:
        rec = {"ts": round(time.time(), 6), "event": event}
        rec.update(self._static)
        rec.update(fields)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            line = json.dumps(rec, sort_keys=True, default=str) + "\n"
            self._fh.write(line)
            self._fh.flush()
        if self._ring is not None:
            self._ring.append(rec)
        return rec

    def flush(self, fsync: bool = False) -> None:
        """Push buffered lines to the OS and, with ``fsync=True``, to
        disk — called from the crash paths (HealthMonitor abort, the
        checkpoint SIGTERM latch, the flight recorder's dump) so the
        final events before a kill are never lost."""
        with self._lock:
            try:
                self._fh.flush()
                if fsync:
                    import os
                    os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass   # closed handle / non-file sink: nothing to sync

    def close(self) -> None:
        self.flush(fsync=self._owns)
        with self._lock:
            if self._owns:
                self._fh.close()


class _NullSpan:
    """Disabled span: shared constant, ~free to enter/exit."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    def __init__(self, tracer: "Tracer", name: str, sync, fields: Dict):
        self._tracer = tracer
        self.name = name
        self._sync = sync
        self._fields = fields
        self.duration_s = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._sync is not None:
            try:
                import jax
                jax.block_until_ready(self._sync)  # lgbm-lint: disable=LGL103 span close
            except Exception:
                pass
        self.duration_s = time.perf_counter() - self._t0
        self._tracer._close(self, failed=exc_type is not None)
        return False


class Tracer:
    """Span factory bound to a registry summary + optional event stream."""

    def __init__(self, enabled: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 events: Optional[EventStream] = None,
                 metric: str = "lgbm_span_seconds"):
        self.enabled = enabled
        self._registry = registry if registry is not None else get_registry()
        self.events = events
        self._metric = metric

    def span(self, name: str, sync=None, **fields):
        """Open a timed span.  ``sync``: arrays to ``block_until_ready``
        at close (ONE sync point); extra ``fields`` land on the event."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, sync, fields)

    def _close(self, s: _Span, failed: bool) -> None:
        self._registry.summary(
            self._metric, "Wall-clock span durations.",
            labels={"span": s.name}).observe(s.duration_s)
        if self.events is not None:
            self.events.write("span", span=s.name,
                              dur_s=round(s.duration_s, 6),
                              failed=failed, **s._fields)


def span(name: str, sync=None, **fields):
    """Module-level convenience: an always-on span against the global
    registry (no event stream).  Library code should prefer a
    ``TrainingObs``-owned tracer, which respects ``observability=none``."""
    return Tracer(enabled=True).span(name, sync=sync, **fields)


# ------------------------------------------------------------ perfetto
_trace_lock = threading.Lock()
_trace_active = False


@contextlib.contextmanager
def perfetto_trace(trace_dir: Optional[str]):
    """Capture a ``jax.profiler`` trace into ``trace_dir`` for the body of
    the ``with``.  ``trace_dir`` falsy -> no-op.  Nested/concurrent
    captures degrade to a warning (jax's profiler is process-global).
    Yields True when a capture actually started."""
    global _trace_active
    if not trace_dir:
        yield False
        return
    with _trace_lock:
        if _trace_active:
            Log.warning("perfetto capture already active; skipping nested "
                        "capture into %s" % trace_dir)
            start = False
        else:
            _trace_active = True
            start = True
    if not start:
        yield False
        return
    started = False
    try:
        import jax
        try:
            jax.profiler.start_trace(trace_dir)
            started = True
        except Exception as e:  # profiler backend unavailable: degrade
            Log.warning("jax.profiler.start_trace failed (%s); continuing "
                        "without Perfetto capture" % e)
        yield started
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                Log.warning("jax.profiler.stop_trace failed: %s" % e)
        with _trace_lock:
            _trace_active = False


class PerfettoWindow:
    """Drive ``perfetto_trace`` over a [start, start+count) iteration
    window from inside the boosting loop.  ``step(lo, hi)`` is called
    before each dispatch covering iterations [lo, hi); capture starts
    when the window first overlaps and stops once ``hi`` passes the end
    (fused blocks widen the capture to block granularity)."""

    def __init__(self, trace_dir: str, start_iter: int, num_iters: int):
        self.trace_dir = trace_dir
        self.lo = int(start_iter)
        self.hi = int(start_iter) + int(num_iters)
        self._cm = None
        self.captured = False

    def step(self, lo: int, hi: int) -> None:
        if self._cm is None and lo < self.hi and hi > self.lo:
            self._cm = perfetto_trace(self.trace_dir)
            self.captured = bool(self._cm.__enter__())
        elif self._cm is not None and lo >= self.hi:
            self.close()

    def close(self) -> None:
        if self._cm is not None:
            cm, self._cm = self._cm, None
            cm.__exit__(None, None, None)
