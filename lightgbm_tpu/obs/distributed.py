"""Distributed telemetry: cross-process federation, straggler skew, and
the flight recorder (ISSUE 10).

Three pieces, all strictly host-side — nothing here runs inside (or
changes) a compiled program, so the training step's jaxpr fingerprint
and per-wave psum count are byte-identical with this module on or off:

- **Federation.** Each process's ``MetricsRegistry`` grows constant
  ``process=<jax.process_index()>`` / ``host=<hostname>`` labels injected
  at exposition time (``registry.set_global_labels`` — no call-site
  changes anywhere).  Once per fused block the processes allgather their
  JSON snapshot + Prometheus text (piggy-backed on the same allgather
  that carries the block timings), and every process caches the merged
  cluster view; the StatsServer's ``/metrics/cluster`` + ``/stats/cluster``
  routes serve that cache — scrapes are pull-only and never trigger a
  collective.  With ``jax.process_count() == 1`` the cluster routes
  degenerate to exactly the local snapshot and no allgather is ever
  issued.

- **Per-wave comm/compute attribution + straggler detection.**  The
  training loop hands ``on_block`` a host/device wall-time split for each
  synced dispatch (host side: feature-mask sampling + dispatch until the
  async call returns; device side: the ``block_until_ready`` wait).  The
  allgathered walls yield ``lgbm_wave_straggler_skew`` (max/median) and a
  per-wave stall estimate: this process's device wait minus the cluster
  minimum is time spent waiting on slower peers at the wave collectives
  — the comm-vs-compute split the GBDT benchmarking literature
  (PAPERS.md 1809.04559) calls out as what separates tuned from untuned
  distributed runs.  Skew above ``obs_straggler_warn_skew`` routes a
  warn-only report through the HealthMonitor (like stumps, stragglers
  never escalate to abort — they are an infra symptom, not a training
  anomaly).

- **Flight recorder.**  A bounded ring of the most recent events/spans
  per process that dumps to ``<obs_event_file>.<process>.crash.jsonl``
  on HealthMonitor abort, SIGTERM, or an unhandled exception — the
  post-mortem for "what was rank 3 doing when the run hung".
  ``tools/merge_events.py`` zips per-host streams (and crash dumps) into
  one time-ordered timeline.

Transport: host metadata only, never inside a compiled program.  On
backends that support multiprocess computations the allgather is
``multihost_utils.process_allgather`` (``parallel.network.JaxHostComm``);
the CPU backend cannot run cross-process computations at all, so there
the coordination-service KV store carries the payload
(``parallel.network.KvHostComm``) — ``parallel.network.default_host_comm``
picks.  Calls are SPMD-lockstep by construction: every process runs the
same block cadence, so allgather N on one process pairs with allgather N
on every other.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..log import Log
from .registry import MetricsRegistry, get_registry


def process_env() -> Tuple[int, int, str]:
    """(process_index, process_count, hostname) — safe to call whether or
    not jax.distributed is initialized (defaults to a single process)."""
    idx, count = 0, 1
    try:
        import jax
        idx = int(jax.process_index())
        count = int(jax.process_count())
    except Exception:
        pass
    import socket
    return idx, count, socket.gethostname()


def straggler_skew(walls: Sequence[float]) -> Tuple[float, int]:
    """``(max/median, argmax)`` over per-process wall times.  The
    max/median ratio is robust to one slow outlier inflating the mean
    (the straggler itself must not drag the denominator); a degenerate
    median (all ~zero) reports 1.0, never inf/NaN."""
    vals = [max(float(w), 0.0) for w in walls]
    if not vals:
        return 1.0, -1
    s = sorted(vals)
    n = len(s)
    med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
    mx = max(vals)
    arg = vals.index(mx)
    if med <= 1e-12:
        return 1.0, arg
    return mx / med, arg


def merge_prometheus_texts(texts: Sequence[str]) -> str:
    """Merge per-process Prometheus expositions into one: HELP/TYPE
    headers deduplicated (first process wins), sample lines grouped per
    family with every process's series kept — the per-process
    ``process=".."`` global labels make them distinct series, so no
    value-level merging is needed or wanted."""
    fams: Dict[str, Dict[str, List[str]]] = {}

    def fam(name: str) -> Dict[str, List[str]]:
        return fams.setdefault(name, {"help": [], "type": [], "samples": []})

    for text in texts:
        cur: Optional[str] = None
        for line in (text or "").splitlines():
            if line.startswith("# HELP "):
                cur = line.split()[2]
                f = fam(cur)
                if not f["help"]:
                    f["help"].append(line)
            elif line.startswith("# TYPE "):
                cur = line.split()[2]
                f = fam(cur)
                if not f["type"]:
                    f["type"].append(line)
            elif line.strip():
                if cur is None:        # headerless stray: key by base name
                    cur = line.split("{")[0].split(" ")[0]
                fam(cur)["samples"].append(line)
    lines: List[str] = []
    for name in sorted(fams):
        f = fams[name]
        lines += f["help"] + f["type"] + f["samples"]
    return "\n".join(lines) + ("\n" if lines else "")


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry records that dumps to
    ``<base_path>.<process>.crash.jsonl`` when the run dies.

    Fed by the EventStream (every written record lands here too) and by
    direct ``record()`` calls; ``install()`` hooks SIGTERM and
    ``sys.excepthook`` so the dump happens on kills and unhandled
    exceptions, and the HealthMonitor's fatal path calls ``dump``
    explicitly.  The SIGTERM hook chains: it dumps, restores the previous
    handler, and re-delivers the signal — composing with the checkpoint
    callback's latch-then-resign protocol (checkpoint/callback.py), which
    restores THIS handler before re-raising, so a checkpointed run dumps
    after its final snapshot and still exits like a SIGTERM'd process.
    Only the first dump wins (``dump`` latches), so abort-then-SIGTERM
    never truncates an earlier, more complete dump.
    """

    def __init__(self, base_path: str, process_index: int = 0,
                 size: int = 512, on_dump=None):
        self.process_index = int(process_index)
        self.dump_path = "%s.%d.crash.jsonl" % (base_path,
                                                self.process_index)
        self._ring = collections.deque(maxlen=max(int(size), 1))
        self._lock = threading.Lock()
        self._dumped = False
        self._on_dump = on_dump
        self._installed = False
        self._prev_sigterm = None
        self._prev_hook = None

    # ------------------------------------------------------------ feed
    def append(self, rec: Dict) -> None:
        with self._lock:
            self._ring.append(dict(rec))

    def record(self, event: str, **fields) -> None:
        rec = {"ts": round(time.time(), 6), "event": event}
        rec.update(fields)
        self.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------ dump
    def dump(self, reason: str) -> Optional[str]:
        with self._lock:
            if self._dumped:
                return self.dump_path
            self._dumped = True
            recs = list(self._ring)
        if self._on_dump is not None:
            try:
                self._on_dump(reason)
            except Exception:
                pass
        header = {"ts": round(time.time(), 6),
                  "event": "flight_recorder_dump", "reason": str(reason),
                  "process": self.process_index, "entries": len(recs)}
        try:
            with open(self.dump_path, "w") as fh:
                fh.write(json.dumps(header, sort_keys=True) + "\n")
                for rec in recs:
                    fh.write(json.dumps(rec, sort_keys=True,
                                        default=str) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError as e:
            Log.warning("obs: flight recorder dump to %s failed: %s"
                        % (self.dump_path, e))
            return None
        return self.dump_path

    # ------------------------------------------------------------ hooks
    def install(self) -> None:
        """Arm the SIGTERM + excepthook crash paths (idempotent)."""
        if self._installed:
            return
        self._installed = True
        self._prev_hook = sys.excepthook
        sys.excepthook = self._excepthook
        if threading.current_thread() is threading.main_thread():
            try:
                self._prev_sigterm = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
            except ValueError:
                self._prev_sigterm = None

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        # == not `is`: attribute access mints a fresh bound method, so an
        # identity check never matches the one install() stored
        if sys.excepthook == self._excepthook:
            sys.excepthook = self._prev_hook or sys.__excepthook__
        try:
            if signal.getsignal(signal.SIGTERM) == self._on_sigterm:
                signal.signal(signal.SIGTERM,
                              self._prev_sigterm
                              if self._prev_sigterm is not None
                              else signal.SIG_DFL)
        except ValueError:
            pass

    def _on_sigterm(self, signum, frame) -> None:
        self.dump("sigterm")
        prev = self._prev_sigterm
        self._installed = False
        try:
            signal.signal(signal.SIGTERM,
                          prev if prev is not None else signal.SIG_DFL)
        except ValueError:
            pass
        if callable(prev):
            prev(signum, frame)
        else:
            signal.raise_signal(signal.SIGTERM)

    def _excepthook(self, etype, value, tb) -> None:
        try:
            self.dump("exception:%s" % getattr(etype, "__name__", etype))
        except Exception:
            pass
        (self._prev_hook or sys.__excepthook__)(etype, value, tb)


class DistributedObs:
    """Per-process distributed-telemetry driver.

    Constructed by ``TrainingObs.from_config`` when observability is on
    and more than one jax process exists (or ``obs_distributed=on``).
    The training loop calls ``on_block`` once per synced dispatch; the
    StatsServer serves ``cluster_stats``/``cluster_prometheus``.  Tests
    drive it with an injected ``comm`` (``parallel.network.LoopbackComm``)
    and explicit process identity — no cluster required.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 monitor=None, comm=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 hostname: Optional[str] = None,
                 warn_skew: float = 2.0,
                 set_labels: bool = True,
                 timeout_ms: int = 60000):
        env_idx, env_count, env_host = process_env()
        self.process_index = env_idx if process_index is None \
            else int(process_index)
        self.process_count = env_count if process_count is None \
            else int(process_count)
        self.hostname = env_host if hostname is None else str(hostname)
        self.registry = registry if registry is not None else get_registry()
        self.monitor = monitor
        self.warn_skew = float(warn_skew)
        self._lock = threading.Lock()
        self._cluster: Optional[Dict] = None
        self._block = 0
        self._degraded = False
        if comm is None and self.process_count > 1:
            from ..parallel.network import default_host_comm
            comm = default_host_comm(namespace="lgbm_obs",
                                     timeout_ms=timeout_ms)
        self._comm = comm
        if set_labels and self.process_count > 1:
            self.registry.set_global_labels({
                "process": str(self.process_index), "host": self.hostname})
        self._g_skew = self.registry.gauge(
            "lgbm_wave_straggler_skew",
            "Max/median of per-process block wall time over the last "
            "allgathered dispatch (1.0 = perfectly balanced).")
        self._g_straggler = self.registry.gauge(
            "lgbm_dist_straggler_process",
            "Process index with the largest wall time in the last "
            "allgathered dispatch.")
        self._g_wall = self.registry.gauge(
            "lgbm_dist_block_seconds",
            "This process's wall time for the last synced dispatch.")
        self._g_host = self.registry.gauge(
            "lgbm_dist_block_host_seconds",
            "Host-side share of the last dispatch (feature sampling + "
            "dispatch until the async call returned).")
        self._g_dev = self.registry.gauge(
            "lgbm_dist_block_device_seconds",
            "Device-side share of the last dispatch (the "
            "block_until_ready wait: compute + wave collectives).")
        self._g_wave = self.registry.gauge(
            "lgbm_dist_wave_seconds",
            "This process's wall time per frontier wave over the last "
            "dispatch.")
        self._g_stall = self.registry.gauge(
            "lgbm_dist_wave_stall_seconds",
            "Per-wave stall estimate: this process's device wait minus "
            "the cluster minimum — time spent waiting on slower peers "
            "at the wave collectives.")
        self._c_blocks = self.registry.counter(
            "lgbm_dist_blocks_total",
            "Synced dispatches accounted by distributed obs.")
        self._c_allgathers = self.registry.counter(
            "lgbm_dist_allgathers_total",
            "Host-metadata allgathers issued (one per block when more "
            "than one process participates; always 0 single-process).")
        self._c_straggler = self.registry.counter(
            "lgbm_dist_straggler_blocks_total",
            "Blocks whose wall-time skew crossed "
            "obs_straggler_warn_skew.")

    # ------------------------------------------------------------ blocks
    def on_block(self, start_iter: int, count: int, busy_s: float,
                 wait_s: float, waves: float = 0.0) -> Optional[Dict]:
        """Account one synced dispatch and (multi-process) run the
        once-per-block allgather: timings + snapshot federation,
        straggler skew, cluster cache refresh.  Returns the cluster
        stats document, or None when single-process/degraded."""
        busy_s = max(float(busy_s), 0.0)
        wait_s = max(float(wait_s), 0.0)
        wall = busy_s + wait_s
        waves = max(float(waves), 0.0)
        self._g_wall.set(wall)
        self._g_host.set(busy_s)
        self._g_dev.set(wait_s)
        if waves > 0:
            self._g_wave.set(wall / waves)
        self._c_blocks.inc()
        if self.process_count <= 1 or self._comm is None:
            self._g_skew.set(1.0)
            return None
        if self._degraded:
            return None
        rec = {"process": self.process_index, "host": self.hostname,
               "block": self._block, "start_iter": int(start_iter),
               "count": int(count), "busy_s": round(busy_s, 6),
               "wait_s": round(wait_s, 6), "wall_s": round(wall, 6),
               "waves": waves}
        payload = {"timing": rec, "stats": self.registry.snapshot(),
                   "prom": self.registry.prometheus_text()}
        try:
            gathered = self._comm.allgather(payload)
            self._c_allgathers.inc()
        except Exception as e:
            # telemetry must never kill training: one warning, then the
            # rest of the run is local-only
            self._degraded = True
            Log.warning("obs.distributed: host allgather failed (%s); "
                        "cluster federation disabled for the rest of "
                        "this run" % e)
            return None
        self._block += 1
        timings = sorted((g["timing"] for g in gathered),
                         key=lambda t: t["process"])
        skew, arg = straggler_skew([t["wall_s"] for t in timings])
        straggler = timings[arg]["process"] if 0 <= arg < len(timings) \
            else -1
        self._g_skew.set(skew)
        self._g_straggler.set(straggler)
        min_dev = min(t["wait_s"] for t in timings)
        stall = max(wait_s - min_dev, 0.0)
        self._g_stall.set(stall / waves if waves > 0 else stall)
        doc = {
            "ts": round(time.time(), 3),
            "process_count": self.process_count,
            "block": rec["block"],
            "processes": {str(g["timing"]["process"]): g["stats"]
                          for g in gathered},
            "timings": {str(t["process"]): t for t in timings},
            "straggler": {"skew": round(skew, 4), "process": straggler,
                          "threshold": self.warn_skew},
        }
        prom = merge_prometheus_texts([g["prom"] for g in gathered])
        with self._lock:
            self._cluster = {"stats": doc, "prom": prom}
        if self.warn_skew > 0 and skew >= self.warn_skew:
            self._c_straggler.inc()
            note = getattr(self.monitor, "note_straggler", None)
            if note is not None:
                note(iteration=int(start_iter), process=straggler,
                     skew=skew, threshold=self.warn_skew)
            else:
                Log.warning(
                    "obs.distributed: process %d is a straggler "
                    "(wall-time skew %.2fx >= %.2fx) at iteration %d"
                    % (straggler, skew, self.warn_skew, int(start_iter)))
        return doc

    # ------------------------------------------------------------ routes
    def cluster_stats(self) -> Dict:
        """The ``/stats/cluster`` body.  Single-process: exactly the live
        local snapshot (and no allgather is ever issued).  Multi-process:
        the cached merge from the last block's allgather; before the
        first block completes, a pending doc carrying only the local
        snapshot."""
        if self.process_count <= 1:
            return self.registry.snapshot()
        with self._lock:
            cached = self._cluster
        if cached is None:
            return {"ts": round(time.time(), 3), "pending": True,
                    "process_count": self.process_count,
                    "processes": {str(self.process_index):
                                  self.registry.snapshot()}}
        return cached["stats"]

    def cluster_prometheus(self) -> str:
        """The ``/metrics/cluster`` body (same caching rules as
        ``cluster_stats``)."""
        if self.process_count <= 1:
            return self.registry.prometheus_text()
        with self._lock:
            cached = self._cluster
        if cached is None:
            return self.registry.prometheus_text()
        return cached["prom"]
