"""Objective functions (gradients/hessians) — pure JAX, vectorized.

TPU-native re-design of src/objective/* (objective_function.h:15-69 interface;
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
rank_objective.hpp, xentropy_objective.hpp). Per-point OpenMP loops become
vectorized array expressions; lambdarank's per-query sequential pair loop
becomes padded [Q, M, M] pairwise tensors vmapped over queries.

Formulas follow the reference exactly (e.g. binary response
``-y*sigmoid / (1 + exp(y*sigmoid*score))``, binary_objective.hpp:106-122;
multiclass hessian ``2 p (1-p)``, multiclass_objective.hpp:86).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .log import Log, LightGBMError, check
from .io.dataset import Metadata

_EPS = 1e-35


class ObjectiveFunction:
    """Interface mirror of objective_function.h:15-69."""

    name = "custom"
    num_model_per_iteration = 1
    is_constant_hessian = False
    need_query = False
    # objective_function.h NeedAccuratePrediction: only classification
    # margins tolerate prediction early stop (predictor.hpp:39)
    need_accurate_prediction = True

    def __init__(self, config: Config):
        self.config = config
        self.label: Optional[jnp.ndarray] = None
        self.weights: Optional[jnp.ndarray] = None
        self.num_data = 0

    def init(self, metadata: Metadata, num_data: int) -> None:
        check(metadata.label is not None, "label is required for objective %s" % self.name)
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weights = (None if metadata.weight is None
                        else jnp.asarray(metadata.weight, jnp.float32))
        self.num_data = num_data

    def _apply_weights(self, grad, hess):
        if self.weights is not None:
            return grad * self.weights, hess * self.weights
        return grad, hess

    def pad_to(self, num_rows: int, mesh=None, layout=None) -> None:
        """Pad per-row arrays for even mesh sharding (padded rows are masked
        out of every histogram/sum by the driver's row_valid mask; gradients
        computed on them are never used). Every jnp attribute of length
        num_data is treated as per-row (label, weights, trans_label,
        label_weight, ...).

        ``layout`` (streamed mesh training) maps a host [n0, ...] array
        to the full padded-row layout — shard-major blocks rather than
        trailing padding (stream/pipeline.py shard_rows_host) — before
        the row sharding is applied.

        Pre-pad host copies are kept (``host()``): host-side statistics
        like boost_from_score must see neither the padding rows (they'd
        bias means/percentiles) nor a multi-process-sharded array (not
        addressable from one host)."""
        n0 = self.label.shape[0]
        pad = num_rows - n0
        sh = None
        if mesh is not None:
            from .parallel.mesh import row_sharding
            sh = row_sharding(mesh)
        self._host_rows = {}
        for name, val in list(self.__dict__.items()):
            if not (isinstance(val, jnp.ndarray) and val.ndim >= 1
                    and val.shape[0] == n0):
                continue
            if val.ndim > 1 and sh is not None and layout is None:
                # mesh row_sharding is rank-1; 2-D per-row arrays
                # (multiclass onehot) keep the mesh path's 1-D contract
                continue
            self._host_rows[name] = np.asarray(val)
            if layout is not None:
                val = jnp.asarray(layout(self._host_rows[name]))
            elif pad > 0:
                val = jnp.concatenate(
                    [val, jnp.zeros((pad,) + val.shape[1:], val.dtype)])
            if sh is not None:
                from .parallel.mesh import row_sharding as _rs
                val = jax.device_put(
                    val, _rs(mesh, extra_dims=val.ndim - 1)
                    if val.ndim > 1 else sh)
            setattr(self, name, val)

    def host(self, name: str):
        """Host numpy view of a per-row attribute — the pre-pad, pre-shard
        copy when pad_to ran (multi-host safe, padding excluded); None when
        the attribute is None."""
        cache = getattr(self, "_host_rows", None)
        if cache is not None and name in cache:
            return cache[name]
        val = getattr(self, name)
        return None if val is None else np.asarray(val)

    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, score: jnp.ndarray) -> jnp.ndarray:
        return score

    # leaf refit hook (RenewTreeOutput, objective_function.h:55-60):
    # returns per-leaf replacement outputs or None
    renew_tree_output = None

    def _wmean(self, values: np.ndarray) -> float:
        w = self.host("weights")
        return float(np.average(np.asarray(values), weights=w))


# ---------------------------------------------------------------- regression
class RegressionL2Loss(ObjectiveFunction):
    """regression_objective.hpp:60-170 (optionally sqrt-transformed labels)."""
    name = "regression"
    is_constant_hessian = True  # when unweighted

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            lab = np.asarray(metadata.label, np.float64)
            self.trans_label = jnp.asarray(np.sign(lab) * np.sqrt(np.abs(lab)),
                                           jnp.float32)
        else:
            self.trans_label = self.label
        self.is_constant_hessian = self.weights is None

    def get_gradients(self, score):
        grad = score - self.trans_label
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        return self._wmean(self.host("trans_label"))

    def convert_output(self, score):
        if self.config.reg_sqrt:
            return jnp.sign(score) * score * score
        return score


class RegressionL1Loss(RegressionL2Loss):
    """regression_objective.hpp:173-260; leaf output renewed to the weighted
    median of residuals (RenewTreeOutput)."""
    name = "regression_l1"

    def get_gradients(self, score):
        diff = score - self.trans_label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = self.host("trans_label")
        if self.weights is not None:
            return _weighted_percentile(lab, self.host("weights"), 0.5)
        return float(np.percentile(lab, 50, method="lower")) if len(lab) else 0.0

    def renew_percentile(self) -> float:
        return 0.5


class RegressionHuberLoss(RegressionL2Loss):
    """regression_objective.hpp:263-350."""
    name = "huber"
    is_constant_hessian = False

    def get_gradients(self, score):
        diff = score - self.trans_label
        alpha = self.config.alpha
        grad = jnp.where(jnp.abs(diff) <= alpha, diff, jnp.sign(diff) * alpha)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)


class RegressionFairLoss(RegressionL2Loss):
    """regression_objective.hpp:353-420."""
    name = "fair"
    is_constant_hessian = False

    def get_gradients(self, score):
        c = self.config.fair_c
        x = score - self.trans_label
        grad = c * x / (jnp.abs(x) + c)
        hess = c * c / ((jnp.abs(x) + c) ** 2)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        return 0.0


class RegressionPoissonLoss(ObjectiveFunction):
    """regression_objective.hpp:423-490: log-link Poisson."""
    name = "poisson"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if float(np.min(self.host("label"))) < 0:
            raise LightGBMError("[poisson]: at least one target label is negative")

    def get_gradients(self, score):
        grad = jnp.exp(score) - self.label
        hess = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        return math.log(max(self._wmean(self.host("label")), 1e-20))

    def convert_output(self, score):
        return jnp.exp(score)


class RegressionQuantileLoss(RegressionL2Loss):
    """regression_objective.hpp:493-560."""
    name = "quantile"

    def get_gradients(self, score):
        alpha = self.config.alpha
        delta = score - self.trans_label
        grad = jnp.where(delta >= 0, 1.0 - alpha, -alpha)
        hess = jnp.ones_like(score)
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = self.host("trans_label")
        if self.weights is not None:
            return _weighted_percentile(lab, self.host("weights"),
                                        self.config.alpha)
        return float(np.percentile(lab, self.config.alpha * 100, method="lower"))

    def renew_percentile(self) -> float:
        return self.config.alpha


class RegressionMAPELoss(ObjectiveFunction):
    """regression_objective.hpp:600-680: |1 - score/label| via label weights."""
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(self.label, np.float64)
        w = np.asarray(self.weights) if self.weights is not None else np.ones_like(lab)
        self.label_weight = jnp.asarray(w / np.maximum(1.0, np.abs(lab)), jnp.float32)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = (jnp.ones_like(score) if self.weights is None
                else self.weights.astype(jnp.float32))
        return grad, hess

    def boost_from_score(self, class_id=0):
        lab = self.host("label")
        return _weighted_percentile(lab, self.host("label_weight"), 0.5)

    def renew_percentile(self) -> float:
        return 0.5


class RegressionGammaLoss(RegressionPoissonLoss):
    """regression_objective.hpp:740-770."""
    name = "gamma"

    def get_gradients(self, score):
        exp_s = jnp.exp(score)
        grad = 1.0 - self.label / exp_s
        hess = self.label / exp_s
        return self._apply_weights(grad, hess)


class RegressionTweedieLoss(RegressionPoissonLoss):
    """regression_objective.hpp:773-814."""
    name = "tweedie"

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        exp_1 = jnp.exp((1 - rho) * score)
        exp_2 = jnp.exp((2 - rho) * score)
        grad = -self.label * exp_1 + exp_2
        hess = (-self.label * (1 - rho) * exp_1 + (2 - rho) * exp_2)
        return self._apply_weights(grad, hess)


# -------------------------------------------------------------------- binary
class BinaryLogloss(ObjectiveFunction):
    """binary_objective.hpp:20-190."""
    need_accurate_prediction = False
    name = "binary"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(self.label)
        uniq = np.unique(lab)
        if not np.all(np.isin(uniq, [0, 1])):
            # reference accepts {-1,1} too via is_pos (binary_objective.hpp:40-70)
            if np.all(np.isin(uniq, [-1, 1])):
                lab = (lab > 0).astype(np.float32)
            else:
                raise LightGBMError("[binary]: label must be 0/1 (or -1/+1)")
        cnt_pos = float(lab.sum())
        cnt_neg = float(len(lab) - lab.sum())
        if cnt_pos == 0 or cnt_neg == 0:
            Log.warning("Contains only one class")
        w_pos, w_neg = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.config.scale_pos_weight
        self.y_signed = jnp.asarray(2 * lab - 1, jnp.float32)
        self.label01 = jnp.asarray(lab, jnp.float32)
        self.label_weight = jnp.asarray(np.where(lab > 0, w_pos, w_neg), jnp.float32)
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg

    def get_gradients(self, score):
        sig = self.config.sigmoid
        response = -self.y_signed * sig / (1.0 + jnp.exp(self.y_signed * sig * score))
        abs_r = jnp.abs(response)
        grad = response * self.label_weight
        hess = abs_r * (sig - abs_r) * self.label_weight
        return self._apply_weights(grad, hess)

    def boost_from_score(self, class_id=0):
        lab = self.host("label01")
        w = self.host("weights")
        pavg = float(np.average(lab, weights=w))
        pavg = min(max(pavg, 1e-15), 1 - 1e-15)
        init = math.log(pavg / (1 - pavg)) / self.config.sigmoid
        Log.info("[binary:BoostFromScore]: pavg=%.6f -> initscore=%.6f", pavg, init)
        return init

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------- multiclass
class MulticlassSoftmax(ObjectiveFunction):
    """multiclass_objective.hpp:20-160: K trees/iteration, softmax."""
    need_accurate_prediction = False
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(self.label).astype(np.int32)
        if lab.min() < 0 or lab.max() >= self.num_class:
            raise LightGBMError(
                "[multiclass]: label must be in [0, %d)" % self.num_class)
        self.label_int = jnp.asarray(lab)
        self.onehot = jax.nn.one_hot(self.label_int, self.num_class,
                                     dtype=jnp.float32)  # [N, K]

    def get_gradients(self, score):
        """score: [N, K] -> grad/hess [N, K]."""
        p = jax.nn.softmax(score, axis=-1)
        grad = p - self.onehot
        hess = 2.0 * p * (1.0 - p)
        if self.weights is not None:
            grad = grad * self.weights[:, None]
            hess = hess * self.weights[:, None]
        return grad, hess

    def boost_from_score(self, class_id=0):
        return 0.0

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    """multiclass_objective.hpp:170-259: K independent binary objectives."""
    need_accurate_prediction = False
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(self.label).astype(np.int32)
        self.onehot = jax.nn.one_hot(jnp.asarray(lab), self.num_class,
                                     dtype=jnp.float32)
        self._binary_inits = []
        for k in range(self.num_class):
            m = Metadata(num_data)
            m.set_label((lab == k).astype(np.float32))
            if self.weights is not None:
                m.set_weight(np.asarray(self.weights))
            b = BinaryLogloss(self.config)
            b.init(m, num_data)
            self._binary_inits.append(b)

    def get_gradients(self, score):
        sig = self.config.sigmoid
        y_signed = 2 * self.onehot - 1
        response = -y_signed * sig / (1.0 + jnp.exp(y_signed * sig * score))
        abs_r = jnp.abs(response)
        grad, hess = response, abs_r * (sig - abs_r)
        if self.weights is not None:
            grad = grad * self.weights[:, None]
            hess = hess * self.weights[:, None]
        return grad, hess

    def boost_from_score(self, class_id=0):
        return self._binary_inits[class_id].boost_from_score(0)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ------------------------------------------------------------------ xentropy
class CrossEntropy(ObjectiveFunction):
    """xentropy_objective.hpp:30-130: labels in [0,1], sigmoid link."""
    name = "xentropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(self.label)
        if lab.min() < 0 or lab.max() > 1:
            raise LightGBMError("[xentropy]: label must be in [0, 1]")

    def get_gradients(self, score):
        p = 1.0 / (1.0 + jnp.exp(-score))
        if self.weights is None:
            return p - self.label, p * (1.0 - p)
        return ((p - self.label) * self.weights,
                p * (1.0 - p) * self.weights)

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._wmean(self.host("label")), 1e-15), 1 - 1e-15)
        return math.log(pavg / (1 - pavg))

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class CrossEntropyLambda(CrossEntropy):
    """xentropy_objective.hpp:140-250: weighted xentropy w/ log1p(exp) link."""
    name = "xentlambda"

    def get_gradients(self, score):
        w = self.weights if self.weights is not None else jnp.ones_like(score)
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = jnp.exp(-score)
        grad = (1.0 - self.label / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (z * d)
        b = (d - 1.0) / d
        hess = self.label * a * (c * b * w - (a - b)) + (1.0 - self.label) * w * b / d * (
            1.0 + w * epf / d)
        # guard numerical blowups like the reference's double math
        hess = jnp.where(jnp.isfinite(hess) & (hess > 0), hess, 1e-6)
        grad = jnp.where(jnp.isfinite(grad), grad, 0.0)
        return grad, hess

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._wmean(self.host("label")), 1e-15), 1 - 1e-15)
        return math.log(math.expm1(pavg)) if pavg > 0 else -50.0

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))


# -------------------------------------------------------------------- ranking
def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 (dcg_calculator.cpp:30-38)."""
    return np.array([0.0] + [float((1 << i) - 1) for i in range(1, max_label)])


class LambdarankNDCG(ObjectiveFunction):
    """rank_objective.hpp:19-240, vectorized over padded queries.

    Per query: sort by score desc, position discounts 1/log2(2+rank), pairwise
    |ΔNDCG|-weighted sigmoid lambdas; exact reference formulas incl. the
    /(0.01+|Δscore|) regularization.
    """
    name = "lambdarank"
    need_query = False  # checked at init

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise LightGBMError("Lambdarank tasks require query information")
        qb = np.asarray(metadata.query_boundaries, np.int64)
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        self.max_docs = int(sizes.max())
        q, m = self.num_queries, self.max_docs
        # padded [Q, M] doc index matrix; padding points at row 0 with mask 0
        doc_idx = np.zeros((q, m), np.int32)
        doc_mask = np.zeros((q, m), np.float32)
        for i in range(q):
            c = sizes[i]
            doc_idx[i, :c] = np.arange(qb[i], qb[i + 1])
            doc_mask[i, :c] = 1.0
        self.doc_idx = jnp.asarray(doc_idx)
        self.doc_mask = jnp.asarray(doc_mask)

        gains = self.config.label_gain
        lg = (np.asarray(gains, np.float64) if gains else default_label_gain())
        self.label_gain = jnp.asarray(lg, jnp.float32)
        lab = np.asarray(self.label).astype(np.int32)
        check(lab.max() < len(lg), "label excels label_gain size")
        # inverse max DCG at k per query (rank_objective.hpp:55-65)
        k = self.config.max_position
        inv = np.zeros(q, np.float64)
        disc = 1.0 / np.log2(2.0 + np.arange(m))
        for i in range(q):
            ql = np.sort(lab[qb[i]:qb[i + 1]])[::-1][:k]
            mx = float(np.sum(lg[ql] * disc[:len(ql)]))
            inv[i] = 1.0 / mx if mx > 0 else 0.0
        self.inverse_max_dcg = jnp.asarray(inv, jnp.float32)
        self.discount = jnp.asarray(disc, jnp.float32)
        self.label_pad = jnp.asarray(lab)

    def get_gradients(self, score):
        sig = self.config.sigmoid
        labels = self.label_pad[self.doc_idx]          # [Q, M] int
        s = score[self.doc_idx]                        # [Q, M]
        mask = self.doc_mask                           # [Q, M]
        neg_inf = jnp.float32(-1e30)
        s_masked = jnp.where(mask > 0, s, neg_inf)

        def one_query(s_q, lab_q, mask_q, inv_max_dcg):
            m = s_q.shape[0]
            # rank of each doc (0 = best); stable sort by -score
            order = jnp.argsort(-s_q, stable=True)      # [M] doc at rank r
            rank_of = jnp.zeros((m,), jnp.int32).at[order].set(
                jnp.arange(m, dtype=jnp.int32))
            disc = self.discount[rank_of] * mask_q      # positional discount
            gain = self.label_gain[lab_q]
            best = jnp.max(jnp.where(mask_q > 0, s_q, neg_inf))
            worst = jnp.min(jnp.where(mask_q > 0, s_q, jnp.float32(1e30)))
            norm = best != worst
            # pairwise [M, M]: i=high, j=low, only label_i > label_j
            ds = s_q[:, None] - s_q[None, :]
            hi = lab_q[:, None] > lab_q[None, :]
            pair_ok = hi & (mask_q[:, None] > 0) & (mask_q[None, :] > 0)
            dcg_gap = gain[:, None] - gain[None, :]
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            delta_ndcg = jnp.where(norm,
                                   delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
            p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * sig * ds))
            p_hess = p_lambda * (2.0 - p_lambda)
            lam = jnp.where(pair_ok, -p_lambda * delta_ndcg, 0.0)
            hes = jnp.where(pair_ok, 2.0 * p_hess * delta_ndcg, 0.0)
            g_q = jnp.sum(lam, axis=1) - jnp.sum(lam, axis=0)
            h_q = jnp.sum(hes, axis=1) + jnp.sum(hes, axis=0)
            return g_q, h_q

        g_pad, h_pad = jax.vmap(one_query)(
            s_masked, labels, mask, self.inverse_max_dcg)
        n = score.shape[0]
        flat_idx = self.doc_idx.reshape(-1)
        flat_m = mask.reshape(-1)
        grad = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            g_pad.reshape(-1) * flat_m)
        hess = jnp.zeros((n,), jnp.float32).at[flat_idx].add(
            h_pad.reshape(-1) * flat_m)
        return self._apply_weights(grad, hess)


# ------------------------------------------------------------------- factory
_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Factory (objective_function.cpp:11-42); None for objective="none"."""
    name = config.objective
    if name in ("none", "", None):
        return None
    if name not in _OBJECTIVES:
        raise LightGBMError("Unknown objective type name: %s" % name)
    return _OBJECTIVES[name](config)


def _weighted_percentile(values: np.ndarray, weights: np.ndarray,
                         alpha: float) -> float:
    """PercentileFun/WeightedPercentileFun analog (regression_objective.hpp:20-55)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values)
    v, w = values[order], weights[order]
    cum = np.cumsum(w)
    target = alpha * cum[-1]
    idx = int(np.searchsorted(cum, target, side="left"))
    return float(v[min(idx, len(v) - 1)])
