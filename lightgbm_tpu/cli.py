"""Command-line application.

TPU-native counterpart of src/main.cpp + src/application/application.cpp:
``python -m lightgbm_tpu [config=train.conf] [key=value ...]`` dispatching
the four reference tasks (include/LightGBM/application.h:74):

- ``task=train``         — load data, train, save model (application.cpp:202)
- ``task=predict``       — batch-score a file (application.cpp:213-250)
- ``task=convert_model`` — model -> standalone C++ if-else scorer
  (gbdt_model_text.cpp:60-243 ModelToIfElse analog)
- ``task=refit``         — refit an existing model's leaf values on new data
  (gbdt.cpp:263-286)

plus one TPU-native extension:

- ``task=serve``         — boot the compiled batch-inference server
  (lightgbm_tpu.serving): load ``input_model``, warm every batch bucket,
  then answer HTTP or stdin JSON requests with zero recompiles. Also
  reachable as ``python -m lightgbm_tpu.serving``.

Argument handling mirrors Application::LoadParameters (application.cpp:48-81):
``key=value`` tokens on the command line, an optional ``config=`` file of
``key=value`` lines with ``#`` comments, command line taking precedence.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .log import Log, LightGBMError


def kv2map(tokens: List[str], strip_comments: bool = False) -> Dict[str, str]:
    """Parse key=value tokens (Config::KV2Map, config.cpp:15). ``#`` comments
    are stripped only from config-file lines — command-line values may
    legitimately contain ``#`` (paths etc.)."""
    out: Dict[str, str] = {}
    for tok in tokens:
        if strip_comments:
            tok = tok.split("#", 1)[0]
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise LightGBMError("Unknown parameter %r (expected key=value)"
                                % tok)
        k, v = tok.split("=", 1)
        k, v = k.strip(), v.strip()
        if k in out:
            Log.warning("Duplicated parameter %s, keeping first value", k)
            continue
        out[k] = v
    return out


def load_parameters(argv: List[str]) -> Dict[str, str]:
    """Command line first, then config file for keys not already set
    (application.cpp:48-81)."""
    cmdline = kv2map(argv)
    conf_path = cmdline.pop("config", cmdline.pop("config_file", ""))
    params = dict(cmdline)
    if conf_path:
        with open(conf_path, "r") as fh:
            file_params = kv2map(fh.read().splitlines(), strip_comments=True)
        for k, v in file_params.items():
            params.setdefault(k, v)
    return params


def _load_file_dataset(path: str, config: Config, params: Dict,
                       reference=None):
    """Build a Dataset from a text file + sidecar files (.weight/.query/
    .init), the Metadata file convention (src/io/metadata.cpp)."""
    from .basic import Dataset
    from .io import parser as parser_mod

    X, y, names = parser_mod.parse_file(
        path, has_header=config.header, label_column=config.label_column)
    weight = parser_mod.load_weight_file(path)
    group = parser_mod.load_query_file(path)
    init_score = parser_mod.load_init_score_file(path)
    return Dataset(X, label=y, reference=reference, weight=weight,
                   group=group, init_score=init_score,
                   feature_name=(names if names else "auto"),
                   params=dict(params))


def run_train(config: Config, params: Dict) -> None:
    from . import engine
    from .callback import print_evaluation

    if not config.data:
        raise LightGBMError("No training data: pass data=<file>")
    Log.info("Loading train data %s", config.data)
    train_set = _load_file_dataset(config.data, config, params)
    valid_sets, valid_names = [], []
    for i, vpath in enumerate(config.valid):
        Log.info("Loading validation data %s", vpath)
        valid_sets.append(_load_file_dataset(vpath, config, params,
                                             reference=train_set))
        valid_names.append(os.path.basename(vpath))
    if config.save_binary:
        train_set.construct().save_binary(config.data + ".bin")

    callbacks = []
    if config.metric_freq > 0 and config.verbosity >= 0:
        callbacks.append(print_evaluation(period=config.metric_freq))
    snapshot_freq = config.snapshot_freq
    if snapshot_freq > 0:
        out = config.output_model

        def snapshot_cb(env):
            it = env.iteration + 1
            if it % snapshot_freq == 0:
                env.model.save_model("%s.snapshot_iter_%d" % (out, it))
        snapshot_cb.order = 40
        callbacks.append(snapshot_cb)
    if config.checkpoint_dir:
        # preemption-safe full-state snapshots + SIGTERM handling
        # (lightgbm_tpu.checkpoint; resume with resume=<dir>)
        from .callback import checkpoint as checkpoint_cb
        callbacks.append(checkpoint_cb(config.checkpoint_dir,
                                       period=config.checkpoint_period,
                                       keep_last_n=config.checkpoint_keep))
    if config.health_monitor in ("abort", "raise"):
        # escalating health actions want per-iteration detection; the
        # callback's presence forces the per-iteration loop and arms the
        # device-side flags before the first compile
        from .callback import health_monitor
        callbacks.append(health_monitor(config.health_monitor))

    booster = engine.train(
        dict(params), train_set,
        num_boost_round=config.num_iterations,
        valid_sets=valid_sets or None,
        valid_names=valid_names or None,
        init_model=(config.input_model or None),
        early_stopping_rounds=(config.early_stopping_round
                               if config.early_stopping_round > 0 else None),
        verbose_eval=False,
        callbacks=callbacks or None,
        resume_from=(config.resume or None),
        supervise=(config.supervise or None))
    booster.save_model(config.output_model)
    Log.info("Finished training; model saved to %s", config.output_model)
    obs = getattr(booster._impl, "obs", None)
    if obs is not None and obs.enabled and obs.monitor is not None:
        Log.info("Telemetry: %d health anomalies (%d reports); see "
                 "docs/Observability.md", obs.monitor.anomaly_count(),
                 len(obs.monitor.reports))


def run_predict(config: Config, params: Dict) -> None:
    from .basic import Booster
    from .io import parser as parser_mod

    if not config.input_model:
        raise LightGBMError("No model file: pass input_model=<file>")
    if not config.data:
        raise LightGBMError("No data for prediction: pass data=<file>")
    booster = Booster(model_file=config.input_model)
    X, _, _ = parser_mod.parse_file(config.data, has_header=config.header,
                                    label_column=config.label_column)
    num_iter = (config.num_iteration_predict
                if config.num_iteration_predict > 0 else None)
    pred = booster.predict(X, num_iteration=num_iter,
                           raw_score=config.predict_raw_score,
                           pred_leaf=config.predict_leaf_index,
                           pred_contrib=config.predict_contrib,
                           pred_early_stop=config.pred_early_stop,
                           pred_early_stop_freq=config.pred_early_stop_freq,
                           pred_early_stop_margin=config.pred_early_stop_margin)
    pred = np.atleast_1d(pred)
    with open(config.output_result, "w") as fh:
        if pred.ndim == 1:
            for v in pred:
                fh.write("%.12g\n" % v)
        else:
            for row in pred:
                fh.write("\t".join("%.12g" % v for v in row) + "\n")
    Log.info("Finished prediction; results saved to %s", config.output_result)


def run_convert_model(config: Config, params: Dict) -> None:
    from .basic import Booster
    from .io.model_text import model_to_cpp

    if not config.input_model:
        raise LightGBMError("No model file: pass input_model=<file>")
    if config.convert_model_language not in ("", "cpp"):
        raise LightGBMError("Unsupported convert_model_language %r "
                            "(only cpp)" % config.convert_model_language)
    booster = Booster(model_file=config.input_model)
    code = model_to_cpp(booster._loaded)
    with open(config.convert_model, "w") as fh:
        fh.write(code)
    Log.info("Model converted to C++ at %s", config.convert_model)


def run_refit(config: Config, params: Dict) -> None:
    from .basic import Booster
    from .io import parser as parser_mod

    if not config.input_model:
        raise LightGBMError("No model file: pass input_model=<file>")
    if not config.data:
        raise LightGBMError("No data for refit: pass data=<file>")
    booster = Booster(model_file=config.input_model)
    X, y, _ = parser_mod.parse_file(config.data, has_header=config.header,
                                    label_column=config.label_column)
    refitted = booster.refit(X, y, decay_rate=config.refit_decay_rate,
                             weight=parser_mod.load_weight_file(config.data),
                             group=parser_mod.load_query_file(config.data))
    refitted.save_model(config.output_model)
    Log.info("Finished refit; model saved to %s", config.output_model)


def run_serve(config: Config, params: Dict) -> None:
    from .serving.server import run_server

    run_server(config, params)


_TASKS = {
    "train": run_train, "training": run_train,
    "predict": run_predict, "prediction": run_predict, "test": run_predict,
    "convert_model": run_convert_model,
    "refit": run_refit, "refit_tree": run_refit,
    "serve": run_serve, "serving": run_serve,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    try:
        params = load_parameters(argv)
        config = Config(dict(params))
        task_fn = _TASKS.get(config.task)
        if task_fn is None:
            raise LightGBMError("Unknown task %r" % config.task)
        task_fn(config, params)
        return 0
    except (LightGBMError, OSError, ValueError) as e:
        # the reference Application catches any std::exception and prints a
        # one-line error (main.cpp); mirror that for I/O and parse failures
        Log.warning("Met Exceptions: %s", str(e))
        print("Error: %s" % e, file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
