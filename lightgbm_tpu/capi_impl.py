"""Python side of the C ABI (native/src/c_api.cpp).

The embedded interpreter calls these flat functions with primitive
arguments (memoryviews over caller-owned buffers, strings, ints) and gets
primitives/bytes back, keeping the C++ shim free of object-protocol
details. The reference implements the same surface natively
(src/c_api.cpp:46-363 Booster wrapper + the LGBM_* bodies); here the
runtime IS the Python package, so the ABI marshals into it.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

# honor the host's JAX_PLATFORMS choice BEFORE any backend init: site
# hooks may overwrite the env var, but jax.config wins over both
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from .basic import Booster, Dataset
from .log import LightGBMError

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def parse_params(s: Optional[str]) -> Dict[str, str]:
    """"k1=v1 k2=v2" -> dict (Config::KV2Map semantics, config.cpp)."""
    out: Dict[str, str] = {}
    for tok in (s or "").replace("\t", " ").split(" "):
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
        else:
            out[tok] = "true"
    return out


def _mat(mv: memoryview, dtype_code: int, nrow: int, ncol: int,
         row_major: int) -> np.ndarray:
    dt = _DTYPES[dtype_code]
    arr = np.frombuffer(mv, dtype=dt, count=nrow * ncol)
    if row_major:
        return arr.reshape(nrow, ncol)
    return arr.reshape(ncol, nrow).T


def dataset_from_file(filename: str, params: str,
                      reference: Optional[Dataset]) -> Dataset:
    p = parse_params(params)
    label_kw = {}
    ds = Dataset(filename, reference=reference, params=p,
                 free_raw_data=False, **label_kw)
    ds.construct()
    return ds


def dataset_from_mat(mv: memoryview, dtype_code: int, nrow: int, ncol: int,
                     row_major: int, params: str,
                     reference: Optional[Dataset]) -> Dataset:
    # the C contract lets the host free its buffer as soon as the call
    # returns; copy=True guards against astype's no-op fast path handing
    # back a view of caller memory
    data = _mat(mv, dtype_code, nrow, ncol, row_major) \
        .astype(np.float64, copy=True)
    ds = Dataset(data, reference=reference, params=parse_params(params),
                 free_raw_data=False)
    return ds


def _csr_parts(indptr_mv, indptr_code, indices_mv, data_mv, data_code,
               nindptr, nelem):
    """Copy CSR pieces out of caller-owned memory (the host may free its
    buffers on return); nelem == 0 (all-zero rows) is a valid matrix."""
    indptr = np.frombuffer(indptr_mv, dtype=_DTYPES[indptr_code],
                           count=nindptr).copy()
    if nelem == 0:
        return indptr, np.zeros(0, np.int32), np.zeros(0, np.float64)
    indices = np.frombuffer(indices_mv, dtype=np.int32, count=nelem).copy()
    vals = np.frombuffer(data_mv, dtype=_DTYPES[data_code],
                         count=nelem).copy()
    return indptr, indices, vals


def dataset_from_csr(indptr_mv: memoryview, indptr_code: int,
                     indices_mv: memoryview, data_mv: memoryview,
                     data_code: int, nindptr: int, nelem: int,
                     num_col: int, params: str,
                     reference: Optional[Dataset]) -> Dataset:
    from scipy.sparse import csr_matrix
    indptr, indices, vals = _csr_parts(
        indptr_mv, indptr_code, indices_mv, data_mv, data_code, nindptr,
        nelem)
    mat = csr_matrix((vals, indices, indptr),
                     shape=(nindptr - 1, num_col))
    return Dataset(mat, reference=reference, params=parse_params(params),
                   free_raw_data=False)


def dataset_set_field(ds: Dataset, name: str, mv: Optional[memoryview],
                      num_element: int, dtype_code: int) -> None:
    arr = None if (mv is None or num_element == 0) else np.array(
        np.frombuffer(mv, dtype=_DTYPES[dtype_code], count=num_element))
    if isinstance(ds, PendingDataset) and not hasattr(ds, "_final") \
            and not ds.finished:
        # streaming construction: the reference allows SetField at any
        # point before FinishLoad; stash and apply at finalize
        ds.pending_fields[name] = arr
        return
    _as_dataset(ds).set_field(name, arr)


def dataset_num_data(ds) -> int:
    if isinstance(ds, PendingDataset) and not hasattr(ds, "_final"):
        # the reference reports num_total_row before FinishLoad
        return int(ds.raw.shape[0])
    return int(_as_dataset(ds).construct().num_data())


def dataset_num_feature(ds) -> int:
    if isinstance(ds, PendingDataset) and not hasattr(ds, "_final"):
        return int(ds.raw.shape[1])
    return int(_as_dataset(ds).construct().num_feature())


def dataset_set_feature_names(ds, names: List[str]) -> None:
    _as_dataset(ds).feature_name = list(names)


def booster_create(train, params: str) -> Booster:
    return Booster(params=parse_params(params), train_set=_as_dataset(train))


def booster_from_file(filename: str) -> Tuple[Booster, int]:
    bst = Booster(model_file=filename)
    return bst, bst.current_iteration


def booster_from_string(model_str: str) -> Tuple[Booster, int]:
    bst = Booster(model_str=model_str)
    return bst, bst.current_iteration


def booster_add_valid(bst: Booster, valid) -> None:
    bst.add_valid(_as_dataset(valid),
                  "valid_%d" % (len(bst._valid_sets) + 1))


def booster_update(bst: Booster) -> int:
    return int(bool(bst.update()))


def booster_update_custom(bst: Booster, grad_mv: memoryview,
                          hess_mv: memoryview, n: int) -> int:
    grad = np.frombuffer(grad_mv, dtype=np.float32, count=n)
    hess = np.frombuffer(hess_mv, dtype=np.float32, count=n)
    return int(bool(bst._impl.train_one_iter(np.array(grad),
                                             np.array(hess))))


def booster_num_classes(bst: Booster) -> int:
    return int(bst._impl.num_class)


def booster_num_train_rows_times_classes(bst: Booster) -> int:
    impl = bst._impl
    return int(impl.num_data * impl.num_tree_per_iteration)


def booster_rollback(bst: Booster) -> None:
    bst.rollback_one_iter()


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration)


def booster_num_model_per_iteration(bst: Booster) -> int:
    return int(bst.num_model_per_iteration())


def booster_num_total_model(bst: Booster) -> int:
    return int(bst.num_trees())


def booster_merge(dst: Booster, src: Booster) -> None:
    """GBDT::MergeFrom (gbdt.h:53-64): src's trees go FIRST (deep copies),
    dst's own trees follow; num_init_iteration tracks the prefix."""
    import copy as _copy
    k = max(dst._impl.num_tree_per_iteration, 1)
    if max(src._impl.num_tree_per_iteration, 1) != k:
        raise LightGBMError("cannot merge boosters with different "
                            "trees-per-iteration")
    merged = _copy.deepcopy(src._impl.models) + list(dst._impl.models)
    dst._impl.models = merged
    dst._impl.num_init_iteration = len(src._impl.models) // k
    dst._impl.iter_ = len(merged) // k


def booster_eval(bst: Booster, data_idx: int) -> bytes:
    if data_idx == 0:
        res = bst.eval_train()
    else:
        res = [r for r in bst.eval_valid()
               if r[0] == ("valid_%d" % data_idx)]
    return np.asarray([v for _, _, v, _ in res], np.float64).tobytes()


def booster_eval_names(bst: Booster) -> List[str]:
    names = []
    for m in bst._impl.train_metrics:
        names.extend(m.names)
    return names


def _predict_kwargs(predict_type: int, num_iteration: int,
                    parameter: str) -> Dict:
    """One predict-kwargs builder for every prediction entry point, so
    the mat/CSR paths cannot drift."""
    kw = dict(num_iteration=(num_iteration if num_iteration > 0 else None))
    if predict_type == 1:
        kw["raw_score"] = True
    elif predict_type == 2:
        kw["pred_leaf"] = True
    elif predict_type == 3:
        kw["pred_contrib"] = True
    p = parse_params(parameter)
    if "pred_early_stop" in p:
        kw["pred_early_stop"] = p["pred_early_stop"] in ("true", "1")
    return kw


def booster_predict_csr(bst: Booster, indptr_mv: memoryview,
                        indptr_code: int, indices_mv: memoryview,
                        data_mv: memoryview, data_code: int, nindptr: int,
                        nelem: int, num_col: int, predict_type: int,
                        num_iteration: int, parameter: str) -> bytes:
    from scipy.sparse import csr_matrix
    indptr, indices, vals = _csr_parts(
        indptr_mv, indptr_code, indices_mv, data_mv, data_code, nindptr,
        nelem)
    mat = csr_matrix((vals, indices, indptr), shape=(nindptr - 1, num_col))
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    # Booster.predict streams sparse input in bounded row blocks itself
    # (the reference's CSR-row streaming); one code path for every caller
    return np.asarray(bst.predict(mat, **kw), np.float64).tobytes()


def booster_predict_mat(bst: Booster, mv: memoryview, dtype_code: int,
                        nrow: int, ncol: int, row_major: int,
                        predict_type: int, num_iteration: int,
                        parameter: str) -> bytes:
    data = _mat(mv, dtype_code, nrow, ncol, row_major)
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    out = np.asarray(bst.predict(np.ascontiguousarray(data, np.float64),
                                 **kw), np.float64)
    return out.tobytes()


def booster_save_model(bst: Booster, start_iteration: int,
                       num_iteration: int, filename: str) -> None:
    # C ABI: num_iteration <= 0 means "all" (not best_iteration)
    bst.save_model(filename,
                   num_iteration=(num_iteration if num_iteration > 0
                                  else -1),
                   start_iteration=max(start_iteration, 0))


def booster_model_to_string(bst: Booster, start_iteration: int,
                            num_iteration: int) -> str:
    return bst.model_to_string(
        num_iteration=(num_iteration if num_iteration > 0 else -1),
        start_iteration=max(start_iteration, 0))


def booster_dump_model(bst: Booster, start_iteration: int,
                       num_iteration: int) -> str:
    import json
    return json.dumps(bst.dump_model(
        num_iteration=(num_iteration if num_iteration > 0 else -1)))


def booster_feature_importance(bst: Booster, num_iteration: int,
                               importance_type: int) -> bytes:
    kind = "gain" if importance_type == 1 else "split"
    imp = bst.feature_importance(importance_type=kind,
                                 iteration=(num_iteration
                                            if num_iteration > 0 else None))
    return np.asarray(imp, np.float64).tobytes()


def network_init_with_functions(num_machines: int, rank: int,
                                reduce_scatter_ptr: int,
                                allgather_ptr: int) -> None:
    """LGBM_NetworkInitWithFunctions (c_api.h:958): register caller-
    provided collective function pointers as the host-side transport."""
    from .parallel import network
    network.init_with_functions(num_machines, rank,
                                reduce_scatter_ptr, allgather_ptr)


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    from .parallel import network
    network.init(machines=machines, local_listen_port=local_listen_port,
                 listen_time_out=listen_time_out, num_machines=num_machines)


def network_free() -> None:
    from .parallel import network
    network.free()


def booster_reset_parameter(bst: Booster, params: str) -> None:
    """LGBM_BoosterResetParameter: re-apply run-time tunable parameters
    (c_api.h:458; routed through Booster.reset_parameter)."""
    bst.reset_parameter(parse_params(params))


def booster_num_feature(bst: Booster) -> int:
    return int(bst.num_feature())


def booster_get_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int) -> float:
    """LGBM_BoosterGetLeafValue (gbdt.h GetLeafValue analog)."""
    return float(bst.get_leaf_output(tree_idx, leaf_idx))


def dataset_feature_names(ds) -> list:
    b = _as_dataset(ds).construct()._binned
    return list(b.feature_names)


# ---------------------------------------------------------------- streaming
class PendingDataset:
    """Push-rows construction state (LGBM_DatasetCreateByReference /
    CreateFromSampledColumn + PushRows*, c_api.h:58-233): rows accumulate
    into a preallocated host matrix; the first consumer (BoosterCreate,
    GetSubset, SaveBinary, ...) finalizes it into a real Dataset, binned
    against the reference's mappers when one was given. The reference bins
    rows as they arrive (Dataset::PushRow); binning once at finish keeps
    the same observable contract — FinishLoad fires when
    start_row + nrow == num_total_row — at the cost of holding the raw
    block, which is the price of reusing the vectorized binning path."""

    def __init__(self, num_total_row: int, ncol: int,
                 reference: Optional[Dataset], params: str):
        self.raw = np.zeros((num_total_row, ncol), np.float64)
        self.pushed = np.zeros(num_total_row, bool)
        self.reference = reference
        self.params = params
        self.finished = False
        self.pending_fields: Dict[str, Optional[np.ndarray]] = {}

    def push(self, rows: np.ndarray, start_row: int) -> None:
        if self.finished:
            raise LightGBMError("dataset already finished loading")
        end = start_row + rows.shape[0]
        if end > self.raw.shape[0]:
            raise LightGBMError(
                "push exceeds num_total_row (%d > %d)"
                % (end, self.raw.shape[0]))
        self.raw[start_row:end] = rows
        self.pushed[start_row:end] = True
        if end == self.raw.shape[0]:
            self.finished = True

    def finalize(self) -> Dataset:
        if not self.pushed.all():
            raise LightGBMError(
                "dataset used before all rows were pushed (%d of %d)"
                % (int(self.pushed.sum()), len(self.pushed)))
        ds = Dataset(self.raw, reference=self.reference,
                     params=parse_params(self.params), free_raw_data=False)
        for name, arr in self.pending_fields.items():
            ds.set_field(name, arr)
        return ds


def _as_dataset(obj):
    """Every ABI entry point that consumes a DatasetHandle routes through
    here so a PendingDataset transparently finalizes on first use (the C
    handle keeps pointing at the same PyObject; the finalized Dataset is
    cached on it)."""
    if isinstance(obj, PendingDataset):
        if not hasattr(obj, "_final"):
            obj._final = obj.finalize()
            obj.raw = None            # release the raw block
        return obj._final
    return obj


def dataset_create_by_reference(reference, num_total_row: int):
    ref = _as_dataset(reference)
    ncol = int(ref.num_feature())
    return PendingDataset(int(num_total_row), ncol, ref, "")


def dataset_create_from_sampled_column(col_mvs: List[Optional[memoryview]],
                                       idx_mvs: List[Optional[memoryview]],
                                       num_per_col: List[int],
                                       num_sample_row: int,
                                       num_total_row: int, params: str):
    """Bin mappers come from the sampled values (DatasetLoader::
    CostructFromSampleData, c_api.h:66-73); rows arrive later via
    PushRows. The sample reconstitutes as a dense matrix (absent entries
    are zero, matching the reference's sparse sample semantics)."""
    ncol = len(col_mvs)
    sample = np.zeros((num_sample_row, ncol), np.float64)
    for j in range(ncol):
        cnt = num_per_col[j]
        if cnt == 0 or col_mvs[j] is None:
            continue
        vals = np.frombuffer(col_mvs[j], dtype=np.float64, count=cnt)
        rows = np.frombuffer(idx_mvs[j], dtype=np.int32, count=cnt)
        sample[rows, j] = vals
    ref = Dataset(sample, params=parse_params(params), free_raw_data=False)
    ref.construct()
    return PendingDataset(int(num_total_row), ncol, ref, params)


def dataset_push_rows(pd, mv: memoryview, dtype_code: int, nrow: int,
                      ncol: int, start_row: int) -> None:
    if not isinstance(pd, PendingDataset):
        raise LightGBMError("LGBM_DatasetPushRows needs a dataset created "
                            "by CreateByReference/CreateFromSampledColumn "
                            "that has not been used yet")
    rows = _mat(mv, dtype_code, nrow, ncol, 1).astype(np.float64, copy=True)
    pd.push(rows, int(start_row))


def dataset_push_rows_by_csr(pd, indptr_mv, indptr_code, indices_mv,
                             data_mv, data_code, nindptr: int, nelem: int,
                             num_col: int, start_row: int) -> None:
    if not isinstance(pd, PendingDataset):
        raise LightGBMError("LGBM_DatasetPushRowsByCSR needs a dataset "
                            "created by CreateByReference/"
                            "CreateFromSampledColumn not yet used")
    indptr, indices, vals = _csr_parts(
        indptr_mv, indptr_code, indices_mv, data_mv, data_code, nindptr,
        nelem)
    from scipy.sparse import csr_matrix
    dense = csr_matrix((vals, indices, indptr),
                       shape=(nindptr - 1, num_col)).toarray() \
        .astype(np.float64)
    pd.push(dense, int(start_row))


def dataset_from_csc(colptr_mv, colptr_code, indices_mv, data_mv,
                     data_code, ncol_ptr: int, nelem: int, num_row: int,
                     params: str, reference) -> Dataset:
    from scipy.sparse import csc_matrix
    colptr = np.frombuffer(colptr_mv, dtype=_DTYPES[colptr_code],
                           count=ncol_ptr).copy()
    if nelem:
        indices = np.frombuffer(indices_mv, dtype=np.int32,
                                count=nelem).copy()
        vals = np.frombuffer(data_mv, dtype=_DTYPES[data_code],
                             count=nelem).copy()
    else:
        indices = np.zeros(0, np.int32)
        vals = np.zeros(0, np.float64)
    mat = csc_matrix((vals, indices, colptr),
                     shape=(num_row, ncol_ptr - 1)).tocsr()
    return Dataset(mat, reference=_as_dataset(reference) if reference
                   else None, params=parse_params(params),
                   free_raw_data=False)


def dataset_from_mats(mvs: List[memoryview], dtype_code: int,
                      nrows: List[int], ncol: int, row_major: int,
                      params: str, reference) -> Dataset:
    parts = [_mat(mv, dtype_code, nr, ncol, row_major)
             for mv, nr in zip(mvs, nrows)]
    data = np.concatenate(parts, axis=0).astype(np.float64, copy=True)
    return Dataset(data, reference=_as_dataset(reference) if reference
                   else None, params=parse_params(params),
                   free_raw_data=False)


# ------------------------------------------------------------- dataset info
_FIELD_OUT_DTYPES = {"label": (np.float32, 0), "weight": (np.float32, 0),
                     "init_score": (np.float64, 1), "group": (np.int32, 2),
                     "query": (np.int32, 2)}


def dataset_get_field(ds, name: str):
    """-> (dtype_code, ndarray or None). The array is stashed on the
    dataset so the C caller's pointer stays valid for the handle's
    lifetime (the reference returns pointers into Metadata storage,
    c_api.h:335-339). group comes back as CUMULATIVE query boundaries
    (nq + 1 entries), matching Metadata::query_boundaries()."""
    ds = _as_dataset(ds)
    dt, code = _FIELD_OUT_DTYPES[name] if name in _FIELD_OUT_DTYPES \
        else (np.float32, 0)
    if name in ("group", "query"):
        m = ds.construct()._binned.metadata
        arr = m.query_boundaries
    else:
        arr = ds.get_field(name)
    if arr is None:
        return code, None
    arr = np.ascontiguousarray(np.asarray(arr), dtype=dt)
    if not hasattr(ds, "_capi_field_cache"):
        ds._capi_field_cache = {}
    pinned = ds._capi_field_cache.setdefault(name, [])
    # Every pointer ever handed to C stays valid until the handle is
    # freed (the header's lifetime contract, c_api.h:335-339), so pinned
    # arrays are never dropped — but a caller polling an unchanged field
    # gets the same pinned array back instead of growing the pin list.
    if pinned:
        cached = pinned[-1]
        if cached.shape == arr.shape and cached.dtype == arr.dtype \
                and np.array_equal(cached, arr, equal_nan=True):
            return code, cached
    pinned.append(arr)
    return code, arr


def dataset_save_binary(ds, filename: str) -> None:
    _as_dataset(ds).save_binary(filename)


def dataset_get_subset(ds, idx_mv: memoryview, num_used: int,
                       params: str) -> Dataset:
    idx = np.frombuffer(idx_mv, dtype=np.int32, count=num_used).copy()
    sub = _as_dataset(ds).subset(idx, params=parse_params(params))
    sub.construct()
    return sub


# Parameters baked into the binned representation at construction time;
# Dataset::ResetConfig refuses to change them on a live handle
# (dataset.cpp:327-348). We reject rather than warn so C callers can't
# silently train with a stale max_bin.
_BIN_AFFECTING = frozenset([
    "max_bin", "bin_construct_sample_cnt", "min_data_in_bin",
    "use_missing", "zero_as_missing", "sparse_threshold",
])


def dataset_update_param(ds, params: str) -> None:
    p = parse_params(params)
    ds = _as_dataset(ds)
    if ds.params is None:
        ds.params = {}
    if ds._binned is not None:
        from .config import _CANON, Config, _coerce
        from .log import Log
        # authoritative: the effective values recorded when the binned
        # representation was built (survives .bin round-trips and subsets)
        effective = getattr(ds._binned, "bin_params", {}) or {}
        for k, v in p.items():
            ck = Config.resolve_key(k)
            if ck not in _BIN_AFFECTING:
                continue
            cur = effective.get(ck)
            if cur is None and ck == "max_bin":
                cur = ds._binned.max_bin
            if cur is None:
                # pre-bin_params .bin file: can't verify — warn like the
                # reference's ResetConfig and accept
                Log.warning("Cannot verify %s against the constructed "
                            "Dataset; accepting unchecked." % ck)
                continue
            ty = _CANON.get(ck, (str, None))[0]
            if _coerce(ck, ty, cur) != _coerce(ck, ty, v):
                raise LightGBMError(
                    "Cannot change %s after constructed Dataset handle." % ck)
    ds.params.update(p)


def dataset_dump_text(ds, filename: str) -> None:
    """Dataset::DumpTextFile analog (c_api.h:306): feature names, per-
    feature bin boundaries, then the binned row matrix."""
    b = _as_dataset(ds).construct()._binned
    with open(filename, "w") as f:
        f.write("num_data: %d\n" % b.num_data)
        f.write("num_feature: %d\n" % b.num_features)
        f.write("feature_names: %s\n" % ",".join(b.feature_names))
        for info in b.get_feature_infos():
            f.write("feature_info: %s\n" % info)
        xb = np.asarray(b.X_binned)
        for i in range(b.num_data):
            f.write(" ".join(str(int(v)) for v in xb[i]) + "\n")


def dataset_add_features_from(target, source) -> None:
    """LGBM_DatasetAddFeaturesFrom (c_api.h:373): append source's feature
    columns to target. Both raw blocks must still be held (the ABI always
    constructs with free_raw_data=False); the merged dataset re-bins, which
    reproduces the reference's merged FeatureGroup layout."""
    t = _as_dataset(target)
    s = _as_dataset(source)
    if t.num_data() != s.num_data():
        raise LightGBMError("cannot add features: row counts differ "
                            "(%d vs %d)" % (t.num_data(), s.num_data()))
    if t.data is None or s.data is None:
        raise LightGBMError("cannot add features: raw data was freed")
    td = _to_2d(t.data)
    sd = _to_2d(s.data)
    t.data = np.concatenate([td, sd], axis=1)
    if t.feature_name and s.feature_name:
        t.feature_name = list(t.feature_name) + list(s.feature_name)
    else:
        t.feature_name = None
    t._binned = None          # force re-construct with the merged block
    t.construct()


def _to_2d(data) -> np.ndarray:
    from .basic import _to_2d_float
    return _to_2d_float(data)


# ------------------------------------------------------------- booster info
def booster_get_feature_names(bst: Booster) -> List[str]:
    return list(bst.feature_name())


def booster_calc_num_predict(bst: Booster, num_row: int, predict_type: int,
                             num_iteration: int) -> int:
    """LGBM_BoosterCalcNumPredict (c_api.cpp:771-789)."""
    impl = bst._impl
    k = max(impl.num_tree_per_iteration, 1)
    total_iter = impl.iter_ + getattr(impl, "num_init_iteration", 0)
    ni = total_iter if num_iteration <= 0 else min(num_iteration, total_iter)
    if predict_type == 2:      # leaf index
        return int(num_row) * k * ni
    if predict_type == 3:      # SHAP contributions
        return int(num_row) * max(impl.num_class, 1) \
            * (int(bst.num_feature()) + 1)
    return int(num_row) * max(impl.num_class, 1)


def booster_get_num_predict(bst: Booster, data_idx: int) -> int:
    impl = bst._impl
    if data_idx == 0:
        n = impl.num_data_orig
    else:
        if data_idx - 1 >= len(impl.valid_data):
            raise LightGBMError("data_idx %d out of range" % data_idx)
        n = impl.valid_data[data_idx - 1].num_data
    return n * max(impl.num_class, 1)


def booster_get_predict(bst: Booster, data_idx: int) -> bytes:
    """LGBM_BoosterGetPredict: objective-converted scores for the train
    (0) or a valid (1..) set, CLASS-MAJOR like GBDT::GetPredictAt
    (gbdt.cpp:585-620: out[j * num_data + i])."""
    impl = bst._impl
    if data_idx == 0:
        scores = np.asarray(impl.scores)[: impl.num_data_orig]    # [n, k]
    else:
        if data_idx - 1 >= len(impl.valid_data):
            raise LightGBMError("data_idx %d out of range" % data_idx)
        impl._materialize()
        scores = np.asarray(impl._valid_pred_cache[data_idx - 1]["scores"])
    if impl.objective is not None:
        out = np.asarray(impl.objective.convert_output(scores), np.float64)
    else:
        out = scores.astype(np.float64)
    return out.T.reshape(-1).tobytes()                # class-major


def booster_refit_with_leaves(bst: Booster, mv: memoryview, nrow: int,
                              ncol: int) -> None:
    """LGBM_BoosterRefit (c_api.h:484) -> GBDT::RefitTree
    (gbdt.cpp:263-286): keep every tree's structure, re-estimate leaf
    outputs from the TRAIN data's gradients at the running scores, with
    leaf assignments supplied by the caller ([nrow, num_models] int32 —
    what PredictForMat with predict_type=leaf returns)."""
    leaf_preds = np.frombuffer(mv, dtype=np.int32,
                               count=nrow * ncol).reshape(nrow, ncol).copy()
    impl = bst._impl
    impl._materialize()
    models = impl.models
    if len(models) != ncol:
        raise LightGBMError("leaf_preds has %d columns but the model has "
                            "%d trees" % (ncol, len(models)))
    if impl.num_data_orig != nrow:
        raise LightGBMError("leaf_preds row count %d != train rows %d"
                            % (nrow, impl.num_data_orig))
    if impl.objective is None:
        raise LightGBMError("cannot refit without an objective")
    cfg = impl.config
    k = max(impl.num_tree_per_iteration, 1)
    n = impl.num_data_orig
    decay = float(getattr(cfg, "refit_decay_rate", 0.9))
    l1, l2, mds = cfg.lambda_l1, cfg.lambda_l2, cfg.max_delta_step
    import jax.numpy as jnp
    scores = np.zeros((n, k), np.float32)
    if getattr(impl, "init_score_offsets", None) is not None:
        scores += np.asarray(impl.init_score_offsets, np.float32)[None, :]
    g = h = None
    for i, ht in enumerate(models):
        c = i % k
        if c == 0:
            if k == 1:
                gj, hj = impl.objective.get_gradients(
                    jnp.asarray(scores[:, 0]))
                g, h = np.asarray(gj)[:, None], np.asarray(hj)[:, None]
            else:
                gj, hj = impl.objective.get_gradients(jnp.asarray(scores))
                g, h = np.asarray(gj), np.asarray(hj)
        nl = ht.num_leaves
        leaves = leaf_preds[:, i]
        if leaves.max(initial=0) >= nl:
            raise LightGBMError("leaf index out of range in tree %d" % i)
        sg = np.bincount(leaves, weights=g[:n, c].astype(np.float64),
                         minlength=nl)
        sh = np.bincount(leaves, weights=h[:n, c].astype(np.float64),
                         minlength=nl) + 1e-15
        out = -np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0) / (sh + l2)
        if mds > 0:
            out = np.clip(out, -mds, mds)
        out *= getattr(ht, "shrinkage", 1.0)
        old = ht.leaf_value[:nl].astype(np.float64)
        ht.leaf_value[:nl] = decay * old + (1.0 - decay) * out
        scores[:, c] += ht.leaf_value[leaves].astype(np.float32)
    impl.models = models      # invalidate materialized prediction tables


def booster_reset_training_data(bst: Booster, new_train) -> None:
    bst.reset_training_data(_as_dataset(new_train))


def booster_set_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    """LGBM_BoosterSetLeafValue -> Tree::SetLeafOutput (c_api.h:921)."""
    impl = bst._impl
    impl._materialize()
    models = impl.models
    if not (0 <= tree_idx < len(models)):
        raise LightGBMError("tree_idx %d out of range" % tree_idx)
    ht = models[tree_idx]
    if not (0 <= leaf_idx < ht.num_leaves):
        raise LightGBMError("leaf_idx %d out of range" % leaf_idx)
    ht.leaf_value[leaf_idx] = float(val)
    impl.models = models      # refresh prediction tables


def booster_shuffle_models(bst: Booster, start_iter: int,
                           end_iter: int) -> None:
    """LGBM_BoosterShuffleModels (c_api.h:423) — random within-range
    permutation of whole iterations (used before Refit)."""
    impl = bst._impl
    impl._materialize()
    models = list(impl.models)
    k = max(impl.num_tree_per_iteration, 1)
    n_iter = len(models) // k
    lo = max(0, start_iter)
    hi = n_iter if end_iter <= 0 else min(end_iter, n_iter)
    # deterministic but distinct across successive calls: fold a
    # per-booster shuffle counter into the seed
    n_shuffles = getattr(impl, "_n_model_shuffles", 0)
    impl._n_model_shuffles = n_shuffles + 1
    perm = np.random.RandomState(
        (impl.config.seed + n_shuffles) % (2 ** 31)).permutation(
        np.arange(lo, hi))
    shuffled = list(models)
    for dst_it, src_it in zip(range(lo, hi), perm):
        for c in range(k):
            shuffled[dst_it * k + c] = models[src_it * k + c]
    impl.models = shuffled


def booster_predict_for_file(bst: Booster, data_filename: str,
                             data_has_header: int, predict_type: int,
                             num_iteration: int, parameter: str,
                             result_filename: str) -> None:
    """LGBM_BoosterPredictForFile (c_api.h:615) — parse, predict, write
    one line per row (tab-separated for multi-output), the reference
    Predictor::SaveTextAsResult contract."""
    from .io.parser import parse_file
    X, _, _names = parse_file(data_filename,
                              has_header=bool(data_has_header))
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    out = np.asarray(bst.predict(np.asarray(X, np.float64), **kw))
    with open(result_filename, "w") as f:
        if out.ndim == 1:
            for v in out:
                f.write("%.17g\n" % float(v))
        else:
            for row in out:
                f.write("\t".join("%.17g" % float(v) for v in row) + "\n")


def booster_predict_csc(bst: Booster, colptr_mv, colptr_code, indices_mv,
                        data_mv, data_code, ncol_ptr: int, nelem: int,
                        num_row: int, predict_type: int, num_iteration: int,
                        parameter: str) -> bytes:
    from scipy.sparse import csc_matrix
    colptr = np.frombuffer(colptr_mv, dtype=_DTYPES[colptr_code],
                           count=ncol_ptr).copy()
    indices = np.frombuffer(indices_mv, dtype=np.int32, count=nelem).copy() \
        if nelem else np.zeros(0, np.int32)
    vals = np.frombuffer(data_mv, dtype=_DTYPES[data_code],
                         count=nelem).copy() if nelem \
        else np.zeros(0, np.float64)
    mat = csc_matrix((vals, indices, colptr),
                     shape=(num_row, ncol_ptr - 1)).tocsr()
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    return np.asarray(bst.predict(mat, **kw), np.float64).tobytes()
