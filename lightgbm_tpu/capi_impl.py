"""Python side of the C ABI (native/src/c_api.cpp).

The embedded interpreter calls these flat functions with primitive
arguments (memoryviews over caller-owned buffers, strings, ints) and gets
primitives/bytes back, keeping the C++ shim free of object-protocol
details. The reference implements the same surface natively
(src/c_api.cpp:46-363 Booster wrapper + the LGBM_* bodies); here the
runtime IS the Python package, so the ABI marshals into it.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

# honor the host's JAX_PLATFORMS choice BEFORE any backend init: site
# hooks may overwrite the env var, but jax.config wins over both
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from .basic import Booster, Dataset
from .log import LightGBMError

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def parse_params(s: Optional[str]) -> Dict[str, str]:
    """"k1=v1 k2=v2" -> dict (Config::KV2Map semantics, config.cpp)."""
    out: Dict[str, str] = {}
    for tok in (s or "").replace("\t", " ").split(" "):
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k.strip()] = v.strip()
        else:
            out[tok] = "true"
    return out


def _mat(mv: memoryview, dtype_code: int, nrow: int, ncol: int,
         row_major: int) -> np.ndarray:
    dt = _DTYPES[dtype_code]
    arr = np.frombuffer(mv, dtype=dt, count=nrow * ncol)
    if row_major:
        return arr.reshape(nrow, ncol)
    return arr.reshape(ncol, nrow).T


def dataset_from_file(filename: str, params: str,
                      reference: Optional[Dataset]) -> Dataset:
    p = parse_params(params)
    label_kw = {}
    ds = Dataset(filename, reference=reference, params=p,
                 free_raw_data=False, **label_kw)
    ds.construct()
    return ds


def dataset_from_mat(mv: memoryview, dtype_code: int, nrow: int, ncol: int,
                     row_major: int, params: str,
                     reference: Optional[Dataset]) -> Dataset:
    # the C contract lets the host free its buffer as soon as the call
    # returns; copy=True guards against astype's no-op fast path handing
    # back a view of caller memory
    data = _mat(mv, dtype_code, nrow, ncol, row_major) \
        .astype(np.float64, copy=True)
    ds = Dataset(data, reference=reference, params=parse_params(params),
                 free_raw_data=False)
    return ds


def _csr_parts(indptr_mv, indptr_code, indices_mv, data_mv, data_code,
               nindptr, nelem):
    """Copy CSR pieces out of caller-owned memory (the host may free its
    buffers on return); nelem == 0 (all-zero rows) is a valid matrix."""
    indptr = np.frombuffer(indptr_mv, dtype=_DTYPES[indptr_code],
                           count=nindptr).copy()
    if nelem == 0:
        return indptr, np.zeros(0, np.int32), np.zeros(0, np.float64)
    indices = np.frombuffer(indices_mv, dtype=np.int32, count=nelem).copy()
    vals = np.frombuffer(data_mv, dtype=_DTYPES[data_code],
                         count=nelem).copy()
    return indptr, indices, vals


def dataset_from_csr(indptr_mv: memoryview, indptr_code: int,
                     indices_mv: memoryview, data_mv: memoryview,
                     data_code: int, nindptr: int, nelem: int,
                     num_col: int, params: str,
                     reference: Optional[Dataset]) -> Dataset:
    from scipy.sparse import csr_matrix
    indptr, indices, vals = _csr_parts(
        indptr_mv, indptr_code, indices_mv, data_mv, data_code, nindptr,
        nelem)
    mat = csr_matrix((vals, indices, indptr),
                     shape=(nindptr - 1, num_col))
    return Dataset(mat, reference=reference, params=parse_params(params),
                   free_raw_data=False)


def dataset_set_field(ds: Dataset, name: str, mv: Optional[memoryview],
                      num_element: int, dtype_code: int) -> None:
    if mv is None or num_element == 0:
        ds.set_field(name, None)
        return
    arr = np.frombuffer(mv, dtype=_DTYPES[dtype_code], count=num_element)
    ds.set_field(name, np.array(arr))


def dataset_num_data(ds: Dataset) -> int:
    return int(ds.construct().num_data())


def dataset_num_feature(ds: Dataset) -> int:
    return int(ds.construct().num_feature())


def dataset_set_feature_names(ds: Dataset, names: List[str]) -> None:
    ds.feature_name = list(names)


def booster_create(train: Dataset, params: str) -> Booster:
    return Booster(params=parse_params(params), train_set=train)


def booster_from_file(filename: str) -> Tuple[Booster, int]:
    bst = Booster(model_file=filename)
    return bst, bst.current_iteration


def booster_from_string(model_str: str) -> Tuple[Booster, int]:
    bst = Booster(model_str=model_str)
    return bst, bst.current_iteration


def booster_add_valid(bst: Booster, valid: Dataset) -> None:
    bst.add_valid(valid, "valid_%d" % (len(bst._valid_sets) + 1))


def booster_update(bst: Booster) -> int:
    return int(bool(bst.update()))


def booster_update_custom(bst: Booster, grad_mv: memoryview,
                          hess_mv: memoryview, n: int) -> int:
    grad = np.frombuffer(grad_mv, dtype=np.float32, count=n)
    hess = np.frombuffer(hess_mv, dtype=np.float32, count=n)
    return int(bool(bst._impl.train_one_iter(np.array(grad),
                                             np.array(hess))))


def booster_num_classes(bst: Booster) -> int:
    return int(bst._impl.num_class)


def booster_num_train_rows_times_classes(bst: Booster) -> int:
    impl = bst._impl
    return int(impl.num_data * impl.num_tree_per_iteration)


def booster_rollback(bst: Booster) -> None:
    bst.rollback_one_iter()


def booster_current_iteration(bst: Booster) -> int:
    return int(bst.current_iteration)


def booster_num_model_per_iteration(bst: Booster) -> int:
    return int(bst.num_model_per_iteration())


def booster_num_total_model(bst: Booster) -> int:
    return int(bst.num_trees())


def booster_merge(dst: Booster, src: Booster) -> None:
    """GBDT::MergeFrom (gbdt.h:53-64): src's trees go FIRST (deep copies),
    dst's own trees follow; num_init_iteration tracks the prefix."""
    import copy as _copy
    k = max(dst._impl.num_tree_per_iteration, 1)
    if max(src._impl.num_tree_per_iteration, 1) != k:
        raise LightGBMError("cannot merge boosters with different "
                            "trees-per-iteration")
    merged = _copy.deepcopy(src._impl.models) + list(dst._impl.models)
    dst._impl.models = merged
    dst._impl.num_init_iteration = len(src._impl.models) // k
    dst._impl.iter_ = len(merged) // k


def booster_eval(bst: Booster, data_idx: int) -> bytes:
    if data_idx == 0:
        res = bst.eval_train()
    else:
        res = [r for r in bst.eval_valid()
               if r[0] == ("valid_%d" % data_idx)]
    return np.asarray([v for _, _, v, _ in res], np.float64).tobytes()


def booster_eval_names(bst: Booster) -> List[str]:
    names = []
    for m in bst._impl.train_metrics:
        names.extend(m.names)
    return names


def _predict_kwargs(predict_type: int, num_iteration: int,
                    parameter: str) -> Dict:
    """One predict-kwargs builder for every prediction entry point, so
    the mat/CSR paths cannot drift."""
    kw = dict(num_iteration=(num_iteration if num_iteration > 0 else None))
    if predict_type == 1:
        kw["raw_score"] = True
    elif predict_type == 2:
        kw["pred_leaf"] = True
    elif predict_type == 3:
        kw["pred_contrib"] = True
    p = parse_params(parameter)
    if "pred_early_stop" in p:
        kw["pred_early_stop"] = p["pred_early_stop"] in ("true", "1")
    return kw


def booster_predict_csr(bst: Booster, indptr_mv: memoryview,
                        indptr_code: int, indices_mv: memoryview,
                        data_mv: memoryview, data_code: int, nindptr: int,
                        nelem: int, num_col: int, predict_type: int,
                        num_iteration: int, parameter: str) -> bytes:
    from scipy.sparse import csr_matrix
    indptr, indices, vals = _csr_parts(
        indptr_mv, indptr_code, indices_mv, data_mv, data_code, nindptr,
        nelem)
    mat = csr_matrix((vals, indices, indptr), shape=(nindptr - 1, num_col))
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    # densify in row blocks so a large sparse batch never materializes as
    # one dense matrix (the reference streams CSR rows)
    block = max(1, 1 << 24 >> max(num_col, 1).bit_length())
    outs = []
    for lo in range(0, mat.shape[0], block):
        dense = mat[lo:lo + block].toarray().astype(np.float64, copy=False)
        outs.append(np.asarray(bst.predict(dense, **kw), np.float64))
    if not outs:
        return b""
    return np.concatenate(outs).tobytes()


def booster_predict_mat(bst: Booster, mv: memoryview, dtype_code: int,
                        nrow: int, ncol: int, row_major: int,
                        predict_type: int, num_iteration: int,
                        parameter: str) -> bytes:
    data = _mat(mv, dtype_code, nrow, ncol, row_major)
    kw = _predict_kwargs(predict_type, num_iteration, parameter)
    out = np.asarray(bst.predict(np.ascontiguousarray(data, np.float64),
                                 **kw), np.float64)
    return out.tobytes()


def booster_save_model(bst: Booster, start_iteration: int,
                       num_iteration: int, filename: str) -> None:
    # C ABI: num_iteration <= 0 means "all" (not best_iteration)
    bst.save_model(filename,
                   num_iteration=(num_iteration if num_iteration > 0
                                  else -1),
                   start_iteration=max(start_iteration, 0))


def booster_model_to_string(bst: Booster, start_iteration: int,
                            num_iteration: int) -> str:
    return bst.model_to_string(
        num_iteration=(num_iteration if num_iteration > 0 else -1),
        start_iteration=max(start_iteration, 0))


def booster_dump_model(bst: Booster, start_iteration: int,
                       num_iteration: int) -> str:
    import json
    return json.dumps(bst.dump_model(
        num_iteration=(num_iteration if num_iteration > 0 else -1)))


def booster_feature_importance(bst: Booster, num_iteration: int,
                               importance_type: int) -> bytes:
    kind = "gain" if importance_type == 1 else "split"
    imp = bst.feature_importance(importance_type=kind,
                                 iteration=(num_iteration
                                            if num_iteration > 0 else None))
    return np.asarray(imp, np.float64).tobytes()


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    from .parallel import network
    network.init(machines=machines, local_listen_port=local_listen_port,
                 listen_time_out=listen_time_out, num_machines=num_machines)


def network_free() -> None:
    from .parallel import network
    network.free()


def booster_reset_parameter(bst: Booster, params: str) -> None:
    """LGBM_BoosterResetParameter: re-apply run-time tunable parameters
    (c_api.h:458; routed through Booster.reset_parameter)."""
    bst.reset_parameter(parse_params(params))


def booster_num_feature(bst: Booster) -> int:
    return int(bst.num_feature())


def booster_get_leaf_value(bst: Booster, tree_idx: int, leaf_idx: int) -> float:
    """LGBM_BoosterGetLeafValue (gbdt.h GetLeafValue analog)."""
    return float(bst.get_leaf_output(tree_idx, leaf_idx))


def dataset_feature_names(ds: Dataset) -> list:
    b = ds.construct()._binned
    return list(b.feature_names)
