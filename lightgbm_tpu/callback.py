"""Training callbacks (reference: python-package/lightgbm/callback.py).

Same contract: callbacks receive a ``CallbackEnv`` namedtuple before/after
each iteration; ``EarlyStopException`` unwinds the training loop
(callback.py:16-31, 55-153).
"""
from __future__ import annotations

import collections
from operator import gt, lt
from typing import Any, Callable, Dict, List

from .log import Log


class EarlyStopException(Exception):
    """Signals the train loop to stop (callback.py:16)."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv: bool = True) -> str:
    """callback.py:34-46."""
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s: %g + %g" % (value[0], value[1], value[2], value[4])
        return "%s's %s: %g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """callback.py:49-72."""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv)
                for x in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """callback.py:75-105."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dictionary")
    eval_result.clear()

    def _init(env: CallbackEnv) -> None:
        for data_name, eval_name, _, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for data_name, eval_name, result, _ in env.evaluation_result_list:
            eval_result[data_name][eval_name].append(result)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """callback.py:108-146: per-iteration parameter schedules; values may be
    lists (indexed by iteration) or callables iteration -> value."""
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if key in ("num_class", "num_classes", "boosting", "boost",
                       "boosting_type", "metric", "metrics", "metric_types"):
                raise RuntimeError("Cannot reset %s during training" % repr(key))
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        "Length of list %r has to equal to 'num_boost_round'."
                        % key)
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """callback.py:149-236."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List[Any] = []
    cmp_op: List[Callable] = []
    enabled = [True]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric is "
                "required for evaluation")
        if verbose:
            Log.info("Training until validation scores don't improve for %d "
                     "rounds.", stopping_rounds)
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            if eval_ret[3]:  # bigger is better
                best_score.append(float("-inf"))
                cmp_op.append(gt)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lt)

    def _callback(env: CallbackEnv) -> None:
        if not best_score:
            _init(env)
        if not enabled[0]:
            return
        for i, eval_ret in enumerate(env.evaluation_result_list):
            score = eval_ret[2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            # train metric doesn't trigger early stop (callback.py:206-209)
            if eval_ret[0] == "training" or eval_ret[0] == env.model.train_set_name:
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is:\n[%d]\t%s",
                             best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x)
                                 for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    Log.info("Did not meet early stopping. Best iteration is:"
                             "\n[%d]\t%s", best_iter[i] + 1, "\t".join(
                                 _format_eval_result(x)
                                 for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    return _callback
