"""Training callbacks.

Mirrors the reference callback *contract* (python-package/lightgbm/callback.py):
factories return callables that receive a ``CallbackEnv`` before/after each
iteration; an ``order`` attribute sequences them; ``before_iteration`` selects
the phase; ``EarlyStopException`` unwinds the train loop. The implementations
here are small stateful classes rather than closure triples — state is explicit
and picklable, and each callback's behavior is testable in isolation.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional

from .log import Log

# Parameters that would change the model topology mid-training; resetting
# them is rejected (the reference enforces the same set).
_IMMUTABLE_DURING_TRAIN = frozenset({
    "num_class", "num_classes", "boosting", "boost", "boosting_type",
    "metric", "metrics", "metric_types"})


class EarlyStopException(Exception):
    """Thrown by early_stopping to unwind the boosting loop."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _eval_text(entry, show_stdv: bool = True) -> str:
    """Render one evaluation tuple: 4-tuple = plain eval, 5-tuple = cv
    aggregate with stdv."""
    data_name, metric_name, value = entry[0], entry[1], entry[2]
    text = "%s's %s: %g" % (data_name, metric_name, value)
    if len(entry) == 5 and show_stdv:
        text += " + %g" % entry[4]
    elif len(entry) not in (4, 5):
        raise ValueError("evaluation entry must have 4 or 5 fields, got %d"
                         % len(entry))
    return text


class _PrintEvaluation:
    before_iteration = False
    order = 10
    # no-op whenever the iteration produced no eval results — lets the
    # engine fuse iteration blocks on device when nothing is evaluated
    only_consumes_evals = True

    def __init__(self, period: int, show_stdv: bool):
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        it = env.iteration + 1
        if it % self.period == 0:
            Log.info("[%d]\t%s", it, "\t".join(
                _eval_text(e, self.show_stdv)
                for e in env.evaluation_result_list))


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Log evaluation results every ``period`` iterations."""
    return _PrintEvaluation(period, show_stdv)


class _RecordEvaluation:
    before_iteration = False
    order = 20
    only_consumes_evals = True

    def __init__(self, store: Dict[str, Dict[str, List[float]]]):
        self.store = store

    def __call__(self, env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list:
            data_name, metric_name, value = entry[0], entry[1], entry[2]
            per_data = self.store.setdefault(data_name,
                                             collections.OrderedDict())
            per_data.setdefault(metric_name, []).append(value)


def record_evaluation(eval_result: Dict[str, Dict[str, List[float]]]) -> Callable:
    """Append each iteration's eval values into ``eval_result`` in place."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result must be a dict, got %s"
                        % type(eval_result).__name__)
    eval_result.clear()
    return _RecordEvaluation(eval_result)


class _ExportEvalMetrics:
    """Publish each iteration's eval tuples as ``lgbm_eval_metric``
    gauges — the registry series train-time scrapers (StatsServer
    ``/metrics``, the PR 9 cluster federation) watch for loss curves.
    ``only_consumes_evals`` keeps the engine free to fuse iteration
    blocks on device when nothing is evaluated."""

    before_iteration = False
    order = 15
    only_consumes_evals = True

    def __init__(self, registry=None):
        self._reg = registry
        self._gauges: Dict[tuple, Any] = {}

    def __call__(self, env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            return
        if self._reg is None:
            from .obs.registry import get_registry
            self._reg = get_registry()
        for entry in env.evaluation_result_list:
            data_name, metric_name, value = entry[0], entry[1], entry[2]
            g = self._gauges.get((data_name, metric_name))
            if g is None:
                g = self._reg.gauge(
                    "lgbm_eval_metric",
                    "Latest evaluation metric value, per dataset and "
                    "metric, updated every evaluated iteration.",
                    {"dataset": str(data_name), "metric": str(metric_name)})
                self._gauges[(data_name, metric_name)] = g
            g.set(float(value))


def export_eval_metrics(registry=None) -> Callable:
    """Stream eval results into the process metrics registry as
    ``lgbm_eval_metric{dataset=,metric=}`` gauges (attached automatically
    by ``engine.train``; pass explicitly to ``cv`` or custom loops)."""
    return _ExportEvalMetrics(registry)


class _ResetParameter:
    before_iteration = True
    order = 10

    def __init__(self, schedules: Dict[str, Any]):
        for key in schedules:
            if key in _IMMUTABLE_DURING_TRAIN:
                raise RuntimeError("Cannot reset %r during training" % key)
        self.schedules = schedules

    def _value_at(self, key: str, value, step: int, total: int):
        if callable(value):
            return value(step)
        if len(value) != total:
            raise ValueError(
                "schedule list for %r has %d entries; expected "
                "num_boost_round = %d" % (key, len(value), total))
        return value[step]

    def __call__(self, env: CallbackEnv) -> None:
        step = env.iteration - env.begin_iteration
        total = env.end_iteration - env.begin_iteration
        changed = {}
        for key, value in self.schedules.items():
            new = self._value_at(key, value, step, total)
            if env.params.get(key) != new:
                changed[key] = new
        if changed:
            env.model.reset_parameter(changed)
            env.params.update(changed)


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules: each kwarg is a list indexed by
    iteration or a callable ``iteration -> value`` (e.g. learning_rate
    decay)."""
    return _ResetParameter(kwargs)


class _EarlyStopping:
    before_iteration = False
    order = 30

    def __init__(self, stopping_rounds: int, first_metric_only: bool,
                 verbose: bool):
        self.stopping_rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.enabled: Optional[bool] = None   # decided on first call
        self.state: List[dict] = []           # one slot per eval entry

    def _start(self, env: CallbackEnv) -> None:
        self.enabled = all(
            env.params.get(a) != "dart"
            for a in ("boosting", "boosting_type", "boost"))
        if not self.enabled:
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("early stopping needs at least one validation "
                             "set with an eval metric")
        if self.verbose:
            Log.info("Training until validation scores don't improve for %d "
                     "rounds.", self.stopping_rounds)
        for entry in env.evaluation_result_list:
            bigger_better = entry[3]
            self.state.append({
                "best": float("-inf") if bigger_better else float("inf"),
                "better": (lambda a, b: a > b) if bigger_better
                          else (lambda a, b: a < b),
                "bigger_better": bool(bigger_better),
                "best_iter": 0,
                "best_entries": None,
            })

    # ------------------------------------------------ checkpoint support
    def get_state(self) -> Optional[List[dict]]:
        """JSON-safe snapshot of the per-metric slots (the ``better``
        comparators are rebuilt from ``bigger_better`` on restore)."""
        if not self.enabled:
            return None
        return [{"best": slot["best"],
                 "bigger_better": slot["bigger_better"],
                 "best_iter": slot["best_iter"],
                 "best_entries": ([list(e) for e in slot["best_entries"]]
                                  if slot["best_entries"] is not None
                                  else None)}
                for slot in self.state]

    def set_state(self, state: List[dict]) -> None:
        """Resume-path inverse of get_state; marks the callback started so
        ``_start`` does not re-append fresh slots."""
        self.enabled = True
        self.state = []
        for slot in state:
            bigger_better = bool(slot["bigger_better"])
            self.state.append({
                "best": float(slot["best"]),
                "better": (lambda a, b: a > b) if bigger_better
                          else (lambda a, b: a < b),
                "bigger_better": bigger_better,
                "best_iter": int(slot["best_iter"]),
                "best_entries": ([tuple(e) for e in slot["best_entries"]]
                                 if slot["best_entries"] is not None
                                 else None),
            })

    def _finish(self, slot: dict, reason: str) -> None:
        if self.verbose:
            Log.info("%s Best iteration is:\n[%d]\t%s", reason,
                     slot["best_iter"] + 1,
                     "\t".join(_eval_text(e) for e in slot["best_entries"]))
        raise EarlyStopException(slot["best_iter"], slot["best_entries"])

    def __call__(self, env: CallbackEnv) -> None:
        if self.enabled is None:
            self._start(env)
        if not self.enabled:
            return
        for i, entry in enumerate(env.evaluation_result_list):
            slot = self.state[i]
            value = entry[2]
            if slot["best_entries"] is None or slot["better"](value,
                                                              slot["best"]):
                slot.update(best=value, best_iter=env.iteration,
                            best_entries=env.evaluation_result_list)
            # the training set never triggers a stop — only validations do
            is_train = entry[0] in ("training",
                                    getattr(env.model, "train_set_name",
                                            "training"))
            if not is_train:
                if env.iteration - slot["best_iter"] >= self.stopping_rounds:
                    self._finish(slot, "Early stopping.")
                if env.iteration == env.end_iteration - 1:
                    self._finish(slot, "Did not meet early stopping.")
            if self.first_metric_only:
                break


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Stop when no validation metric improved for ``stopping_rounds``
    consecutive iterations; records the best iteration on the exception."""
    return _EarlyStopping(stopping_rounds, first_metric_only, verbose)


class _HealthMonitor:
    """Arms device-side health monitoring on the booster. Runs in the
    ``before_iteration`` slot so the FIRST call lands before the first
    compile — the health branch enters the initial program for free.  Its
    presence also disables engine-side block fusion (it is a
    before-callback), which is exactly what "flag within 1 iteration"
    requires."""

    before_iteration = True
    order = 5

    def __init__(self, action: str = "warn"):
        self.action = action
        self._armed = False

    def __call__(self, env) -> None:
        if self._armed:
            return
        impl = getattr(env.model, "_impl", env.model)
        impl.enable_health_monitor(self.action)
        self._armed = True


def health_monitor(action: str = "warn") -> Callable:
    """Watch training health (non-finite grad/hess, degenerate gains) via
    device-side flags fused into the training step (lightgbm_tpu.obs).
    ``action``: ``warn`` logs and counts; ``abort`` checkpoints into
    ``checkpoint_dir`` (when configured) then raises; ``raise`` raises
    immediately. See docs/Observability.md."""
    return _HealthMonitor(action)


def checkpoint(directory: str, period: int = 1, keep_last_n: int = 3,
               on_sigterm: bool = True) -> Callable:
    """Preemption-safe training snapshots (lightgbm_tpu.checkpoint): save
    the complete training state into ``directory`` every ``period``
    iterations and on SIGTERM; resume with
    ``engine.train(..., resume_from=directory)``. See docs/Checkpointing.md.
    """
    from .checkpoint.callback import _Checkpoint
    return _Checkpoint(directory, period=period, keep_last_n=keep_last_n,
                       on_sigterm=on_sigterm)
