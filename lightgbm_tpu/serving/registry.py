"""Model registry: model files -> immutable device-resident tree bundles.

The serving analog of the reference's prediction application layer
(src/application/predictor.hpp): a model is loaded ONCE, its trees are
packed to model-wide fixed shapes (core/tree.py pack_predict_table) and
stacked ``[iterations, num_tree_per_iteration, ...]`` on device, and every
request thereafter only reads the bundle. Bundles are immutable — capping
``num_iteration`` slices the stacked arrays (cheap device slice, cached),
never mutates them — so concurrent request threads need no locking past
the registry dict itself.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..log import LightGBMError, check


def _sibling_profile(model_path: str):
    """Recover the training data profile for a model-text file from the
    checkpoint meta.json written next to it (``snap_N.model.txt`` ->
    ``snap_N.meta.json``).  Snapshots double as servable models, and the
    profile travels in their JSON meta — this is how a hot-rolled bundle
    gets its drift reference.  Returns None for bare model files or
    pre-profile snapshots (always legal)."""
    import json
    import os
    if not model_path.endswith(".model.txt"):
        return None
    meta_path = model_path[:-len(".model.txt")] + ".meta.json"
    if not os.path.exists(meta_path):
        return None
    try:
        with open(meta_path, "r") as fh:
            meta = json.load(fh)
        from ..obs.drift import DataProfile
        return DataProfile.from_json_dict(meta.get("data_profile"))
    except Exception:  # noqa: BLE001 - a corrupt sibling never blocks a load
        return None


class ModelBundle:
    """One loaded model, ready to serve.

    ``trees`` holds the PredictTree arrays stacked ``[I, K, ...]`` where
    ``I`` is boosting iterations and ``K`` trees-per-iteration (1 unless
    multiclass); ``objective`` supplies ``convert_output`` for non-raw
    scores (None for custom-objective models, which serve raw only).
    """

    def __init__(self, model_id: str, trees, num_class: int, k: int,
                 num_features: int, objective=None,
                 average_output: bool = False,
                 feature_names: Optional[List[str]] = None,
                 pandas_categorical=None, host_models=None,
                 profile=None):
        self.model_id = model_id
        self.trees = trees
        self.num_class = num_class
        self.num_tree_per_iteration = k
        self.num_features = num_features
        self.objective = objective
        self.average_output = average_output
        self.feature_names = list(feature_names or [])
        self.pandas_categorical = pandas_categorical
        self.total_iterations = int(trees.leaf_value.shape[0])
        self.generation = 0       # bumped by ModelRegistry.register
        # host-side trees (HostTree/LoadedTree), kept for the serving
        # traversal's SoA pack (serving/traversal.py); None disables the
        # traversal backend for this bundle (replay fallback)
        self.host_models = host_models
        # training data profile (obs.drift.DataProfile) or None: the
        # reference distribution drift monitoring scores against.
        # Optional EVERYWHERE — models loaded from bare text files or
        # pre-profile snapshots legally carry none (drift reports
        # "no_profile" for them)
        self.profile = profile
        self._capped: Dict[int, "jnp.ndarray"] = {}
        self._flat: Dict[bool, tuple] = {}        # quantize -> (forest, depth)
        self._flat_capped: Dict[tuple, object] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_impl(cls, model_id: str, impl,
                  feature_names: Optional[List[str]] = None,
                  pandas_categorical=None) -> "ModelBundle":
        """Bundle a boosting driver (basic.Booster._impl or a GBDT built
        directly, as bench.py does)."""
        models = impl.models
        check(len(models) > 0, "cannot serve an empty model")
        k = max(impl.num_tree_per_iteration, 1)
        total = (len(models) // k) * k   # drop a partial trailing iteration
        stacked = impl._stacked_predict_trees(0, total)
        trees = jax.tree.map(
            lambda a: a.reshape((total // k, k) + a.shape[1:]), stacked)
        if feature_names is None and getattr(impl, "train_data", None) is not None:
            feature_names = list(impl.train_data.feature_names)
        nf = len(feature_names) if feature_names else int(max(
            (int(np.max(t.split_feature, initial=0)) for t in models),
            default=0)) + 1
        profile = None
        if getattr(impl, "train_data", None) is not None:
            try:
                profile = impl.train_data.data_profile()
            except Exception:  # noqa: BLE001 - profile is best-effort
                profile = None
        return cls(model_id, trees, num_class=impl.num_class, k=k,
                   num_features=nf, objective=impl.objective,
                   average_output=impl.average_output,
                   feature_names=feature_names,
                   pandas_categorical=pandas_categorical,
                   host_models=list(models[:total]), profile=profile)

    @classmethod
    def from_booster(cls, model_id: str, booster) -> "ModelBundle":
        return cls.from_impl(model_id, booster._impl,
                             feature_names=booster._feature_names(),
                             pandas_categorical=booster.pandas_categorical)

    def effective_iterations(self, num_iteration: Optional[int]) -> int:
        if num_iteration is None or num_iteration <= 0:
            return self.total_iterations
        return min(int(num_iteration), self.total_iterations)

    def trees_for(self, num_iteration: Optional[int]):
        """Stacked trees capped to ``num_iteration`` (the
        GBDT::Predict num_iteration contract); full model returns the
        original arrays, capped views are sliced once and cached."""
        iters = self.effective_iterations(num_iteration)
        if iters == self.total_iterations:
            return self.trees
        with self._lock:
            if iters not in self._capped:
                self._capped[iters] = jax.tree.map(lambda a: a[:iters],
                                                   self.trees)
            return self._capped[iters]

    def flat_for(self, num_iteration: Optional[int] = None,
                 quantize: bool = False):
        """``(FlatForest, depth)`` for the serving traversal backend:
        packed ONCE per bundle (== per model generation — a hot-roll swaps
        the whole bundle, so stale tables die with it), device-put, and
        sliced/cached per ``num_iteration`` cap like ``trees_for``. The
        full-ensemble depth bounds every capped slice too."""
        if self.host_models is None:
            raise LightGBMError(
                "model %r has no host-side trees; the traversal backend "
                "needs a bundle built by from_impl/from_booster "
                "(serving_backend=replay serves bare-tree bundles)"
                % self.model_id)
        iters = self.effective_iterations(num_iteration)
        t = iters * self.num_tree_per_iteration
        q = bool(quantize)
        with self._lock:
            if q not in self._flat:
                from .traversal import pack_flat_forest
                host, depth = pack_flat_forest(self.host_models, quantize=q)
                self._flat[q] = (jax.tree.map(jnp.asarray, host), depth)
            full, depth = self._flat[q]
            if t == self.total_iterations * self.num_tree_per_iteration:
                return full, depth
            key = (t, q)
            if key not in self._flat_capped:
                self._flat_capped[key] = jax.tree.map(lambda a: a[:t], full)
            return self._flat_capped[key], depth


class ModelRegistry:
    """Named, immutable model bundles (the serving fleet's model store).

    Bundles never mutate; re-registration with ``replace=True`` swaps the
    whole bundle atomically under the registry lock and bumps that model's
    generation counter. Replace listeners (ServingEngine's predictor-cache
    purge) fire after the swap, outside the lock.
    """

    def __init__(self):
        self._bundles: Dict[str, ModelBundle] = {}
        self._generation: Dict[str, int] = {}
        self._replace_listeners: List = []
        self._lock = threading.Lock()

    def load_file(self, model_id: str, path: str,
                  replace: bool = False) -> ModelBundle:
        """Load a LightGBM model-text file (io/model_text.py format)."""
        return self.register(self.stage_file(model_id, path), replace=replace)

    def stage_file(self, model_id: str, path: str) -> ModelBundle:
        """Build a bundle from a model file WITHOUT registering it, its
        generation pre-set to the value ``register`` will assign. Lets a
        hot-roller compile the next generation's predictors off the
        request path (ServingEngine.prewarm_bundle) before the atomic
        ``register(..., replace=True)`` swap."""
        from ..basic import Booster
        from ..io.model_text import parse_model_file
        parse_model_file(path)   # fail fast with a format error, not mid-serve
        booster = Booster(model_file=path)
        bundle = ModelBundle.from_booster(model_id, booster)
        if bundle.profile is None:
            bundle.profile = _sibling_profile(path)
        with self._lock:
            bundle.generation = self._generation.get(model_id, 0) + 1
        return bundle

    def register_booster(self, model_id: str, booster,
                         replace: bool = False) -> ModelBundle:
        return self.register(ModelBundle.from_booster(model_id, booster),
                             replace=replace)

    def register_impl(self, model_id: str, impl,
                      replace: bool = False) -> ModelBundle:
        return self.register(ModelBundle.from_impl(model_id, impl),
                             replace=replace)

    def register(self, bundle: ModelBundle,
                 replace: bool = False) -> ModelBundle:
        replaced = False
        with self._lock:
            if bundle.model_id in self._bundles and not replace:
                raise LightGBMError("model id %r already registered "
                                    "(pass replace=True to swap it)"
                                    % bundle.model_id)
            replaced = bundle.model_id in self._bundles
            gen = self._generation.get(bundle.model_id, 0) + 1
            self._generation[bundle.model_id] = gen
            bundle.generation = gen
            self._bundles[bundle.model_id] = bundle
            listeners = list(self._replace_listeners)
        if replaced:
            # outside the lock: listeners may take their own locks
            # (ServingEngine purges its compiled-predictor cache here)
            for fn in listeners:
                fn(bundle.model_id)
        return bundle

    def generation(self, model_id: str) -> int:
        with self._lock:
            return self._generation.get(model_id, 0)

    def add_replace_listener(self, fn) -> None:
        """``fn(model_id)`` is called after an existing model is replaced."""
        with self._lock:
            self._replace_listeners.append(fn)

    def get(self, model_id: str) -> ModelBundle:
        with self._lock:
            b = self._bundles.get(model_id)
        if b is None:
            raise LightGBMError("unknown model id %r (registered: %s)"
                                % (model_id, sorted(self._bundles)))
        return b

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self._bundles)

    # ------------------------------------------------- checkpoint hot-roll
    def watch_dir(self, model_id: str, checkpoint_dir: str,
                  poll_interval: float = 10.0,
                  start: bool = False, engine=None) -> "CheckpointWatcher":
        """Hot-roll the newest valid snapshot of a lightgbm_tpu.checkpoint
        directory into this registry under ``model_id``. Returns a watcher;
        call ``poll()`` for one synchronous check (the first poll registers
        the current snapshot) or pass ``start=True`` for a daemon-thread
        loop. Replacement is atomic and invalidates the model's compiled
        predictors via the replace listeners.

        With ``engine`` (a ServingEngine), every poll that finds a newer
        snapshot PREWARMS it first — the staged bundle's predictors are
        compiled off the request path and credited to the warmup floor,
        then the swap commits; live traffic never waits on a compile and
        the zero-recompile-after-warmup invariant survives the roll."""
        w = CheckpointWatcher(self, model_id, checkpoint_dir, poll_interval,
                              engine=engine)
        if start:
            w.start()
        return w


class CheckpointWatcher:
    """Polls a checkpoint directory's manifest; loads newer snapshots."""

    def __init__(self, registry: ModelRegistry, model_id: str,
                 checkpoint_dir: str, poll_interval: float = 10.0,
                 engine=None):
        self.registry = registry
        self.model_id = model_id
        self.checkpoint_dir = checkpoint_dir
        self.poll_interval = float(poll_interval)
        self.engine = engine
        self._last_id = -1
        self._rejected_ids: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if engine is not None and hasattr(engine, "add_drift_hook"):
            # refit trigger: a drift warn on ANY model this engine serves
            # polls the checkpoint directory immediately (off-thread) —
            # see arm_drift_refit for the contract
            engine.add_drift_hook(self._drift_poll)

    def poll(self) -> bool:
        """One check: register the newest valid snapshot if it is newer
        than what we already rolled in. Returns True when a (re)load
        happened; verification failures fall back exactly like resume
        does (manifest checksums, newest -> oldest). With an attached
        engine the staged bundle is prewarmed BEFORE the swap — and a
        bundle the engine's guarded roll REFUSES (canary validation,
        docs/Resilience.md) is remembered and skipped on later polls, the
        prior generation left serving."""
        from ..checkpoint.manager import CheckpointManager
        from ..log import Log
        latest = CheckpointManager(self.checkpoint_dir).latest_model()
        if latest is None:
            return False
        snap_id, model_path = latest
        if snap_id <= self._last_id or snap_id in self._rejected_ids:
            return False
        if self.engine is not None:
            from ..log import LightGBMError
            try:
                bundle = self.engine.stage_and_prewarm(self.model_id,
                                                       model_path)
            except LightGBMError as e:
                self._rejected_ids.add(snap_id)
                live = (self.model_id in self.registry.ids())
                Log.warning("serving: snapshot %d REJECTED for model %r "
                            "(%s); %s", snap_id, self.model_id, e,
                            "prior generation stays live" if live
                            else "no prior generation registered")
                return False
        else:
            bundle = self.registry.stage_file(self.model_id, model_path)
        self.registry.register(bundle, replace=True)
        self._last_id = snap_id
        Log.info("serving: hot-rolled snapshot %d from %s into model %r",
                 snap_id, self.checkpoint_dir, self.model_id)
        return True

    def arm_drift_refit(self, monitor) -> None:
        """Subscribe this watcher to a DriftMonitor (obs/drift.py): when
        serving traffic drifts past the warn threshold, poll the
        checkpoint directory immediately — if a refit loop has produced a
        newer snapshot, it hot-rolls in without waiting out the poll
        interval. This is the refit-trigger contract from
        docs/Observability.md: the hook never trains anything itself; it
        closes the loop between "the data moved" and "pick up the
        retrained model". Watchers built with ``engine=`` arm themselves
        through ``ServingEngine.add_drift_hook`` — this method is the
        manual seam for monitors created outside an engine."""
        monitor.on_drift(self._drift_poll)

    def _drift_poll(self, report) -> None:
        """Drift hooks fire on the serving request thread that crossed
        the threshold — the poll (which may compile a staged bundle) runs
        on its own daemon thread so the triggering request never waits."""
        t = threading.Thread(target=self._safe_poll, daemon=True,
                             name="ckpt-drift-poll-%s" % self.model_id)
        t.start()

    def _safe_poll(self) -> None:
        try:
            self.poll()
        except Exception as e:  # noqa: BLE001 - keep serving alive
            from ..log import Log
            Log.warning("drift-triggered checkpoint poll %r: %s",
                        self.model_id, e)

    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(self.poll_interval):
                try:
                    self.poll()
                except Exception as e:  # noqa: BLE001 - keep serving alive
                    from ..log import Log
                    Log.warning("checkpoint watcher %r: %s",
                                self.model_id, e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ckpt-watch-%s" % self.model_id)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
