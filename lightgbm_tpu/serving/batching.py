"""Micro-batching queue: coalesce concurrent requests into one forward pass.

Individual serving requests are tiny (often 1 row); dispatching each as its
own device call wastes the accelerator and pays per-call latency. The queue
holds arriving requests for at most ``deadline_ms`` and fuses every
compatible request — same ``(model_id, raw_score, num_iteration)`` — into
ONE padded bucketed pass through the ServingEngine, then scatters the rows
of the result back to each caller's Future.

Deadline semantics: the clock starts at the OLDEST queued request, so a
request never waits more than ``deadline_ms`` in the queue regardless of
traffic; a full bucket (``max_rows``) dispatches immediately. This is the
classic serving trade — p50 rises by at most the deadline, throughput
scales with the bucket — and ``deadline_ms=0`` degrades to pass-through
(still fusing whatever is already queued).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np

from ..log import LightGBMError
from .predictor import ServingEngine


class _Request:
    __slots__ = ("key", "X", "future", "t")

    def __init__(self, key, X, future):
        self.key = key
        self.X = X
        self.future = future
        self.t = time.perf_counter()


class MicroBatchQueue:
    """Deadline-bounded request coalescer in front of a ServingEngine."""

    def __init__(self, engine: ServingEngine, max_rows: Optional[int] = None,
                 deadline_ms: float = 2.0):
        self.engine = engine
        self.max_rows = int(max_rows) if max_rows else engine.max_batch
        self.deadline_s = max(float(deadline_ms), 0.0) / 1000.0
        self._queue: List[_Request] = []
        self._cond = threading.Condition()
        self._running = False
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatchQueue":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._worker = threading.Thread(target=self._loop,
                                        name="lgbm-serve-batcher", daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None
        # fail any stragglers rather than hanging their callers
        with self._cond:
            leftovers, self._queue = self._queue, []
        for r in leftovers:
            r.future.set_exception(LightGBMError("serving queue stopped"))

    # ------------------------------------------------------------ submit
    def submit(self, model_id: str, X, raw_score: bool = False,
               num_iteration: Optional[int] = None) -> "Future":
        """Enqueue one request; the Future resolves to the same array
        ``engine.predict`` would return for it alone."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        fut: Future = Future()
        req = _Request((model_id, bool(raw_score), num_iteration), X, fut)
        with self._cond:
            if not self._running:
                raise LightGBMError("MicroBatchQueue.submit before start()")
            self._queue.append(req)
            self.engine.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()
        return fut

    def predict(self, model_id: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None) -> np.ndarray:
        """Blocking convenience wrapper around submit()."""
        return self.submit(model_id, X, raw_score, num_iteration).result()

    # ------------------------------------------------------------ worker
    def _collect(self) -> List[_Request]:
        """Under the lock: wait out the head request's deadline, then take
        every queued request sharing its key (arrival order preserved)."""
        head = self._queue[0]
        deadline = head.t + self.deadline_s
        while self._running:
            rows = 0
            for r in self._queue:
                if r.key == head.key:
                    rows += r.X.shape[0]
            now = time.perf_counter()
            if rows >= self.max_rows or now >= deadline:
                break
            self._cond.wait(timeout=deadline - now)
        taken = [r for r in self._queue if r.key == head.key]
        self._queue = [r for r in self._queue if r.key != head.key]
        self.engine.metrics.set_queue_depth(len(self._queue))
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._queue:
                    self._cond.wait()
                if not self._running:
                    return
                batch = self._collect()
            if batch:
                self._dispatch(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        model_id, raw_score, num_iteration = batch[0].key
        try:
            X = (batch[0].X if len(batch) == 1
                 else np.concatenate([r.X for r in batch], axis=0))
            out = self.engine.predict(model_id, X, raw_score=raw_score,
                                      num_iteration=num_iteration,
                                      _record_request=False)
            done = time.perf_counter()
            lo = 0
            for r in batch:
                hi = lo + r.X.shape[0]
                r.future.set_result(out[lo:hi])
                # per-CALLER accounting: latency includes the coalescing
                # wait (what the caller actually observed)
                self.engine.metrics.record_request(r.X.shape[0], done - r.t)
                lo = hi
        except Exception as e:  # noqa: BLE001 - delivered to each caller
            self.engine.metrics.record_error()
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
