"""Micro-batching queue: coalesce concurrent requests into one forward pass.

Individual serving requests are tiny (often 1 row); dispatching each as its
own device call wastes the accelerator and pays per-call latency. The queue
holds arriving requests for at most ``deadline_ms`` and fuses every
compatible request — same ``(model_id, raw_score, num_iteration)`` — into
ONE padded bucketed pass through the ServingEngine, then scatters the rows
of the result back to each caller's Future.

Deadline semantics: the clock starts at the OLDEST queued request, so a
request never waits more than ``deadline_ms`` in the queue regardless of
traffic; a full bucket (``max_rows``) dispatches immediately. This is the
classic serving trade — p50 rises by at most the deadline, throughput
scales with the bucket — and ``deadline_ms=0`` degrades to pass-through
(still fusing whatever is already queued).

Overload protection (docs/Resilience.md): ``max_queue_rows`` bounds the
TOTAL queued rows — a request that would exceed it is shed immediately
with :class:`OverloadedError` (fast-fail beats unbounded latency for every
admitted request behind it). ``request_timeout_ms`` is a per-request
deadline: a request still queued past it is expired at dispatch time
instead of wasting a device pass. ``stop(drain=True)`` (the default)
closes admission first, finishes the queued work, then joins the worker —
submit during drain gets a clean error, queued callers get answers.

Multi-model QoS (docs/Fleet.md): an optional :class:`fleet.qos.QosPolicy`
adds per-MODEL admission quotas (only the over-quota model sheds; the
rest keep being admitted under the engine-wide bound) and replaces the
head-of-line dispatch pick with weighted-fair queueing — each dispatch
serves the queued model with the smallest ``rows_served / weight``
virtual time, so shared-engine tenants get device rows proportional to
their weights under saturation. Without a policy the behavior is exactly
the pre-QoS queue (head-key dispatch, engine-wide shed only).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..log import LightGBMError, OverloadedError
from .predictor import ServingEngine


class _Request:
    __slots__ = ("key", "X", "future", "t", "deadline", "span", "qspan")

    def __init__(self, key, X, future, timeout_s=0.0, span=None, qspan=None):
        self.key = key
        self.X = X
        self.future = future
        self.t = time.perf_counter()
        self.deadline = self.t + timeout_s if timeout_s > 0 else None
        self.span = span        # trace root (obs/reqtrace.py) or None
        self.qspan = qspan      # open queue_wait child span or None


class MicroBatchQueue:
    """Deadline-bounded request coalescer in front of a ServingEngine."""

    def __init__(self, engine: ServingEngine, max_rows: Optional[int] = None,
                 deadline_ms: float = 2.0, max_queue_rows: int = 0,
                 request_timeout_ms: float = 0.0, qos=None, tracer=None):
        self.engine = engine
        self.max_rows = int(max_rows) if max_rows else engine.max_batch
        self.deadline_s = max(float(deadline_ms), 0.0) / 1000.0
        self.max_queue_rows = max(int(max_queue_rows), 0)   # 0 = unbounded
        self.request_timeout_s = max(float(request_timeout_ms), 0.0) / 1000.0
        self.qos = qos                      # fleet.qos.QosPolicy or None
        self.tracer = tracer                # obs.reqtrace.RequestTracer/None
        self._last_pick = None              # QoS decision for the batch span
        self._queue: List[_Request] = []
        self._queued_rows = 0
        self._model_rows: Dict[str, int] = {}
        self._cond = threading.Condition()
        self._running = False
        self._draining = False
        self._worker: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatchQueue":
        with self._cond:
            if self._running:
                return self
            self._running = True
            self._draining = False
        self._worker = threading.Thread(target=self._loop,
                                        name="lgbm-serve-batcher", daemon=True)
        self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the queue. ``drain=True`` closes admission, lets the worker
        finish everything already queued, then joins; ``drain=False`` stops
        immediately and fails queued callers."""
        with self._cond:
            if drain:
                self._draining = True
            else:
                self._running = False
            self._cond.notify_all()
        if drain:
            # admission is closed; the worker empties the queue then we
            # shut it down for real
            deadline = time.monotonic() + 30.0
            with self._cond:
                while self._queue and time.monotonic() < deadline:
                    self._cond.wait(timeout=0.05)
                self._running = False
                self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None
        # fail any stragglers rather than hanging their callers
        with self._cond:
            leftovers, self._queue = self._queue, []
            self._queued_rows = 0
            self._model_rows.clear()
            self._publish_depth_locked()
        for r in leftovers:
            r.future.set_exception(LightGBMError("serving queue stopped"))
            if r.span is not None:
                r.span.finish("error", error="serving queue stopped")

    # ------------------------------------------------------------ submit
    def _publish_depth_locked(self) -> None:
        self.engine.metrics.set_queue_depth(len(self._queue))
        self.engine.metrics.set_queue_rows(self._queued_rows)

    def submit(self, model_id: str, X, raw_score: bool = False,
               num_iteration: Optional[int] = None,
               trace=None) -> "Future":
        """Enqueue one request; the Future resolves to the same array
        ``engine.predict`` would return for it alone. Sheds with
        OverloadedError when admission would exceed ``max_queue_rows``.

        ``trace`` is an optional inbound ``x-lgbm-trace`` header value
        (or pre-parsed ``(trace_id, parent_span_id)``): when a tracer is
        wired, a trace ROOT is minted here — admission is where a
        request's life starts, so shed/draining exits are recorded on the
        trace before the error propagates."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        fut: Future = Future()
        span = qspan = None
        if self.tracer is not None and self.tracer.enabled:
            span = self.tracer.start_trace("request", ctx=trace,
                                           model=str(model_id),
                                           rows=int(X.shape[0]))
            qspan = span.child("queue_wait")
        req = _Request((model_id, bool(raw_score), num_iteration), X, fut,
                       self.request_timeout_s, span, qspan)
        try:
            with self._cond:
                if not self._running:
                    raise LightGBMError(
                        "MicroBatchQueue.submit before start()")
                if self._draining:
                    raise LightGBMError(
                        "serving queue is draining (shutting down); "
                        "request rejected")
                nrows = X.shape[0]
                if self.max_queue_rows and \
                        self._queued_rows + nrows > self.max_queue_rows:
                    self.engine.metrics.record_shed()
                    raise OverloadedError(
                        "serving queue overloaded: %d queued rows + %d "
                        "would exceed serve_max_queue_rows=%d"
                        % (self._queued_rows, nrows, self.max_queue_rows),
                        retry_after_s=max(self.deadline_s * 2, 0.05))
                if self.qos is not None and not self.qos.admit(
                        model_id, self._model_rows.get(model_id, 0), nrows):
                    # per-MODEL shed: only this tenant backs off; everyone
                    # else keeps being admitted under the engine-wide bound
                    self.engine.metrics.record_shed()
                    raise OverloadedError(
                        "model %r over its QoS quota: %d queued rows + %d "
                        "would exceed quota_rows=%d"
                        % (model_id, self._model_rows.get(model_id, 0),
                           nrows, self.qos.quota(model_id)),
                        retry_after_s=max(self.deadline_s * 2, 0.05))
                self._queue.append(req)
                self._queued_rows += nrows
                self._model_rows[model_id] = \
                    self._model_rows.get(model_id, 0) + nrows
                self._publish_depth_locked()
                self._cond.notify_all()
        except OverloadedError as e:
            # finish OUTSIDE the queue lock: a kept shed-trace writes to
            # the event stream, which must not serialize admissions
            if span is not None:
                span.finish("shed", error=str(e))
            raise
        except Exception as e:
            if span is not None:
                span.finish("error", error=str(e))
            raise
        return fut

    def predict(self, model_id: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                trace=None) -> np.ndarray:
        """Blocking convenience wrapper around submit()."""
        return self.submit(model_id, X, raw_score, num_iteration,
                           trace=trace).result()

    def stats(self) -> Dict:
        """Queue + per-model QoS state (the ``queue`` block of /stats)."""
        with self._cond:
            out: Dict = {"queued_requests": len(self._queue),
                         "queued_rows": self._queued_rows,
                         "model_rows": dict(self._model_rows)}
            if self.qos is not None:
                out["qos"] = self.qos.snapshot()
        return out

    # ------------------------------------------------------------ worker
    def _pick_key_locked(self) -> Tuple:
        """The dispatch key: head-of-line without QoS; with a policy, the
        oldest key of the queued model with the smallest weighted-fair
        virtual time (fleet/qos.py)."""
        if self.qos is None:
            return self._queue[0].key
        by_model: Dict[str, int] = {}
        for r in self._queue:
            by_model[r.key[0]] = by_model.get(r.key[0], 0) + r.X.shape[0]
        mid = self.qos.pick(by_model)
        # remember the decision for the batch span: which tenant the
        # weighted-fair virtual time elected, over what queue composition
        self._last_pick = {"picked": mid, "queued_rows": dict(by_model)}
        for r in self._queue:
            if r.key[0] == mid:
                return r.key
        return self._queue[0].key

    def _collect(self) -> List[_Request]:
        """Under the lock: wait out the head request's deadline, then take
        every queued request sharing the picked dispatch key (arrival
        order preserved within the key)."""
        head = self._queue[0]
        deadline = head.t + self.deadline_s
        while self._running and not self._draining:
            key = self._pick_key_locked()
            rows = 0
            for r in self._queue:
                if r.key == key:
                    rows += r.X.shape[0]
            now = time.perf_counter()
            if rows >= self.max_rows or now >= deadline:
                break
            self._cond.wait(timeout=deadline - now)
        key = self._pick_key_locked()
        taken = [r for r in self._queue if r.key == key]
        self._queue = [r for r in self._queue if r.key != key]
        nrows = sum(r.X.shape[0] for r in taken)
        self._queued_rows -= nrows
        left = self._model_rows.get(key[0], 0) - nrows
        if left > 0:
            self._model_rows[key[0]] = left
        else:
            self._model_rows.pop(key[0], None)
        if self.qos is not None:
            self.qos.account(key[0], nrows)
        self._publish_depth_locked()
        self._cond.notify_all()   # stop(drain=True) waits on queue empty
        return taken

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._queue:
                    if self._draining:
                        return
                    self._cond.wait()
                if not self._running:
                    return
                batch = self._collect()
            # expire requests whose per-request deadline passed while
            # queued — their caller stopped waiting; don't burn a pass
            if self.request_timeout_s > 0:
                now = time.perf_counter()
                live = []
                for r in batch:
                    if r.deadline is not None and now > r.deadline:
                        self.engine.metrics.record_timeout()
                        r.future.set_exception(OverloadedError(
                            "request expired in queue after %.0f ms "
                            "(serve_request_timeout_ms=%.0f)"
                            % ((now - r.t) * 1000.0,
                               self.request_timeout_s * 1000.0),
                            retry_after_s=max(self.deadline_s * 2, 0.05)))
                        if r.span is not None:
                            r.qspan.end("shed")
                            r.span.finish("shed", error="expired in queue")
                    else:
                        live.append(r)
                batch = live
            if batch:
                self._dispatch(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        model_id, raw_score, num_iteration = batch[0].key
        bspan = pspan = None
        spans = [r.span for r in batch if r.span is not None]
        if spans:
            # queue_wait ends when the batch leaves the queue; the batch
            # span is ONE span linked from every coalesced request, with
            # the QoS election and the engine pass as children
            for r in batch:
                if r.qspan is not None:
                    r.qspan.end()
            bspan = self.tracer.batch_span(
                "batch", spans, model=str(model_id), requests=len(batch),
                rows=int(sum(r.X.shape[0] for r in batch)))
            pick = self._last_pick
            if pick is not None:
                bspan.child("qos_pick", picked=pick["picked"],
                            queued_rows=pick["queued_rows"]).end()
            pspan = bspan.child("predict", model=str(model_id))
        try:
            X = (batch[0].X if len(batch) == 1
                 else np.concatenate([r.X for r in batch], axis=0))
            # _span only travels when tracing minted one: duck-typed
            # engines (resilience fakes, wrappers) never see the kwarg
            kw = {"_span": pspan} if pspan is not None else {}
            out = self.engine.predict(model_id, X, raw_score=raw_score,
                                      num_iteration=num_iteration,
                                      _record_request=False, **kw)
            if pspan is not None:
                pspan.end()
                bspan.end()
            done = time.perf_counter()
            lo = 0
            for r in batch:
                hi = lo + r.X.shape[0]
                r.future.set_result(out[lo:hi])
                # per-CALLER accounting: latency includes the coalescing
                # wait (what the caller actually observed)
                self.engine.metrics.record_request(r.X.shape[0], done - r.t)
                if r.span is not None:
                    r.span.finish(
                        "ok", latency_ms=round((done - r.t) * 1000.0, 3))
                lo = hi
        except Exception as e:  # noqa: BLE001 - delivered to each caller
            self.engine.metrics.record_error()
            if pspan is not None:
                pspan.end("error", error=str(e))
                bspan.end("error")
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                if r.span is not None:
                    r.span.finish("error", error=str(e))
