"""Compiled-predictor cache: shape-bucketed, zero-recompile batch inference.

XLA compiles one executable per input shape, so serving arbitrary request
sizes naively would retrace on every new batch size — the exact failure
mode the ROADMAP's "heavy traffic" goal cannot afford. The cache here is
keyed ``(model_id, bucket, raw_score, num_iteration)``:

- request rows are padded up to a POWER-OF-TWO bucket (floored at
  ``min_bucket``, capped at ``max_batch``; larger requests are chunked),
  so at most ``log2(max_batch / min_bucket) + 1`` shapes exist per key
  prefix and a warmup pass over them makes every later request a cache
  hit with zero new compilations;
- each cache entry owns ONE jit-compiled function closed over nothing —
  trees ride in as device-resident arguments — so entries never interfere
  and a cache miss maps 1:1 to a compilation request;
- the raw->output transform (sigmoid / softmax / exp) and the
  average-output division are baked INTO the compiled function, keeping a
  whole request one device round-trip.

Multi-device: with a serving mesh (parallel/mesh.py serving_mesh) the
padded batch is row-sharded and trees replicated; GSPMD partitions the
forest apply. Buckets smaller than the mesh run replicated — the dispatch
decision is a static property of the cache key, so warmup covers it.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..bucketing import pow2_bucket, pow2_ladder
from ..core import tree as tree_mod
from ..log import LightGBMError, Log, check
from ..parallel.mesh import replicated, row_sharding, serving_mesh
from ..config import SERVING_BACKENDS
from ..resilience import faults
from . import traversal as traversal_mod
from .metrics import ServingMetrics
from .registry import ModelBundle, ModelRegistry


def bucket_rows(n: int, min_bucket: int = 16, max_batch: int = 4096) -> int:
    """Power-of-two padded size for an ``n``-row request (chunks of
    ``max_batch`` beyond the cap). Thin wrapper over the shared
    ``lightgbm_tpu.bucketing`` ladder, which the frontier grower's wave
    widths also ride."""
    check(n >= 1, "empty prediction request")
    return pow2_bucket(n, min_bucket, max_batch)


def bucket_sizes(min_bucket: int = 16, max_batch: int = 4096) -> List[int]:
    """Every bucket the cache can produce — the warmup schedule."""
    return pow2_ladder(min_bucket, max_batch)


class _CompiledPredictor:
    """One cache entry: a jit function pinned to (trees, bucket, transform).

    ``backend="traversal"`` (default) serves from the bundle's packed
    ``FlatForest`` (serving/traversal.py): O(depth) fused gather steps
    over all rows x all trees instead of the replay path's
    O(num_leaves) sequential split replays — same bit-exact outputs.
    ``backend="replay"`` keeps the training-side path (also the
    fallback for bundles without host-side trees)."""

    def __init__(self, bundle: ModelBundle, bucket: int, raw_score: bool,
                 num_iteration: int, mesh=None, backend: str = "traversal",
                 cascade_trees: int = 0, cascade_margin: float = 10.0,
                 quantize_leaves: bool = False):
        self.bucket = bucket
        use_traversal = (backend == "traversal"
                         and bundle.host_models is not None)
        self.backend = "traversal" if use_traversal else "replay"
        if use_traversal:
            trees, depth = bundle.flat_for(num_iteration,
                                           quantize=quantize_leaves)
        else:
            trees = bundle.trees_for(num_iteration)
            depth = 0
        self._mesh = mesh
        # static per-entry dispatch: shard rows when the bucket tiles the
        # mesh evenly, otherwise replicate the batch too (tiny buckets)
        self._shard = (mesh is not None
                       and bucket % mesh.devices.size == 0)
        if mesh is not None:
            trees = jax.device_put(trees, replicated(mesh))
            self._x_sharding = (row_sharding(mesh, extra_dims=1)
                                if self._shard else replicated(mesh))
        else:
            self._x_sharding = None
        self._trees = trees
        convert = (None if raw_score or bundle.objective is None
                   else bundle.objective.convert_output)
        avg_iters = num_iteration if bundle.average_output else 0
        k = bundle.num_tree_per_iteration

        def apply(t, x):
            if use_traversal:
                out = traversal_mod.forest_scores_flat(
                    t, x, k, depth, cascade_trees=cascade_trees,
                    cascade_margin=cascade_margin)      # [bucket, K] f32
            else:
                out = tree_mod.predict_forest_scores(t, x)
            if avg_iters:
                out = out / np.float32(avg_iters)
            if convert is not None:
                out = convert(out)
            return out

        self._fn = jax.jit(apply)

    def __call__(self, xpad: np.ndarray) -> jnp.ndarray:
        x = (jax.device_put(xpad, self._x_sharding)
             if self._x_sharding is not None else jnp.asarray(xpad))
        return self._fn(self._trees, x)


class ServingEngine:
    """Registry + predictor cache + (optional) mesh: the serve path's core.

    ``predict`` is thread-safe and synchronous; the micro-batching queue
    (serving/batching.py) sits in front of it for concurrent traffic.
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 max_batch: int = 4096, min_bucket: int = 16,
                 num_devices: int = 1,
                 metrics: Optional[ServingMetrics] = None,
                 backend: str = "traversal", cascade_trees: int = 0,
                 cascade_margin: float = 10.0,
                 quantize_leaves: bool = False,
                 guard_hot_roll: bool = True, canary_rows: int = 16,
                 roll_max_latency_ms: float = 0.0,
                 drift: bool = True, drift_warn_psi: float = 0.25,
                 drift_min_rows: int = 256, drift_decay: float = 0.999):
        check(max_batch >= 1 and min_bucket >= 1,
              "serve_max_batch and serve_min_bucket must be >= 1")
        check(backend in SERVING_BACKENDS,
              "serving_backend should be one of %s, got %r"
              % (list(SERVING_BACKENDS), backend))
        check(cascade_trees >= 0 and cascade_margin >= 0,
              "serving_cascade_trees and serving_cascade_margin must be >= 0")
        # normalize both to powers of two so bucket_rows' ladder is exact
        self.min_bucket = 1 << (int(min_bucket) - 1).bit_length()
        self.max_batch = max(1 << (int(max_batch) - 1).bit_length(),
                             self.min_bucket)
        self.backend = backend
        self.cascade_trees = int(cascade_trees)
        self.cascade_margin = float(cascade_margin)
        self.quantize_leaves = bool(quantize_leaves)
        self.guard_hot_roll = bool(guard_hot_roll)
        self.canary_rows = max(int(canary_rows), 1)
        self.roll_max_latency_ms = max(float(roll_max_latency_ms), 0.0)
        self.registry = registry if registry is not None else ModelRegistry()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.mesh = serving_mesh(num_devices) if num_devices != 1 else None
        self._cache: Dict[Tuple, _CompiledPredictor] = {}
        self._lock = threading.Lock()
        # train/serve drift (obs/drift.py): one DriftMonitor per live
        # (model, generation), created lazily on the first predict so a
        # pre-profile bundle costs one dict lookup per request and a
        # profile-less registry costs nothing at boot
        self.drift_enabled = bool(drift)
        self.drift_warn_psi = float(drift_warn_psi)
        self.drift_min_rows = int(drift_min_rows)
        self.drift_decay = float(drift_decay)
        self._drift: Dict[str, Tuple[int, object]] = {}
        self._drift_hooks: List = []   # attached to every (future) monitor
        self._health_monitor = None  # lazy HealthMonitor, warn-only routing
        # atomic re-registration (checkpoint hot-roll): purge this model's
        # compiled predictors when its bundle is swapped
        self.registry.add_replace_listener(self._invalidate_model)

    # ------------------------------------------------------------ cache
    def _invalidate_model(self, model_id: str) -> None:
        """Drop cache entries compiled against generations OTHER than the
        model's current one. The generation in the cache key already
        prevents stale *hits*; this reclaims dead entries' device memory
        while keeping entries a hot-roll prewarm compiled for the
        just-committed generation (prewarm_bundle)."""
        current = self.registry.generation(model_id)
        with self._lock:
            for key in [k for k in self._cache
                        if k[0] == model_id and k[1] != current]:
                del self._cache[key]
            held = self._drift.get(model_id)
            if held is not None and held[0] != current:
                # the new generation may carry a different (or no) training
                # profile — drop the monitor; the next predict rebuilds it
                del self._drift[model_id]
                from ..obs.drift import unregister_monitor
                unregister_monitor(model_id)

    def _active_margin(self) -> float:
        """The cascade margin live entries are compiled against; part of
        the cache key so a retune (set_cascade_margin) can build the new
        margin's entries while the old ones keep serving. Constant 0.0
        when no cascade is configured — margin writes then never churn
        the cache."""
        return float(self.cascade_margin) if self.cascade_trees > 0 else 0.0

    def _predictor(self, bundle: ModelBundle, bucket: int, raw_score: bool,
                   iters: int) -> _CompiledPredictor:
        margin = self._active_margin()
        key = (bundle.model_id, getattr(bundle, "generation", 0), bucket,
               bool(raw_score), iters, margin)
        with self._lock:
            entry = self._cache.get(key)
            if entry is None:
                entry = _CompiledPredictor(
                    bundle, bucket, raw_score, iters, mesh=self.mesh,
                    backend=self.backend, cascade_trees=self.cascade_trees,
                    cascade_margin=margin if self.cascade_trees > 0
                    else self.cascade_margin,
                    quantize_leaves=self.quantize_leaves)
                self._cache[key] = entry
                hit = False
            else:
                hit = True
        self.metrics.record_cache(hit)
        return entry

    def cache_size(self) -> int:
        with self._lock:
            return len(self._cache)

    def set_cascade_margin(self, margin: float) -> int:
        """Retune the early-exit cascade margin OFF the request path (the
        fleet CascadeAutotuner's apply hook): compile + execute every
        bucket at the new margin inside a warmup-credit window — exactly
        the ``stage_and_prewarm`` accounting, so the zero-recompile
        serving invariant survives the retune — then purge the old
        margin's entries. Returns the number of entries re-warmed (0 for
        a no-op or when no cascade is configured)."""
        margin = float(margin)
        check(margin >= 0, "cascade margin must be >= 0, got %s" % margin)
        if self.cascade_trees <= 0 or margin == self.cascade_margin:
            self.cascade_margin = margin
            return 0
        from ..profiling import backend_compile_count
        c0 = backend_compile_count()
        m0 = self.metrics.cache_misses
        self.cascade_margin = margin
        warmed = 0
        try:
            for mid in self.registry.ids():
                warmed += self._warm_bundle(self.registry.get(mid),
                                            (False,), (None,))
        finally:
            self.metrics.add_warmup_credit(backend_compile_count() - c0,
                                           self.metrics.cache_misses - m0)
        with self._lock:
            for key in [k for k in self._cache if k[5] != margin]:
                del self._cache[key]
        return warmed

    # ------------------------------------------------------------ drift
    def drift_monitor(self, bundle: ModelBundle):
        """The DriftMonitor for ``bundle``'s current generation (created
        and ``register_monitor``-ed on first use, so ``/drift`` and the
        cluster federation see it).  A monitor exists even when the bundle
        carries no training profile — it then reports ``no_profile``
        instead of silently vanishing from the status surfaces.  Returns
        None only when drift monitoring is disabled engine-wide."""
        if not self.drift_enabled:
            return None
        gen = getattr(bundle, "generation", 0)
        with self._lock:
            held = self._drift.get(bundle.model_id)
            if held is not None and held[0] == gen:
                return held[1]
        from ..obs.drift import DriftMonitor, register_monitor
        mon = DriftMonitor(
            getattr(bundle, "profile", None), model_id=bundle.model_id,
            warn_psi=self.drift_warn_psi, min_rows=self.drift_min_rows,
            decay=self.drift_decay, monitor=self._drift_health())
        for hook in list(self._drift_hooks):
            mon.on_drift(hook)
        with self._lock:
            held = self._drift.get(bundle.model_id)
            if held is not None and held[0] == gen:
                return held[1]   # raced another request; keep the winner
            self._drift[bundle.model_id] = (gen, mon)
        register_monitor(mon)
        return mon

    def add_drift_hook(self, hook) -> None:
        """Subscribe ``hook(report_dict)`` to ok->warn drift transitions
        of EVERY model this engine serves — current monitors and ones not
        yet created (they are lazy, per generation).  This is how
        ``CheckpointWatcher`` arms its refit-trigger poll without knowing
        which bundle will drift first."""
        self._drift_hooks.append(hook)
        with self._lock:
            monitors = [held[1] for held in self._drift.values()]
        for mon in monitors:
            mon.on_drift(hook)

    def _drift_health(self):
        """Warn-only HealthMonitor shared by this engine's drift monitors
        (note_drift never escalates, so ``action="warn"`` is exact)."""
        if self._health_monitor is None:
            from ..obs.health import HealthMonitor
            self._health_monitor = HealthMonitor(action="warn")
        return self._health_monitor

    def drift_status(self) -> Dict:
        """Worst drift status across this engine's live monitors — the
        ``drift`` field of the serving ``/healthz`` payload.  ``disabled``
        when the engine runs with ``serve_drift=false``; ``no_profile``
        when no monitored model carries a training profile yet."""
        if not self.drift_enabled:
            return {"status": "disabled", "models": {}}
        with self._lock:
            monitors = [held[1] for held in self._drift.values()]
        rank = {"warn": 2, "ok": 1, "no_profile": 0}
        worst, models = "no_profile", {}
        for mon in monitors:
            st = mon.status()
            models[st.get("model", "")] = st
            if rank.get(st["status"], 0) > rank[worst]:
                worst = st["status"]
        return {"status": worst, "models": models}

    # ------------------------------------------------------------ predict
    def predict(self, model_id: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                _record_request: bool = True, _span=None) -> np.ndarray:
        """Serve one request; output matches ``Booster.predict`` (same f32
        accumulation order, same transform) for any request size.
        ``_record_request=False`` is for the micro-batch queue, which
        accounts its callers itself (per-caller count + queue-inclusive
        latency) so a fused dispatch is not double-counted.

        ``_span`` is an optional trace span (obs/reqtrace.py): when
        present, each bucket pass is split into a ``device_dispatch``
        child (the async jit call returning a device future) and a
        ``device_wait`` child (the host blocking on the transfer) — the
        split only exists on the traced path; the untraced fast path is
        the exact pre-trace statement, same compiled entries either way."""
        t0 = time.perf_counter()
        # serve_predict seam: "request" = dispatched predict, counted by
        # the plan's per-point counter (fused queue batches count once)
        faults.inject("serve_predict", model=model_id)
        bundle = self.registry.get(model_id)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        check(X.ndim == 2, "prediction input must be 2-D")
        if bundle.num_features:
            check(X.shape[1] == bundle.num_features,
                  "model %r expects %d features, request has %d"
                  % (model_id, bundle.num_features, X.shape[1]))
        iters = bundle.effective_iterations(num_iteration)
        if _span is not None and self.cascade_trees > 0:
            # cascade stages run inside the compiled program; the trace
            # records the configuration the pass was compiled against
            _span.annotate(cascade_trees=self.cascade_trees,
                           cascade_margin=self.cascade_margin)
        n = X.shape[0]
        outs = []
        for lo in range(0, n, self.max_batch):
            xc = X[lo:lo + self.max_batch]
            b = bucket_rows(xc.shape[0], self.min_bucket, self.max_batch)
            xpad = xc
            if b != xc.shape[0]:
                xpad = np.zeros((b, X.shape[1]), np.float32)
                xpad[:xc.shape[0]] = xc
            entry = self._predictor(bundle, b, raw_score, iters)
            t1 = time.perf_counter()
            if _span is not None:
                dspan = _span.child("device_dispatch", bucket=b)
                dev = entry(xpad)
                dspan.end()
                wspan = _span.child("device_wait", bucket=b)
                out = np.asarray(dev, np.float64)[:xc.shape[0]]
                wspan.end()
            else:
                out = np.asarray(entry(xpad), np.float64)[:xc.shape[0]]
            self.metrics.record_batch(b)
            self.metrics.record_bucket_latency(
                b, (time.perf_counter() - t1) * 1000.0)
            outs.append(out)
        out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        if bundle.num_tree_per_iteration == 1:
            out = out[:, 0]
        if self.drift_enabled:
            mon = self.drift_monitor(bundle)
            if mon is not None:
                try:
                    mon.observe(X, scores=out)
                except Exception as e:  # diagnostics must not fail serving
                    Log.debug("drift observe failed for %r: %s",
                              model_id, e)
        if _record_request:
            self.metrics.record_request(n, time.perf_counter() - t0)
        return out

    # ------------------------------------------------------------ warmup
    def warmup(self, model_ids: Optional[Iterable[str]] = None,
               raw_scores: Iterable[bool] = (False,),
               num_iterations: Iterable[Optional[int]] = (None,),
               extract_costs: bool = False) -> int:
        """Compile every bucket for the given key prefixes so live traffic
        never compiles; returns the number of entries warmed. Marks the
        metrics recompile floor when done.

        ``extract_costs=True`` additionally runs the obs cost model over
        each warmed bucket (``predict_b<bucket>`` entries: XLA FLOPs /
        bytes per forward pass, feeding ``GET /roofline`` and bench).
        AOT extraction shares nothing with the serving executables, so it
        cannot retrace them — and it runs BEFORE the recompile floor is
        marked, so its own one-time compiles never trip the serving
        zero-recompile assertion."""
        ids = list(model_ids) if model_ids is not None else self.registry.ids()
        cm = None
        if extract_costs:
            from ..obs.costmodel import get_cost_model
            cm = get_cost_model()
        warmed = 0
        for mid in ids:
            warmed += self._warm_bundle(self.registry.get(mid), raw_scores,
                                        num_iterations, cm)
        self.metrics.mark_warmup_done()
        return warmed

    def _warm_bundle(self, bundle: ModelBundle, raw_scores, num_iterations,
                     cm=None) -> int:
        """Compile + execute every bucket for one bundle (shared by
        boot-time ``warmup`` and hot-roll ``prewarm_bundle``)."""
        nf = max(bundle.num_features, 1)
        warmed = 0
        for b in bucket_sizes(self.min_bucket, self.max_batch):
            zeros = np.zeros((b, nf), np.float32)
            for raw in raw_scores:
                for ni in num_iterations:
                    iters = bundle.effective_iterations(ni)
                    entry = self._predictor(bundle, b, raw, iters)
                    # lgbm-lint: disable=LGL103 serving warmup sync
                    jax.block_until_ready(entry(zeros))
                    warmed += 1
                    if cm is not None:
                        cm.analyze(
                            "predict_b%d" % b, entry._fn,
                            jax.tree_util.tree_map(
                                lambda a: jax.ShapeDtypeStruct(
                                    a.shape, a.dtype), entry._trees),
                            jax.ShapeDtypeStruct((b, nf), jnp.float32),
                            extra_key="model=%s;raw=%d;iters=%d"
                            % (bundle.model_id, int(raw), iters))
        return warmed

    def prewarm_bundle(self, bundle: ModelBundle,
                       raw_scores: Iterable[bool] = (False,),
                       num_iterations: Iterable[Optional[int]] = (None,)
                       ) -> int:
        """Compile a STAGED bundle's predictors before it is registered
        (registry.stage_file -> prewarm_bundle -> register): a hot-roll
        pays its compilations here, off the request path, and the
        compiles/misses are credited to the metrics floors so the
        zero-recompile-after-warmup assertion survives the roll. Entries
        are cached under the staged generation; the generation-aware
        purge keeps them when the swap commits."""
        from ..profiling import backend_compile_count
        c0 = backend_compile_count()
        m0 = self.metrics.cache_misses
        warmed = self._warm_bundle(bundle, raw_scores, num_iterations)
        self.metrics.add_warmup_credit(backend_compile_count() - c0,
                                       self.metrics.cache_misses - m0)
        return warmed

    def stage_and_prewarm(self, model_id: str, path: str,
                          raw_scores: Iterable[bool] = (False,),
                          num_iterations: Iterable[Optional[int]] = (None,)
                          ) -> ModelBundle:
        """The full off-path half of a hot-roll: stage ``path`` as the
        next generation of ``model_id`` and prewarm it, crediting EVERY
        compilation in the window — the staged bundle's device stacking
        included, not just the predictor compiles — to the warmup
        floors. Caller commits with ``registry.register(bundle,
        replace=True)`` (CheckpointWatcher.poll does exactly this).

        Guarded roll (``guard_hot_roll``, docs/Resilience.md): canary
        rows are scored on the staged bundle — finite outputs,
        traversal-vs-replay parity, optional latency cap — and a failing
        bundle is REFUSED: its compiled entries are purged, the
        ``lgbm_serving_rollbacks_total`` counter ticks, and the raised
        LightGBMError leaves the prior generation serving untouched."""
        from ..profiling import backend_compile_count
        c0 = backend_compile_count()
        m0 = self.metrics.cache_misses
        try:
            bundle = self.registry.stage_file(model_id, path)
            self._warm_bundle(bundle, raw_scores, num_iterations)
            if self.guard_hot_roll:
                try:
                    self._validate_bundle(bundle)
                except LightGBMError as e:
                    self.metrics.record_rollback()
                    self._purge_generation(model_id,
                                           getattr(bundle, "generation", 0))
                    Log.warning("hot-roll REFUSED for %r (%s): prior "
                                "generation stays live", model_id, e)
                    raise
        finally:
            # validation compiles (if any) are staged-roll work, never
            # serving recompiles — credit even on refusal
            self.metrics.add_warmup_credit(backend_compile_count() - c0,
                                           self.metrics.cache_misses - m0)
        return bundle

    # ------------------------------------------------------------ guard
    def _purge_generation(self, model_id: str, generation: int) -> None:
        """Drop every compiled entry of one (model, generation) — the
        refused staged bundle's predictors must not linger in device
        memory or ever serve a request."""
        with self._lock:
            for key in [k for k in self._cache
                        if k[0] == model_id and k[1] == generation]:
                del self._cache[key]

    def _canary(self, bundle: ModelBundle) -> np.ndarray:
        """Deterministic canary rows: a fixed grid spanning a wide value
        range (zeros, extremes, and a dense ramp), enough to route down
        both sides of any split and surface NaN/inf leaves."""
        nf = max(bundle.num_features, 1)
        n = self.canary_rows
        X = np.linspace(-1e3, 1e3, num=n * nf,
                        dtype=np.float32).reshape(n, nf)
        X[0, :] = 0.0
        if n > 1:
            X[1, :] = np.float32(1e30)
        return X

    def _validate_bundle(self, bundle: ModelBundle) -> None:
        """Score canary rows on the STAGED bundle; raise LightGBMError on
        any failed check. Runs inside the stage_and_prewarm credit window
        so nothing here counts as a serving recompile."""
        if getattr(bundle, "profile", None) is None:
            # warn, don't refuse: pre-profile snapshots/model files are
            # valid models — they just cannot be drift-monitored, and the
            # /drift route will say "no_profile" for them
            Log.warning(
                "staged model %r carries no training data profile "
                "(pre-profile snapshot or bare model file); train/serve "
                "drift detection is unavailable for this generation",
                bundle.model_id)
        X = self._canary(bundle)
        iters = bundle.effective_iterations(None)
        b = bucket_rows(X.shape[0], self.min_bucket, self.max_batch)
        xpad = X
        if b != X.shape[0]:
            xpad = np.zeros((b, X.shape[1]), np.float32)
            xpad[:X.shape[0]] = X
        entry = self._predictor(bundle, b, False, iters)
        # lgbm-lint: disable=LGL103 canary probe, sync is the point
        jax.block_until_ready(entry(xpad))   # warm before timing
        t1 = time.perf_counter()
        # lgbm-lint: disable=LGL103 canary latency measurement
        out = np.asarray(jax.block_until_ready(entry(xpad)))[:X.shape[0]]
        latency_ms = (time.perf_counter() - t1) * 1000.0
        if not np.isfinite(out).all():
            bad = int(np.count_nonzero(~np.isfinite(out)))
            raise LightGBMError(
                "staged model %r failed canary validation: %d non-finite "
                "output(s) across %d canary rows"
                % (bundle.model_id, bad, X.shape[0]))
        if bundle.host_models is not None:
            # eager traversal-vs-replay parity on the canary rows: both
            # paths must agree before the flat forest serves traffic
            flat, depth = bundle.flat_for(iters)
            trees = bundle.trees_for(iters)
            xj = jnp.asarray(X)
            a = np.asarray(traversal_mod.forest_scores_flat(
                flat, xj, bundle.num_tree_per_iteration, depth))
            r = np.asarray(tree_mod.predict_forest_scores(trees, xj))
            if not (np.isfinite(a).all() and np.isfinite(r).all()):
                raise LightGBMError(
                    "staged model %r failed canary validation: non-finite "
                    "raw scores (traversal/replay)" % bundle.model_id)
            if not np.allclose(a, r, rtol=1e-5, atol=1e-5):
                raise LightGBMError(
                    "staged model %r failed canary validation: traversal "
                    "vs replay diverge (max |diff| %.3g)"
                    % (bundle.model_id, float(np.max(np.abs(a - r)))))
        if self.roll_max_latency_ms and \
                latency_ms > self.roll_max_latency_ms:
            raise LightGBMError(
                "staged model %r failed canary validation: warmed predict "
                "took %.1f ms > serve_roll_max_latency_ms=%.1f"
                % (bundle.model_id, latency_ms, self.roll_max_latency_ms))
