"""Compiled-predictor cache: shape-bucketed, zero-recompile batch inference.

XLA compiles one executable per input shape, so serving arbitrary request
sizes naively would retrace on every new batch size — the exact failure
mode the ROADMAP's "heavy traffic" goal cannot afford. The cache here is
keyed ``(model_id, bucket, raw_score, num_iteration)``:

- request rows are padded up to a POWER-OF-TWO bucket (floored at
  ``min_bucket``, capped at ``max_batch``; larger requests are chunked),
  so at most ``log2(max_batch / min_bucket) + 1`` shapes exist per key
  prefix and a warmup pass over them makes every later request a cache
  hit with zero new compilations;
- each cache entry owns ONE jit-compiled function closed over nothing —
  trees ride in as device-resident arguments — so entries never interfere
  and a cache miss maps 1:1 to a compilation request;
- the raw->output transform (sigmoid / softmax / exp) and the
  average-output division are baked INTO the compiled function, keeping a
  whole request one device round-trip.

Multi-device: with a serving mesh (parallel/mesh.py serving_mesh) the
padded batch is row-sharded and trees replicated; GSPMD partitions the
forest apply. Buckets smaller than the mesh run replicated — the dispatch
decision is a static property of the cache key, so warmup covers it.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..bucketing import pow2_bucket, pow2_ladder
from ..core import tree as tree_mod
from ..log import LightGBMError, check
from ..parallel.mesh import replicated, row_sharding, serving_mesh
from .metrics import ServingMetrics
from .registry import ModelBundle, ModelRegistry


def bucket_rows(n: int, min_bucket: int = 16, max_batch: int = 4096) -> int:
    """Power-of-two padded size for an ``n``-row request (chunks of
    ``max_batch`` beyond the cap). Thin wrapper over the shared
    ``lightgbm_tpu.bucketing`` ladder, which the frontier grower's wave
    widths also ride."""
    check(n >= 1, "empty prediction request")
    return pow2_bucket(n, min_bucket, max_batch)


def bucket_sizes(min_bucket: int = 16, max_batch: int = 4096) -> List[int]:
    """Every bucket the cache can produce — the warmup schedule."""
    return pow2_ladder(min_bucket, max_batch)


class _CompiledPredictor:
    """One cache entry: a jit function pinned to (trees, bucket, transform)."""

    def __init__(self, bundle: ModelBundle, bucket: int, raw_score: bool,
                 num_iteration: int, mesh=None):
        self.bucket = bucket
        trees = bundle.trees_for(num_iteration)
        self._mesh = mesh
        # static per-entry dispatch: shard rows when the bucket tiles the
        # mesh evenly, otherwise replicate the batch too (tiny buckets)
        self._shard = (mesh is not None
                       and bucket % mesh.devices.size == 0)
        if mesh is not None:
            trees = jax.device_put(trees, replicated(mesh))
            self._x_sharding = (row_sharding(mesh, extra_dims=1)
                                if self._shard else replicated(mesh))
        else:
            self._x_sharding = None
        self._trees = trees
        convert = (None if raw_score or bundle.objective is None
                   else bundle.objective.convert_output)
        avg_iters = num_iteration if bundle.average_output else 0

        def apply(t, x):
            out = tree_mod.predict_forest_scores(t, x)      # [bucket, K] f32
            if avg_iters:
                out = out / np.float32(avg_iters)
            if convert is not None:
                out = convert(out)
            return out

        self._fn = jax.jit(apply)

    def __call__(self, xpad: np.ndarray) -> jnp.ndarray:
        x = (jax.device_put(xpad, self._x_sharding)
             if self._x_sharding is not None else jnp.asarray(xpad))
        return self._fn(self._trees, x)


class ServingEngine:
    """Registry + predictor cache + (optional) mesh: the serve path's core.

    ``predict`` is thread-safe and synchronous; the micro-batching queue
    (serving/batching.py) sits in front of it for concurrent traffic.
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 max_batch: int = 4096, min_bucket: int = 16,
                 num_devices: int = 1,
                 metrics: Optional[ServingMetrics] = None):
        check(max_batch >= 1 and min_bucket >= 1,
              "serve_max_batch and serve_min_bucket must be >= 1")
        # normalize both to powers of two so bucket_rows' ladder is exact
        self.min_bucket = 1 << (int(min_bucket) - 1).bit_length()
        self.max_batch = max(1 << (int(max_batch) - 1).bit_length(),
                             self.min_bucket)
        self.registry = registry if registry is not None else ModelRegistry()
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.mesh = serving_mesh(num_devices) if num_devices != 1 else None
        self._cache: Dict[Tuple, _CompiledPredictor] = {}
        self._lock = threading.Lock()
        # atomic re-registration (checkpoint hot-roll): purge this model's
        # compiled predictors when its bundle is swapped
        self.registry.add_replace_listener(self._invalidate_model)

    # ------------------------------------------------------------ cache
    def _invalidate_model(self, model_id: str) -> None:
        """Drop every cache entry compiled against a replaced bundle. The
        generation in the cache key already prevents stale *hits*; this
        reclaims the dead entries' device memory."""
        with self._lock:
            for key in [k for k in self._cache if k[0] == model_id]:
                del self._cache[key]

    def _predictor(self, bundle: ModelBundle, bucket: int, raw_score: bool,
                   iters: int) -> _CompiledPredictor:
        key = (bundle.model_id, getattr(bundle, "generation", 0), bucket,
               bool(raw_score), iters)
        with self._lock:
            entry = self._cache.get(key)
            if entry is None:
                entry = _CompiledPredictor(bundle, bucket, raw_score, iters,
                                           mesh=self.mesh)
                self._cache[key] = entry
                hit = False
            else:
                hit = True
        self.metrics.record_cache(hit)
        return entry

    def cache_size(self) -> int:
        with self._lock:
            return len(self._cache)

    # ------------------------------------------------------------ predict
    def predict(self, model_id: str, X, raw_score: bool = False,
                num_iteration: Optional[int] = None,
                _record_request: bool = True) -> np.ndarray:
        """Serve one request; output matches ``Booster.predict`` (same f32
        accumulation order, same transform) for any request size.
        ``_record_request=False`` is for the micro-batch queue, which
        accounts its callers itself (per-caller count + queue-inclusive
        latency) so a fused dispatch is not double-counted."""
        t0 = time.perf_counter()
        bundle = self.registry.get(model_id)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        check(X.ndim == 2, "prediction input must be 2-D")
        if bundle.num_features:
            check(X.shape[1] == bundle.num_features,
                  "model %r expects %d features, request has %d"
                  % (model_id, bundle.num_features, X.shape[1]))
        iters = bundle.effective_iterations(num_iteration)
        n = X.shape[0]
        outs = []
        for lo in range(0, n, self.max_batch):
            xc = X[lo:lo + self.max_batch]
            b = bucket_rows(xc.shape[0], self.min_bucket, self.max_batch)
            xpad = xc
            if b != xc.shape[0]:
                xpad = np.zeros((b, X.shape[1]), np.float32)
                xpad[:xc.shape[0]] = xc
            entry = self._predictor(bundle, b, raw_score, iters)
            out = np.asarray(entry(xpad), np.float64)[:xc.shape[0]]
            self.metrics.record_batch(b)
            outs.append(out)
        out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
        if bundle.num_tree_per_iteration == 1:
            out = out[:, 0]
        if _record_request:
            self.metrics.record_request(n, time.perf_counter() - t0)
        return out

    # ------------------------------------------------------------ warmup
    def warmup(self, model_ids: Optional[Iterable[str]] = None,
               raw_scores: Iterable[bool] = (False,),
               num_iterations: Iterable[Optional[int]] = (None,),
               extract_costs: bool = False) -> int:
        """Compile every bucket for the given key prefixes so live traffic
        never compiles; returns the number of entries warmed. Marks the
        metrics recompile floor when done.

        ``extract_costs=True`` additionally runs the obs cost model over
        each warmed bucket (``predict_b<bucket>`` entries: XLA FLOPs /
        bytes per forward pass, feeding ``GET /roofline`` and bench).
        AOT extraction shares nothing with the serving executables, so it
        cannot retrace them — and it runs BEFORE the recompile floor is
        marked, so its own one-time compiles never trip the serving
        zero-recompile assertion."""
        ids = list(model_ids) if model_ids is not None else self.registry.ids()
        cm = None
        if extract_costs:
            from ..obs.costmodel import get_cost_model
            cm = get_cost_model()
        warmed = 0
        for mid in ids:
            bundle = self.registry.get(mid)
            nf = max(bundle.num_features, 1)
            for b in bucket_sizes(self.min_bucket, self.max_batch):
                zeros = np.zeros((b, nf), np.float32)
                for raw in raw_scores:
                    for ni in num_iterations:
                        iters = bundle.effective_iterations(ni)
                        entry = self._predictor(bundle, b, raw, iters)
                        # lgbm-lint: disable=LGL103 serving warmup sync
                        jax.block_until_ready(entry(zeros))
                        warmed += 1
                        if cm is not None:
                            cm.analyze(
                                "predict_b%d" % b, entry._fn,
                                jax.tree_util.tree_map(
                                    lambda a: jax.ShapeDtypeStruct(
                                        a.shape, a.dtype), entry._trees),
                                jax.ShapeDtypeStruct((b, nf), jnp.float32),
                                extra_key="model=%s;raw=%d;iters=%d"
                                % (mid, int(raw), iters))
        self.metrics.mark_warmup_done()
        return warmed
