"""Serving front-ends: HTTP (stdlib ThreadingHTTPServer) and JSON-lines stdin.

The wire layer is deliberately thin — parse JSON, hand rows to the
MicroBatchQueue, serialize the Future's result — so every interesting
property (bucketing, zero-recompile, sharding, metrics) lives in the
engine underneath and is shared by both transports and by in-process
callers (bench.py, tools/serve_smoke.py).

HTTP API:
  POST /predict   {"model": "...", "data": [[...], ...],
                   "raw_score": false, "num_iteration": null}
                  -> {"model": ..., "rows": N, "predictions": [...]}
  GET  /metrics   one ServingMetrics snapshot (docs/Serving.md schema)
  GET  /metrics/prometheus   process-wide obs registry, Prometheus text
                  exposition 0.0.4 (serving + compile + training series)
  GET  /healthz   {"status": "ok", "models": [...], "drift": "ok"|"warn"|
                   "no_profile"|"disabled"} — drift fed by the engine's
                  DriftMonitors (obs/drift.py; warn-only, never 503s)
  GET  /drift     per-model train/serve drift detail: PSI/JS per feature
                  vs the bundled training profile + the score sketch
  GET  /slo       burn-rate verdicts per declared SLO (obs/slo.py) —
                  {"slos": {...}} or {"status": "disabled"} without one
  GET  /traces    recently KEPT request traces (tail sampling) with their
                  span records — obs_trace=true only, else empty
  GET  /models    registered model ids + shapes

POST /predict honors an inbound ``x-lgbm-trace: <trace_id>[-<span_id>]``
header (obs/reqtrace.py) so fleet peers and load generators keep one
trace id across hops.

stdin mode (``serve_stdin=true``) speaks the same request objects, one JSON
object per line, replies one JSON line each — the subprocess-friendly
transport used by the CLI tests.
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..config import Config
from ..log import Log, LightGBMError, OverloadedError
from ..obs.registry import get_registry
from ..obs.reqtrace import TRACE_HEADER
from ..resilience.breaker import CircuitBreaker
from .batching import MicroBatchQueue
from .metrics import ServingMetrics
from .predictor import ServingEngine, bucket_sizes
from .registry import ModelRegistry


def _predictions_payload(model_id: str, out: np.ndarray) -> Dict:
    return {"model": model_id, "rows": int(np.asarray(out).shape[0]),
            "predictions": np.asarray(out).tolist()}


class ServingApp:
    """Engine + queue + registry bound together for a transport to drive.

    The circuit breaker sits BETWEEN validation and dispatch: client
    errors (missing data, unknown model, bad width) are classified before
    the queue and never count as failures; only dispatch failures — the
    engine itself is sick — advance the breaker. An open breaker rejects
    fast with OverloadedError carrying the Retry-After hint; transports
    map that to 503."""

    def __init__(self, engine: ServingEngine,
                 queue: Optional[MicroBatchQueue] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.engine = engine
        self.queue = queue if queue is not None else MicroBatchQueue(engine)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # fleet attachments (docs/Fleet.md), wired by build_app when the
        # matching config is set; all optional and None in the plain app
        self.tuner = None          # fleet.qos.CascadeAutotuner
        self.announcer = None      # fleet.replica.ReplicaAnnouncer
        self.coordinator = None    # fleet.replica.RollingDeployCoordinator
        self.watcher = None        # serving.registry.CheckpointWatcher
        self.cluster = None        # fleet.replica.FleetClusterProvider
        self.tracer = None         # obs.reqtrace.RequestTracer
        self.slo = None            # obs.slo.SloEngine
        self.trace_events = None   # EventStream owned by build_app
        self.queue.start()

    # ------------------------------------------------------------ requests
    def handle_predict(self, req: Dict, trace: Optional[str] = None) -> Dict:
        model_id = req.get("model", "")
        if not model_id:
            ids = self.engine.registry.ids()
            if len(ids) != 1:
                raise LightGBMError(
                    "request must name a model (registered: %s)" % ids)
            model_id = ids[0]
        data = req.get("data")
        if data is None:
            raise LightGBMError('request is missing "data"')
        # client-side validation BEFORE the breaker/queue: an unknown
        # model or wrong width is the caller's fault, not engine sickness
        self.engine.registry.get(model_id)
        X = np.asarray(data, np.float32)
        if not self.breaker.allow():
            self.engine.metrics.record_shed()
            raise OverloadedError(
                "circuit breaker open (%d consecutive dispatch failures); "
                "retry in %.1fs"
                % (self.breaker.failure_threshold,
                   self.breaker.retry_after_s()),
                retry_after_s=max(self.breaker.retry_after_s(), 0.1))
        try:
            out = self.queue.predict(
                model_id, X, raw_score=bool(req.get("raw_score", False)),
                num_iteration=req.get("num_iteration"), trace=trace)
        except OverloadedError:
            raise          # admission shed: not an engine failure
        except Exception:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return _predictions_payload(model_id, out)

    def handle_models(self) -> Dict:
        models = []
        for mid in self.engine.registry.ids():
            b = self.engine.registry.get(mid)
            models.append({"model": mid, "num_features": b.num_features,
                           "num_class": b.num_class,
                           "iterations": b.total_iterations})
        return {"models": models}

    def close(self) -> None:
        for part in (self.coordinator, self.announcer, self.tuner):
            if part is not None:
                part.stop()
        if self.watcher is not None:
            self.watcher.stop()
        if self.slo is not None:
            self.slo.stop()
        self.queue.stop()
        if self.trace_events is not None:
            self.trace_events.close()


class _Handler(BaseHTTPRequestHandler):
    app: ServingApp = None  # type: ignore[assignment]  # bound by make_server

    def log_message(self, fmt, *args):  # route through our logger, not stderr
        Log.debug("serve: " + fmt, *args)

    def _reply(self, code: int, payload: Dict,
               retry_after_s: Optional[float] = None) -> None:
        self._reply_raw(code, json.dumps(payload).encode("utf-8"),
                        "application/json", retry_after_s=retry_after_s)

    def _reply_raw(self, code: int, body: bytes, ctype: str,
                   retry_after_s: Optional[float] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if retry_after_s is not None:
            self.send_header("Retry-After",
                             str(max(int(round(retry_after_s)), 1)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        if self.path == "/healthz":
            brk = self.app.breaker.snapshot()
            code = 200 if brk["state"] != "open" else 503
            # drift is advisory: a drifted model still answers correctly
            # for its training distribution, so "warn" never turns the
            # probe 503 — it flags the refit loop, not the load balancer
            self._reply(code, {"status": "ok" if code == 200 else "degraded",
                               "models": self.app.engine.registry.ids(),
                               "drift":
                                   self.app.engine.drift_status()["status"],
                               "breaker": brk})
        elif self.path == "/stats":
            snap = self.app.engine.metrics.snapshot()
            snap["breaker"] = self.app.breaker.snapshot()
            snap["queue"] = self.app.queue.stats()
            if self.app.tuner is not None:
                snap["cascade_autotune"] = self.app.tuner.snapshot()
            if self.app.announcer is not None:
                # the full announced document, not just the name: /stats
                # is how an operator checks what THIS replica is telling
                # the fleet (snap_id, rejections, digest)
                snap["replica"] = self.app.announcer.state()
            self._reply(200, snap)
        elif self.path == "/metrics/cluster":
            # fleet federation (docs/Fleet.md): merged per-replica gauges
            # from the KV namespace; without a fleet, the local registry
            # (the single-replica degenerate case, like obs StatsServer)
            text = (self.app.cluster.cluster_prometheus()
                    if self.app.cluster is not None
                    else get_registry().prometheus_text())
            self._reply_raw(200, text.encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/stats/cluster":
            snap = (self.app.cluster.cluster_stats()
                    if self.app.cluster is not None
                    else {"fleet": {"replicas": 0, "live": 0},
                          "replicas": {}})
            self._reply(200, snap)
        elif self.path == "/metrics":
            self._reply(200, self.app.engine.metrics.snapshot())
        elif self.path == "/metrics/prometheus":
            # the whole process' registry, not just this engine's slice —
            # a scrape sees serving, compile-cache and training series
            self._reply_raw(200, get_registry().prometheus_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/drift":
            # same body as the training StatsServer's /drift: the process
            # -wide monitor registry, which this engine's lazily-created
            # monitors publish into
            from ..obs.drift import drift_snapshot
            self._reply(200, drift_snapshot())
        elif self.path == "/slo":
            # burn-rate verdicts (docs/Observability.md): ticks + evaluates
            # on demand so a scrape always sees current windows, even when
            # the background ticker period is long
            body = (self.app.slo.status() if self.app.slo is not None
                    else {"status": "disabled", "slos": {}})
            self._reply(200, body)
        elif self.path == "/traces":
            # most recent KEPT traces (tail sampling), newest last — the
            # quick "what did the slow request spend its time on" view
            body = (self.app.tracer.recent_traces()
                    if self.app.tracer is not None else [])
            self._reply(200, {"traces": body})
        elif self.path == "/models":
            self._reply(200, self.app.handle_models())
        else:
            self._reply(404, {"error": "unknown path %r" % self.path})

    def do_POST(self):  # noqa: N802 - http.server API
        if self.path != "/predict":
            self._reply(404, {"error": "unknown path %r" % self.path})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            # inbound trace context (x-lgbm-trace: <trace_id>[-<span_id>]):
            # a fleet peer or load generator continues its trace through
            # this replica; absent/malformed headers mint a fresh trace
            trace = self.headers.get(TRACE_HEADER)
            self._reply(200, self.app.handle_predict(req, trace=trace))
        except OverloadedError as e:
            # shed (bounded admission) or breaker-open: 503 + Retry-After
            self._reply(503, {"error": str(e),
                              "retry_after_s": e.retry_after_s},
                        retry_after_s=e.retry_after_s)
        except (LightGBMError, ValueError, KeyError) as e:
            self.app.engine.metrics.record_error()
            self._reply(400, {"error": str(e)})


def make_server(app: ServingApp, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bind (not yet serving) — port 0 lets the OS pick (tests read
    ``server.server_address``)."""
    handler = type("BoundHandler", (_Handler,), {"app": app})
    return ThreadingHTTPServer((host, port), handler)


def serve_stdin(app: ServingApp, in_stream=None, out_stream=None) -> int:
    """One JSON request per line in, one JSON reply per line out; blank
    line or EOF ends the session. Returns requests served."""
    import sys
    in_stream = in_stream if in_stream is not None else sys.stdin
    out_stream = out_stream if out_stream is not None else sys.stdout
    served = 0
    for line in in_stream:
        line = line.strip()
        if not line:
            break
        try:
            reply = app.handle_predict(json.loads(line))
        except OverloadedError as e:
            reply = {"error": str(e), "overloaded": True,
                     "retry_after_s": e.retry_after_s}
        except (LightGBMError, ValueError, KeyError) as e:
            app.engine.metrics.record_error()
            reply = {"error": str(e)}
        out_stream.write(json.dumps(reply) + "\n")
        out_stream.flush()
        served += 1
    return served


def _metrics_writer(metrics: ServingMetrics, path: str, freq_s: float,
                    stop: threading.Event) -> threading.Thread:
    def loop():
        while not stop.wait(max(freq_s, 0.1)):
            metrics.write_jsonl(path)
    t = threading.Thread(target=loop, name="lgbm-serve-metrics", daemon=True)
    t.start()
    return t


def build_app(config: Config) -> ServingApp:
    """Engine + queue from serve_* config; loads ``input_model`` (if any)
    under id "default" — tests/embedders register models themselves.

    Fleet wiring (docs/Fleet.md), each independently optional:
    ``serve_qos_*`` puts a QosPolicy on the queue;
    ``serve_latency_budget_ms`` starts the cascade-margin autotuner;
    ``fleet_kv_dir`` makes this process an announced replica (named
    ``fleet_replica``) and — when ``checkpoint_dir`` is also set — a
    participant in rolling deploys of that directory's snapshots."""
    if config.fault_inject:
        from ..resilience import faults
        faults.install_plan(config.fault_inject, config.fault_seed)
    engine = ServingEngine(
        max_batch=config.serve_max_batch, min_bucket=config.serve_min_bucket,
        num_devices=config.serve_num_devices,
        backend=config.serving_backend,
        cascade_trees=config.serving_cascade_trees,
        cascade_margin=config.serving_cascade_margin,
        quantize_leaves=config.serving_quantize_leaves,
        guard_hot_roll=config.serve_guard_hot_roll,
        canary_rows=config.serve_canary_rows,
        roll_max_latency_ms=config.serve_roll_max_latency_ms,
        drift=config.serve_drift,
        drift_warn_psi=config.obs_drift_warn_psi,
        drift_min_rows=config.obs_drift_min_rows,
        drift_decay=config.obs_drift_decay)
    if config.input_model:
        engine.registry.load_file("default", config.input_model)
    qos = None
    if config.serve_qos_weights or config.serve_qos_quota_rows:
        from ..fleet.qos import QosPolicy
        qos = QosPolicy.from_spec(config.serve_qos_weights,
                                  config.serve_qos_quota_rows)
    tracer = None
    trace_events = None
    if config.obs_trace:
        from ..obs.reqtrace import RequestTracer
        from ..obs.trace import EventStream
        if config.obs_event_file:
            trace_events = EventStream(
                config.obs_event_file,
                static_fields={"source": "serve",
                               "replica": config.fleet_replica or ""})
        tracer = RequestTracer(events=trace_events,
                               slow_ms=config.obs_trace_slow_ms,
                               sample=config.obs_trace_sample,
                               seed=config.seed)
    app = ServingApp(
        engine,
        MicroBatchQueue(engine, deadline_ms=config.serve_deadline_ms,
                        max_queue_rows=config.serve_max_queue_rows,
                        request_timeout_ms=config.serve_request_timeout_ms,
                        qos=qos, tracer=tracer),
        breaker=CircuitBreaker(
            failure_threshold=config.serve_breaker_failures,
            cooldown_s=config.serve_breaker_cooldown_s))
    app.tracer = tracer
    app.trace_events = trace_events
    if config.serve_slo_p99_ms > 0 or config.serve_slo_availability > 0:
        from ..obs.slo import SloEngine
        slo = SloEngine(fast_window_s=config.slo_fast_window_s,
                        slow_window_s=config.slo_slow_window_s,
                        burn_warn=config.slo_burn_warn,
                        monitor=engine._drift_health())
        if config.serve_slo_p99_ms > 0:
            slo.add_latency_slo(
                "serve_p99", "lgbm_serving_request_latency_ms",
                threshold_ms=config.serve_slo_p99_ms,
                objective=config.serve_slo_target,
                description="fraction of requests under serve_slo_p99_ms")
        if config.serve_slo_availability > 0:
            slo.add_availability_slo(
                "serve_availability", "lgbm_serving_requests_total",
                bad=["lgbm_serving_errors_total",
                     "lgbm_serving_shed_total",
                     "lgbm_serving_request_timeouts_total"],
                objective=config.serve_slo_availability,
                description="requests neither errored, shed nor expired")
        app.slo = slo.start(config.slo_tick_s)
    if config.serve_latency_budget_ms > 0:
        from ..fleet.qos import CascadeAutotuner
        app.tuner = CascadeAutotuner(
            engine, config.serve_latency_budget_ms,
            interval_s=config.serve_qos_tune_interval_s).start()
    if config.fleet_kv_dir:
        from ..fleet.replica import (FileKvClient, FleetClusterProvider,
                                     ReplicaAnnouncer,
                                     RollingDeployCoordinator)
        client = FileKvClient(config.fleet_kv_dir)
        replica = config.fleet_replica or ("replica-%d" % os.getpid())
        if config.checkpoint_dir:
            # the watcher is DRIVEN by the coordinator (one replica rolls
            # at a time); its own poll thread stays off
            app.watcher = engine.registry.watch_dir(
                "default", config.checkpoint_dir, engine=engine)
        app.announcer = ReplicaAnnouncer(
            client, replica, engine=engine, watcher=app.watcher,
            period_s=config.fleet_announce_period_s).start()
        if app.watcher is not None:
            app.coordinator = RollingDeployCoordinator(
                client, app.announcer, app.watcher).start()
        app.cluster = FleetClusterProvider(client)
    return app


def run_server(config: Config, params: Optional[Dict] = None) -> int:
    """cli.py task=serve entry: boot, warm every bucket, serve until EOF
    (stdin mode) or interrupt (HTTP mode)."""
    if not config.input_model:
        raise LightGBMError("No model file: pass input_model=<file>")
    app = build_app(config)
    engine = app.engine
    if config.serve_warmup:
        warmed = engine.warmup()
        Log.info("serve: warmed %d compiled predictors (buckets %s)",
                 warmed, ",".join(str(b) for b in
                                  bucket_sizes(engine.min_bucket,
                                               engine.max_batch)))
    stop = threading.Event()
    if config.serve_metrics_file:
        _metrics_writer(engine.metrics, config.serve_metrics_file,
                        config.serve_metrics_freq, stop)
    try:
        if config.serve_stdin:
            served = serve_stdin(app)
            Log.info("serve: stdin session done, %d requests", served)
            return 0
        server = make_server(app, config.serve_host, config.serve_port)
        Log.info("serve: listening on http://%s:%d (pid %d)",
                 server.server_address[0], server.server_address[1],
                 os.getpid())
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            Log.info("serve: interrupted, shutting down")
        finally:
            server.server_close()
        return 0
    finally:
        stop.set()
        if config.serve_metrics_file:
            engine.metrics.write_jsonl(config.serve_metrics_file)
        app.close()
