"""Serving-specialized SoA ensemble traversal: O(depth) steps, all trees at once.

The training-side replay path (core/tree.py) moves rows through a tree by
replaying its ``num_leaves - 1`` splits in creation order and sequences
trees through ``lax.scan`` — ~254 steps per 255-leaf tree, no cross-tree
vectorization. That is the right shape for training (it mirrors how
DataPartition evolves) but the wrong one for serving, where the model is
frozen and every microsecond of batch latency counts.

Here the whole ensemble is packed ONCE per model generation into a single
structure-of-arrays node table (``FlatForest``: ``[T, max_nodes]`` split
feature / threshold / default-left / missing-type / child pointers plus a
``[T, max_leaves]`` leaf-value table, ``T`` = iterations x classes), the
flattened node-array layout TF Boosted Trees and Booster serve from. All
rows x all trees then advance level-by-level: each of the ``depth`` fused
steps gathers the current node's fields for every (row, tree) pair, makes
the split decision (core/tree.py ``decision_go_left`` — the SAME routing
math as replay, so outputs are bit-identical), and follows a child
pointer. Leaves are encoded ``~leaf_index`` (negative) in the child
arrays, exactly the HostTree/LoadedTree on-disk convention, so landing on
a leaf freezes the row: ``depth`` steps suffice for every row and the
loop bound is a static property of the packed model.

Per-class summation replays iteration order through a sequential
``lax.scan`` — the identical f32 add order as ``predict_forest_scores``
— so serving outputs match ``Booster.predict`` bit-for-bit, not just to
tolerance.

Early-exit cascades (``serving_cascade_trees=k`` /
``serving_cascade_margin=m``): score the first ``k`` iterations for
everyone, then only continue through the remaining trees when some row's
margin (binary: ``2*|score|``; multiclass: top1-top2) is below ``m``.
The whole second stage sits under one ``lax.cond``, so a confident batch
skips it entirely on device; ``m = inf`` keeps every row uncertain and
reproduces the full-model output exactly (the parity test for the knob).

Optionally the leaf table is quantized to int16 with a per-tree f32
scale (``serving_quantize_leaves``) — halves leaf-table bandwidth at
~1e-4 relative output error, OFF by default to preserve exact parity.
"""
from __future__ import annotations

from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.tree import decision_go_left
from ..log import check


class FlatForest(NamedTuple):
    """Whole-ensemble SoA node table; every field's leading axis is the
    flattened tree index ``T = iterations * num_tree_per_iteration``
    (iteration-major, matching the stacked replay layout)."""
    feature: jnp.ndarray        # [T, Nn] int32 split feature per node
    threshold: jnp.ndarray      # [T, Nn] f32 real-value threshold
    default_left: jnp.ndarray   # [T, Nn] bool
    missing_type: jnp.ndarray   # [T, Nn] int32
    is_categorical: jnp.ndarray  # [T, Nn] bool
    cat_bitset: jnp.ndarray     # [T, Nn, W] uint32 raw-category bitsets
    left: jnp.ndarray           # [T, Nn] int32 child; >=0 node, <0 = ~leaf
    right: jnp.ndarray          # [T, Nn] int32 child; >=0 node, <0 = ~leaf
    leaf_value: jnp.ndarray     # [T, L] f32 (or int16 when quantized)
    leaf_scale: jnp.ndarray     # [T] f32 dequant scale (ones unless quantized)


def _tree_depth(left: np.ndarray, right: np.ndarray) -> int:
    """Traversal steps needed for one tree: the max count of internal
    nodes on any root-to-leaf path (>= 1; a stump still takes one step to
    follow ``~0`` to leaf 0). Iterative — trees can be chain-shaped."""
    # NOTE: only a truly empty tree short-circuits. A root whose LEFT
    # child is a leaf is NOT a stump — its right subtree can be
    # arbitrarily deep (sparse-trained chain trees look exactly like
    # this), and under-counting depth freezes traversal mid-tree.
    if len(left) == 0:
        return 1
    depth = 1
    stack: List[Tuple[int, int]] = [(0, 1)]
    while stack:
        node, d = stack.pop()
        depth = max(depth, d)
        for child in (int(left[node]), int(right[node])):
            if child >= 0:
                stack.append((child, d + 1))
    return depth


def pack_flat_forest(models, quantize: bool = False
                     ) -> Tuple[FlatForest, int]:
    """Pack host trees (boosting.gbdt.HostTree / io.model_text.LoadedTree,
    iteration-major) into one numpy ``FlatForest`` plus the static
    traversal depth. Runs once per model generation on host; callers
    device-put the result."""
    check(len(models) > 0, "cannot pack an empty model")
    max_nodes = max(max(t.num_nodes, 1) for t in models)
    max_leaves = max(t.num_leaves for t in models)
    cat_words = max(t.cat_bitset.shape[1] for t in models)
    tcount = len(models)

    feature = np.zeros((tcount, max_nodes), np.int32)
    threshold = np.zeros((tcount, max_nodes), np.float32)
    default_left = np.zeros((tcount, max_nodes), bool)
    missing_type = np.zeros((tcount, max_nodes), np.int32)
    is_categorical = np.zeros((tcount, max_nodes), bool)
    cat_bitset = np.zeros((tcount, max_nodes, cat_words), np.uint32)
    # padding children point at leaf 0 (~0 == -1): a row that somehow
    # lands on a padded node freezes on a real leaf instead of escaping
    left = np.full((tcount, max_nodes), -1, np.int32)
    right = np.full((tcount, max_nodes), -1, np.int32)
    leaf_f32 = np.zeros((tcount, max_leaves), np.float32)
    depth = 1
    for ti, ht in enumerate(models):
        nn = len(ht.left_child)
        feature[ti, :nn] = ht.split_feature
        threshold[ti, :nn] = ht.threshold.astype(np.float32)
        default_left[ti, :nn] = ht.default_left
        missing_type[ti, :nn] = ht.missing_type
        is_categorical[ti, :nn] = ht.is_categorical
        bw = ht.cat_bitset.shape[1]
        cat_bitset[ti, :len(ht.cat_bitset), :bw] = ht.cat_bitset
        left[ti, :nn] = ht.left_child
        right[ti, :nn] = ht.right_child
        nl = len(ht.leaf_value)
        leaf_f32[ti, :nl] = ht.leaf_value.astype(np.float32)
        depth = max(depth, _tree_depth(ht.left_child, ht.right_child))

    if quantize:
        scale = np.maximum(np.abs(leaf_f32).max(axis=1), 1e-30) / 32767.0
        leaf = np.round(leaf_f32 / scale[:, None]).astype(np.int16)
        leaf_scale = scale.astype(np.float32)
    else:
        leaf = leaf_f32
        leaf_scale = np.ones((tcount,), np.float32)

    return FlatForest(feature=feature, threshold=threshold,
                      default_left=default_left, missing_type=missing_type,
                      is_categorical=is_categorical, cat_bitset=cat_bitset,
                      left=left, right=right, leaf_value=leaf,
                      leaf_scale=leaf_scale), depth


def _terminal_nodes(forest: FlatForest, x: jnp.ndarray,
                    depth: int) -> jnp.ndarray:
    """[N, T] terminal encoded nodes (``~leaf_index``, all negative after
    ``depth`` steps): all rows x all trees, breadth-first gather + decide
    + follow-child."""
    n = x.shape[0]
    tcount = forest.left.shape[0]
    tr = jnp.arange(tcount, dtype=jnp.int32)[None, :]        # [1, T]
    max_cat = forest.cat_bitset.shape[-1] * 32

    def step(_, node):
        internal = node >= 0
        idx = jnp.maximum(node, 0)                           # [N, T]
        feat = forest.feature[tr, idx]
        fval = jnp.take_along_axis(x, feat, axis=1)          # [N, T]
        bits = forest.cat_bitset[tr, idx]                    # [N, T, W]
        go_left = decision_go_left(
            fval, forest.threshold[tr, idx], forest.default_left[tr, idx],
            forest.missing_type[tr, idx], forest.is_categorical[tr, idx],
            lambda wi: jnp.take_along_axis(bits, wi[..., None],
                                           axis=2)[..., 0],
            max_cat)
        nxt = jnp.where(go_left, forest.left[tr, idx], forest.right[tr, idx])
        return jnp.where(internal, nxt, node)

    return lax.fori_loop(0, depth, step,
                         jnp.zeros((n, tcount), jnp.int32))


def forest_leaf_ids(forest: FlatForest, x: jnp.ndarray,
                    depth: int) -> jnp.ndarray:
    """[N, T] int32 leaf index each row lands on in each tree — the
    routing half of the traversal without the leaf-table gather. This is
    the refit primitive (fleet/refit.py): leaf ids feed per-leaf
    segment-sums of gradients, so leaf OUTPUTS can be recomputed on fresh
    data while the structure that produced the ids stays frozen."""
    return ~_terminal_nodes(forest, x, depth)


def _leaf_values(forest: FlatForest, x: jnp.ndarray,
                 depth: int) -> jnp.ndarray:
    """[N, T] per-tree leaf values: all rows x all trees, ``depth``
    breadth-first steps of gather + decide + follow-child."""
    tcount = forest.left.shape[0]
    tr = jnp.arange(tcount, dtype=jnp.int32)[None, :]        # [1, T]
    node = _terminal_nodes(forest, x, depth)
    vals = forest.leaf_value[tr, ~node]                      # [N, T]
    if forest.leaf_value.dtype != jnp.float32:               # quantized table
        vals = vals.astype(jnp.float32) * forest.leaf_scale[None, :]
    return vals


def _sum_iterations(acc: jnp.ndarray, vals: jnp.ndarray,
                    k: int) -> jnp.ndarray:
    """Accumulate [N, T'] per-tree values into [N, K] scores, one
    iteration per scan step — the identical f32 add order as
    ``predict_forest_scores`` (bit-exact parity with Booster.predict)."""
    n = vals.shape[0]
    per_iter = vals.reshape(n, vals.shape[1] // k, k)

    def body(carry, v):                                      # v [N, K]
        return carry + v, None

    out, _ = lax.scan(body, acc, jnp.transpose(per_iter, (1, 0, 2)))
    return out


def _slice_trees(forest: FlatForest, lo: int, hi: int) -> FlatForest:
    return jax.tree.map(lambda a: a[lo:hi], forest)


def forest_scores_flat(forest: FlatForest, x: jnp.ndarray, k: int,
                       depth: int, cascade_trees: int = 0,
                       cascade_margin: float = 10.0) -> jnp.ndarray:
    """[N, K] raw ensemble scores from a packed ``FlatForest``.

    ``k`` is trees-per-iteration, ``depth`` the static bound from
    ``pack_flat_forest``. ``cascade_trees > 0`` enables the two-stage
    early-exit cascade; with ``cascade_trees == 0`` (or covering the
    whole model) this is a single traversal + per-iteration sum.
    """
    tcount = forest.left.shape[0]
    ck = min(max(int(cascade_trees), 0), tcount // k) * k
    acc_shape = (x.shape[0], k)
    if ck <= 0 or ck >= tcount:
        return _sum_iterations(
            jnp.zeros(acc_shape, jnp.float32),
            _leaf_values(forest, x, depth), k)

    acc1 = _sum_iterations(
        jnp.zeros(acc_shape, jnp.float32),
        _leaf_values(_slice_trees(forest, 0, ck), x, depth), k)
    if k > 1:
        top2 = lax.top_k(acc1, 2)[0]
        margin = top2[:, 0] - top2[:, 1]
    else:
        margin = 2.0 * jnp.abs(acc1[:, 0])
    uncertain = margin < jnp.float32(cascade_margin)

    def stage2(acc):
        vals = _leaf_values(_slice_trees(forest, ck, tcount), x, depth)
        full = _sum_iterations(acc, vals, k)
        return jnp.where(uncertain[:, None], full, acc)

    return lax.cond(jnp.any(uncertain), stage2, lambda acc: acc, acc1)
