"""``python -m lightgbm_tpu.serving input_model=model.txt [key=value ...]``

Same key=value argument convention as the main CLI; task is forced to
serve. See docs/Serving.md for the serve_* parameters.
"""
from __future__ import annotations

import sys


def main(argv=None) -> int:
    from ..cli import main as cli_main
    argv = sys.argv[1:] if argv is None else argv
    return cli_main(["task=serve"] + list(argv))


if __name__ == "__main__":
    sys.exit(main())
