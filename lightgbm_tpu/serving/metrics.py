"""Serving metrics: latency quantiles, queue depth, cache and compile counts.

Two sources of truth for "did we recompile":

- the predictor cache's own miss counter (every miss creates + compiles a
  new bucketed predictor), and
- a process-wide XLA backend-compile hook riding jax.monitoring's
  ``/jax/core/compile/backend_compile_duration`` event — this counts REAL
  backend compilations, so it also catches accidental retraces inside an
  already-cached predictor (shape leaks, weak-type flips) that the cache
  key cannot see.

Snapshots export as JSON (one object) or JSON-lines (append per snapshot),
the schema documented in docs/Serving.md.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Dict, Optional

# the hook itself lives in profiling (training's zero-recompile invariant
# and the persistent-cache counters share it); re-exported here because
# serving callers (serve_smoke, tests) learned these names first
from ..profiling import (backend_compile_count,  # noqa: F401
                         install_compile_hook, latency_summary)


class ServingMetrics:
    """Aggregated serving counters + a bounded latency window."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._t0 = time.time()
        self.requests = 0
        self.rows = 0
        self.batches = 0                 # padded forward passes dispatched
        self.cache_hits = 0
        self.cache_misses = 0            # == predictor compiles requested
        self.errors = 0
        self.queue_depth = 0             # gauge, updated by the batch queue
        self._latency_ms = collections.deque(maxlen=window)
        self._batch_rows = collections.deque(maxlen=window)
        self._compile_floor = 0          # backend compiles at warmup end
        self._miss_floor = 0             # cache misses at warmup end
        install_compile_hook()

    # ------------------------------------------------------------ recording
    def record_request(self, rows: int, latency_s: float) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows
            self._latency_ms.append(latency_s * 1000.0)

    def record_batch(self, rows: int) -> None:
        with self._lock:
            self.batches += 1
            self._batch_rows.append(rows)

    def record_cache(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    def mark_warmup_done(self) -> None:
        """Anchor the recompile counter: compiles past this point are
        recompiles (the serve_smoke.py zero-recompile assertion)."""
        with self._lock:
            self._compile_floor = backend_compile_count()
            self._miss_floor = self.cache_misses

    def recompiles_after_warmup(self) -> int:
        with self._lock:
            return backend_compile_count() - self._compile_floor

    def cache_misses_after_warmup(self) -> int:
        with self._lock:
            return self.cache_misses - self._miss_floor

    # ------------------------------------------------------------ export
    def snapshot(self) -> Dict:
        with self._lock:
            lat = latency_summary(self._latency_ms)
            rows_per_batch = (float(sum(self._batch_rows))
                              / max(len(self._batch_rows), 1))
            return {
                "ts": round(time.time(), 3),
                "uptime_s": round(time.time() - self._t0, 3),
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "rows_per_batch": round(rows_per_batch, 2),
                "queue_depth": self.queue_depth,
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
                "errors": self.errors,
                "backend_compiles": backend_compile_count(),
                "recompiles_after_warmup":
                    backend_compile_count() - self._compile_floor,
                "latency_ms": lat,
            }

    def write_jsonl(self, path_or_fh) -> Dict:
        """Append one snapshot as a JSON line; returns the snapshot."""
        snap = self.snapshot()
        line = json.dumps(snap, sort_keys=True) + "\n"
        if hasattr(path_or_fh, "write"):
            path_or_fh.write(line)
            path_or_fh.flush()
        else:
            with open(path_or_fh, "a") as fh:
                fh.write(line)
        return snap
