"""Serving metrics: latency quantiles, queue depth, cache and compile counts.

Backed by the process-wide observability registry
(``lightgbm_tpu.obs.registry``): every ``ServingMetrics`` instance owns a
labelled slice (``sink="serving-N"``) of shared ``lgbm_serving_*`` series,
so the Prometheus exposition (serving ``/metrics/prometheus``, training
stats endpoint) and this class's JSON snapshots read the SAME counters —
no second bookkeeping path.  The public API and snapshot schema are
unchanged from the pre-registry version (docs/Serving.md); request
latency is exposed as a Prometheus HISTOGRAM
(``lgbm_serving_request_latency_ms_bucket``) so multi-process scrapes
can aggregate it, while the JSON snapshot's p50/p90/p99 view stays.

Two sources of truth for "did we recompile":

- the predictor cache's own miss counter (every miss creates + compiles a
  new bucketed predictor), and
- a process-wide XLA backend-compile hook riding jax.monitoring's
  ``/jax/core/compile/backend_compile_duration`` event — this counts REAL
  backend compilations, so it also catches accidental retraces inside an
  already-cached predictor (shape leaks, weak-type flips) that the cache
  key cannot see.

Snapshots export as JSON (one object) or JSON-lines (append per snapshot),
the schema documented in docs/Serving.md.
"""
from __future__ import annotations

import collections
import itertools
import json
import threading
import time
from typing import Dict

from ..obs.registry import get_registry
# the hook itself lives in profiling (training's zero-recompile invariant
# and the persistent-cache counters share it); re-exported here because
# serving callers (serve_smoke, tests) learned these names first
from ..profiling import (backend_compile_count,  # noqa: F401
                         install_compile_hook, latency_summary)

_sink_seq = itertools.count()


class ServingMetrics:
    """Aggregated serving counters + a bounded latency window."""

    # sub-ms to multi-second: wide enough for a padded-batch compile-warm
    # predict (sub-ms..ms) and a queue-inclusive cold request (seconds)
    LATENCY_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                          250.0, 500.0, 1000.0, 2500.0, 5000.0)
    # device predict latency per shape bucket: finer at the low end —
    # a warm traversal pass is sub-ms on accelerator, low-ms on CPU
    PREDICT_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                          50.0, 100.0, 250.0, 1000.0)

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._t0 = time.time()
        reg = get_registry()
        # per-instance label: each engine/test gets independent series
        # while one scrape of the global registry still sees them all
        lbl = {"sink": "serving-%d" % next(_sink_seq)}
        self._lbl = dict(lbl)
        self._c_requests = reg.counter(
            "lgbm_serving_requests_total", "Prediction requests served.",
            labels=lbl)
        self._c_rows = reg.counter(
            "lgbm_serving_rows_total", "Prediction rows served.", labels=lbl)
        self._c_batches = reg.counter(
            "lgbm_serving_batches_total",
            "Padded forward passes dispatched.", labels=lbl)
        self._c_cache_hits = reg.counter(
            "lgbm_serving_predictor_cache_hits_total",
            "Compiled-predictor cache hits.", labels=lbl)
        self._c_cache_misses = reg.counter(
            "lgbm_serving_predictor_cache_misses_total",
            "Compiled-predictor cache misses (== compiles requested).",
            labels=lbl)
        self._c_errors = reg.counter(
            "lgbm_serving_errors_total", "Failed requests.", labels=lbl)
        self._g_queue = reg.gauge(
            "lgbm_serving_queue_depth",
            "Micro-batch queue depth in REQUESTS (gauge, set by the batch "
            "queue).", labels=lbl)
        # queue depth in ROWS: dispatch sizing and the admission bound
        # (serve_max_queue_rows) are row-based; a queue of 3 requests can
        # be 3 rows or 12288 — report both
        self._g_queue_rows = reg.gauge(
            "lgbm_serve_queue_rows",
            "Micro-batch queue depth in ROWS (gauge; the admission bound "
            "serve_max_queue_rows applies to this).", labels=lbl)
        self._c_shed = reg.counter(
            "lgbm_serving_shed_total",
            "Requests shed by bounded admission or open circuit breaker.",
            labels=lbl)
        self._c_timeouts = reg.counter(
            "lgbm_serving_request_timeouts_total",
            "Requests expired past their per-request deadline before "
            "dispatch.", labels=lbl)
        self._c_rollbacks = reg.counter(
            "lgbm_serving_rollbacks_total",
            "Hot-rolls refused by canary validation (prior generation "
            "kept live).", labels=lbl)
        # request latency is a HISTOGRAM (cumulative le-buckets), not a
        # summary: bucket counts aggregate across serving processes and
        # scrape intervals, which windowed quantiles cannot — Summary
        # stays the right tool for in-process span timings.  The JSON
        # snapshot keeps its p50/p90/p99 schema from a local window.
        self._h_latency = reg.histogram(
            "lgbm_serving_request_latency_ms",
            "Request latency (milliseconds, queue-inclusive for batched "
            "callers).", labels=lbl, buckets=self.LATENCY_BUCKETS_MS)
        self._lat_window = collections.deque(maxlen=window)
        self._batch_rows = collections.deque(maxlen=window)
        self._bucket_hist: Dict[int, object] = {}   # bucket -> Histogram
        self._compile_floor = 0          # backend compiles at warmup end
        self._miss_floor = 0             # cache misses at warmup end
        self._warmup_credit_compiles = 0  # hot-roll prewarm compiles
        self._warmup_credit_misses = 0
        install_compile_hook()

    # ------------------------------------------------------------ views
    # historical attribute API, now reading the registry-backed series
    @property
    def requests(self) -> int:
        return int(self._c_requests.value)

    @property
    def rows(self) -> int:
        return int(self._c_rows.value)

    @property
    def batches(self) -> int:
        return int(self._c_batches.value)

    @property
    def cache_hits(self) -> int:
        return int(self._c_cache_hits.value)

    @property
    def cache_misses(self) -> int:
        return int(self._c_cache_misses.value)

    @property
    def errors(self) -> int:
        return int(self._c_errors.value)

    @property
    def queue_depth(self) -> int:
        return int(self._g_queue.value)

    @property
    def queue_rows(self) -> int:
        return int(self._g_queue_rows.value)

    @property
    def shed(self) -> int:
        return int(self._c_shed.value)

    @property
    def request_timeouts(self) -> int:
        return int(self._c_timeouts.value)

    @property
    def rollbacks(self) -> int:
        return int(self._c_rollbacks.value)

    # ------------------------------------------------------------ recording
    def record_request(self, rows: int, latency_s: float) -> None:
        self._c_requests.inc()
        self._c_rows.inc(rows)
        ms = latency_s * 1000.0
        self._h_latency.observe(ms)
        with self._lock:
            self._lat_window.append(ms)

    def record_batch(self, rows: int) -> None:
        self._c_batches.inc()
        with self._lock:
            self._batch_rows.append(rows)

    def record_bucket_latency(self, bucket: int, ms: float) -> None:
        """Device predict latency for one padded forward pass, keyed by
        its shape bucket (``lgbm_serving_predict_latency_ms`` histogram
        with a ``bucket`` label; the per-bucket p50/p99 view bench.py
        reports rides ``bucket_latency()``)."""
        with self._lock:
            h = self._bucket_hist.get(bucket)
            if h is None:
                lbl = dict(self._lbl)
                lbl["bucket"] = str(int(bucket))
                h = get_registry().histogram(
                    "lgbm_serving_predict_latency_ms",
                    "Device predict latency per shape bucket "
                    "(milliseconds, padded forward pass only).",
                    labels=lbl, buckets=self.PREDICT_BUCKETS_MS)
                self._bucket_hist[bucket] = h
        h.observe(ms)

    def bucket_latency(self) -> Dict[str, Dict[str, float]]:
        """``{bucket: {count, p50_ms, p99_ms}}`` estimated from the
        per-bucket histogram counts (obs Histogram.quantile)."""
        with self._lock:
            hists = sorted(self._bucket_hist.items())
        return {str(b): {"count": int(h.count),
                         "p50_ms": round(h.quantile(0.5), 4),
                         "p99_ms": round(h.quantile(0.99), 4)}
                for b, h in hists}

    def record_cache(self, hit: bool) -> None:
        (self._c_cache_hits if hit else self._c_cache_misses).inc()

    def record_error(self) -> None:
        self._c_errors.inc()

    def set_queue_depth(self, depth: int) -> None:
        self._g_queue.set(depth)

    def set_queue_rows(self, rows: int) -> None:
        self._g_queue_rows.set(rows)

    def record_shed(self) -> None:
        self._c_shed.inc()

    def record_timeout(self) -> None:
        self._c_timeouts.inc()

    def record_rollback(self) -> None:
        self._c_rollbacks.inc()

    def mark_warmup_done(self) -> None:
        """Anchor the recompile counter: compiles past this point are
        recompiles (the serve_smoke.py zero-recompile assertion)."""
        with self._lock:
            self._compile_floor = backend_compile_count()
            self._miss_floor = self.cache_misses
            self._warmup_credit_compiles = 0
            self._warmup_credit_misses = 0

    def add_warmup_credit(self, compiles: int, misses: int) -> None:
        """Raise the recompile/miss floors for compilations a hot-roll
        prewarm paid OFF the request path (ServingEngine.prewarm_bundle):
        they are warmup work for the next model generation, not serving
        recompiles. Tracked separately so snapshots show how much credit
        was granted."""
        with self._lock:
            self._compile_floor += int(compiles)
            self._miss_floor += int(misses)
            self._warmup_credit_compiles += int(compiles)
            self._warmup_credit_misses += int(misses)

    def recompiles_after_warmup(self) -> int:
        with self._lock:
            return backend_compile_count() - self._compile_floor

    def cache_misses_after_warmup(self) -> int:
        with self._lock:
            return self.cache_misses - self._miss_floor

    # ------------------------------------------------------------ export
    def snapshot(self) -> Dict:
        by_bucket = self.bucket_latency()
        # copy the windows under the lock; the percentile math, dict
        # build and (at the caller) JSON serialization all run OUTSIDE
        # it — record_request() on the hot path must never wait on a
        # stats scrape (tests/test_obs_export.py pins the interleaving)
        with self._lock:
            lat_window = list(self._lat_window)
            batch_rows = list(self._batch_rows)
            compile_floor = self._compile_floor
            credit_compiles = self._warmup_credit_compiles
            credit_misses = self._warmup_credit_misses
        lat = latency_summary(lat_window)
        rows_per_batch = float(sum(batch_rows)) / max(len(batch_rows), 1)
        return {
            "ts": round(time.time(), 3),
            "uptime_s": round(time.time() - self._t0, 3),
            "requests": self.requests,
            "rows": self.rows,
            "batches": self.batches,
            "rows_per_batch": round(rows_per_batch, 2),
            "queue_depth": self.queue_depth,
            "queue_rows": self.queue_rows,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "errors": self.errors,
            "shed": self.shed,
            "request_timeouts": self.request_timeouts,
            "rollbacks": self.rollbacks,
            "backend_compiles": backend_compile_count(),
            "recompiles_after_warmup":
                backend_compile_count() - compile_floor,
            "warmup_credit_compiles": credit_compiles,
            "warmup_credit_misses": credit_misses,
            "latency_ms": lat,
            "predict_latency_ms_by_bucket": by_bucket,
            }

    def write_jsonl(self, path_or_fh) -> Dict:
        """Append one snapshot as a JSON line; returns the snapshot."""
        snap = self.snapshot()
        line = json.dumps(snap, sort_keys=True) + "\n"
        if hasattr(path_or_fh, "write"):
            path_or_fh.write(line)
            path_or_fh.flush()
        else:
            with open(path_or_fh, "a") as fh:
                fh.write(line)
        return snap
