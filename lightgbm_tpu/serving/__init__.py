"""lightgbm_tpu.serving — compiled, shape-bucketed batch inference.

The training side of this repo grows trees; this package serves them under
heavy traffic without ever recompiling after warmup:

- registry.py   model files -> immutable device-resident tree bundles
- traversal.py  SoA flattened-ensemble traversal (the default hot path)
- predictor.py  compiled-predictor cache, power-of-two batch bucketing
- batching.py   deadline-bounded micro-batch coalescing queue
- server.py     HTTP / stdin front-ends (cli.py task=serve)
- metrics.py    latency quantiles, cache + XLA-recompile counters

Entry points: ``python -m lightgbm_tpu.serving input_model=model.txt`` or
``python -m lightgbm_tpu task=serve input_model=model.txt``; in-process,
build a ServingEngine and register boosters directly (see docs/Serving.md).
"""
from .batching import MicroBatchQueue
from .metrics import ServingMetrics, backend_compile_count, install_compile_hook
from .predictor import ServingEngine, bucket_rows, bucket_sizes
from .registry import CheckpointWatcher, ModelBundle, ModelRegistry
from .server import ServingApp, build_app, make_server, run_server, serve_stdin
from .traversal import FlatForest, forest_scores_flat, pack_flat_forest

__all__ = [
    "CheckpointWatcher", "FlatForest", "MicroBatchQueue", "ModelBundle",
    "ModelRegistry", "ServingApp", "ServingEngine", "ServingMetrics",
    "backend_compile_count", "bucket_rows", "bucket_sizes", "build_app",
    "forest_scores_flat", "install_compile_hook", "make_server",
    "pack_flat_forest", "run_server", "serve_stdin",
]
