"""Power-of-two shape bucketing shared by serving and training.

XLA compiles one executable per input shape, so any dimension that varies
at runtime must be snapped to a small ladder of compile-time sizes or the
process retraces forever. Serving learned this first (serving/predictor.py
pads request rows to a pow-2 bucket); frontier growth
(core/grow_frontier.py) has the same problem in the NODE dimension — wave
``w`` has at most ``min(2^w, leaf budget)`` live splits, but a fixed-width
wave pays ``num_leaves - 1`` slot-sweeps regardless. Both now share this
module: the ladder is the warmup schedule, the bucket function is the
dispatch key, and ``log2(cap) + 1`` specializations bound the compile
count.
"""
from __future__ import annotations

from typing import List, Optional


def pow2_bucket(n: int, min_bucket: int = 1,
                cap: Optional[int] = None) -> int:
    """Smallest power-of-two multiple of ``min_bucket`` that covers ``n``
    (doubling from ``min_bucket``), clamped to ``cap`` when given. The
    serving row-pad and the frontier wave width both key on this."""
    b = max(int(min_bucket), 1)
    n = int(n)
    while b < n:
        b <<= 1
    return b if cap is None else min(b, int(cap))


def pow2_ladder(min_bucket: int, cap: int) -> List[int]:
    """Every bucket ``pow2_bucket`` can return for sizes in [1, cap] — the
    warmup schedule. Doubles from ``min_bucket`` and always ends exactly at
    ``cap`` (which need not be a power of two)."""
    out: List[int] = []
    b = max(int(min_bucket), 1)
    cap = int(cap)
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(cap)
    return out


def frontier_max_width(num_leaves: int, max_depth: int = -1) -> int:
    """Largest possible frontier wave: ``num_leaves - 1`` (every remaining
    split may land in one wave), clamped by ``max_depth`` — a depth-``d``
    tree's frontier never exceeds ``2^(d-1)`` leaves, because wave ``w``
    splits only depth-``w`` leaves and depth-capped children are never
    granted positive gain (grow_batched.apply_split_wave)."""
    kb = max(int(num_leaves) - 1, 1)
    if max_depth is not None and int(max_depth) > 0:
        kb = min(kb, 1 << (int(max_depth) - 1))
    return kb


def wave_width_ladder(num_leaves: int, max_depth: int = -1) -> List[int]:
    """The frontier grower's bucket ladder: pow-2 widths up to the clamped
    maximum wave width. One wave-step specialization exists per entry."""
    return pow2_ladder(1, frontier_max_width(num_leaves, max_depth))


def wave_width_bucket(live: int, num_leaves: int,
                      max_depth: int = -1) -> int:
    """Bucketed width a wave with ``live`` positive-gain leaves runs at —
    the host-side mirror of the grower's ``lax.switch`` branch selection,
    used by profiling/bench occupancy accounting."""
    return pow2_bucket(max(int(live), 1), 1,
                       frontier_max_width(num_leaves, max_depth))
