"""Device mesh construction + sharding helpers.

The reference's distribution model (tree_learner=serial/feature/data/voting ×
num_machines, config.h:177,748) maps onto a jax.sharding.Mesh:

- ``data`` axis: rows sharded (DataParallelTreeLearner analog). The exact
  grower psums histograms under an explicit shard_map; the frontier grower
  selects its wave-collective schedule from ``parallel/learners.py`` —
  full psum (serial schedule), tiled reduce-scatter + best-record election
  (``tree_learner=data``, data_parallel_tree_learner.cpp:146-161), or the
  PV-Tree vote (``tree_learner=voting``).
- ``feature`` axis: feature columns sharded (FeatureParallelTreeLearner
  analog); per-feature split search shards naturally, the global argmax is
  the SyncUpGlobalBestSplit (parallel_tree_learner.h:186) analog.
- voting-parallel uses the explicit shard_map path (learners.py
  VotingLearner) because its comm compression (top-k vote, then reduce only
  elected features, voting_parallel_tree_learner.cpp:166-360) is a manual
  optimization GSPMD cannot infer.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..log import Log, LightGBMError

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


_warned_fallback = False


def _warn_serial_fallback(reason: str) -> None:
    """One-time loud notice that a parallel tree_learner is running the
    serial schedule — a silent fallback here cost users real scaling runs
    (the config LOOKS distributed but every collective is a no-op)."""
    global _warned_fallback
    if not _warned_fallback:
        _warned_fallback = True
        Log.warning("tree_learner falls back to serial: " + reason)


def build_mesh(config: Config, devices=None) -> Optional[Mesh]:
    """Build the training mesh from config (mesh_shape / tree_learner).

    Returns None for single-device serial training (the common case on one
    chip) — everything then runs unsharded. When a parallel tree_learner
    was requested but no mesh can be built, the fallback is announced once
    via Log.warning (never silently).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if config.mesh_shape:
        shape = tuple(int(s) for s in config.mesh_shape)
        total = int(np.prod(shape))
        if total > n:
            raise LightGBMError(
                "mesh_shape %s needs %d devices, only %d available"
                % (shape, total, n))
        devs = np.asarray(devices[:total])
        if len(shape) == 1:
            axis = (FEATURE_AXIS if config.tree_learner == "feature"
                    else DATA_AXIS)
            return Mesh(devs.reshape(shape), (axis,))
        return Mesh(devs.reshape(shape), (DATA_AXIS, FEATURE_AXIS))
    if config.tree_learner != "serial" and n > 1:
        axis = (FEATURE_AXIS if config.tree_learner == "feature"
                else DATA_AXIS)
        return Mesh(np.asarray(devices), (axis,))
    if config.tree_learner != "serial":
        _warn_serial_fallback(
            "tree_learner=%s requested but only %d device is visible and "
            "no mesh_shape was given (single-process runs need "
            "mesh_shape=[P] over virtual/local devices; multi-process runs "
            "need num_machines>1 with machines/local_listen_port so "
            "jax.distributed exposes every process's devices)"
            % (config.tree_learner, n))
    return None


def serving_mesh(num_devices: int = 0, devices=None) -> Optional[Mesh]:
    """1-D data mesh for the serving path (lightgbm_tpu.serving): padded
    request batches are row-sharded over the data axis, trees replicated —
    the inference analog of the data-parallel training layout above.

    ``num_devices`` 0 means all local devices; a single device (or a
    single-device request) returns None and everything runs unsharded.
    """
    devices = devices if devices is not None else jax.devices()
    nd = len(devices) if num_devices <= 0 else min(int(num_devices),
                                                   len(devices))
    if nd <= 1:
        return None
    return Mesh(np.asarray(devices[:nd]), (DATA_AXIS,))


def row_sharding(mesh: Optional[Mesh], extra_dims: int = 0):
    """Sharding for [N, ...] arrays: rows over the data axis."""
    if mesh is None:
        return None
    spec = [DATA_AXIS if DATA_AXIS in mesh.axis_names else None]
    spec += [None] * extra_dims
    return NamedSharding(mesh, P(*spec))


def feature_sharding(mesh: Optional[Mesh]):
    """Sharding for [N, F] bin matrices in feature-parallel mode."""
    if mesh is None:
        return None
    if FEATURE_AXIS in mesh.axis_names:
        row = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
        return NamedSharding(mesh, P(row, FEATURE_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS, None))


def replicated(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P())


def shard_rows(mesh: Optional[Mesh], *arrays):
    """device_put [N, ...] arrays with rows over the data axis, padding not
    required (jax shards uneven remainders automatically)."""
    if mesh is None:
        return arrays if len(arrays) > 1 else arrays[0]
    out = []
    for a in arrays:
        sh = row_sharding(mesh, extra_dims=a.ndim - 1)
        out.append(jax.device_put(a, sh))
    return tuple(out) if len(out) > 1 else out[0]
