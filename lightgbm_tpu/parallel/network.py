"""Distributed topology bootstrap.

The reference's Network/Linkers stack (src/network/: TCP mesh construction,
Bruck allgather, recursive-halving reduce-scatter — network.cpp:64-298) is
replaced wholesale by XLA collectives over the device mesh: psum/all_gather/
reduce_scatter compiled into the training step (see parallel.learners).
What remains host-side is multi-process bootstrap: the analog of
Network::Init (application.cpp:169) is ``jax.distributed.initialize``.

``init`` accepts the reference's ``machines`` ip:port list for API compat
(basic.py:1734 set_network) and maps it onto jax.distributed's
coordinator/process model.
"""
from __future__ import annotations

import os
from typing import List, Optional

from ..log import Log, LightGBMError

_initialized = False
_num_machines = 1
_rank = 0


def init(machines: str = "", local_listen_port: int = 12400,
         time_out: int = 120, num_machines: int = 1) -> None:
    """Network::Init analog. With num_machines == 1 this is a no-op; with
    more, the caller must run one process per host and the machine list's
    first entry is used as the jax.distributed coordinator."""
    global _initialized, _num_machines, _rank
    if num_machines <= 1:
        _initialized = True
        return
    import jax
    hosts: List[str] = [m.strip() for m in machines.split(",") if m.strip()]
    if len(hosts) != num_machines:
        raise LightGBMError(
            "machines list has %d entries but num_machines=%d"
            % (len(hosts), num_machines))
    coordinator = hosts[0]
    process_id = int(os.environ.get("LIGHTGBM_TPU_RANK",
                                    os.environ.get("JAX_PROCESS_ID", "0")))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_machines,
                               process_id=process_id,
                               initialization_timeout=time_out)
    _initialized = True
    _num_machines = num_machines
    _rank = process_id
    Log.info("Distributed init: rank %d / %d (coordinator %s)",
             _rank, _num_machines, coordinator)


def free() -> None:
    global _initialized, _num_machines, _rank
    _initialized = False
    _num_machines = 1
    _rank = 0


def num_machines() -> int:
    return _num_machines


def rank() -> int:
    return _rank


class HostComm:
    """Host-side allgather seam for distributed ingest (the pluggable
    collectives idea of LGBM_NetworkInitWithFunctions, network.h:96 /
    c_api.h:958 — kept so tests can run the identical code path without a
    cluster).

    ``allgather(obj) -> list[obj]`` returns every host's object in rank
    order. The jax implementation rides jax.experimental.multihost_utils;
    LoopbackComm simulates K hosts in one process for tests.
    """

    def allgather(self, obj):
        raise NotImplementedError


class JaxHostComm(HostComm):
    """Cross-host allgather via jax.distributed (host metadata only — the
    heavy per-iteration collectives are XLA ops inside the training step).

    Arbitrary picklable objects (ragged arrays included) are supported by
    gathering pickled bytes: lengths first (fixed shape), then the padded
    byte arrays — the same serialize-then-Allgather shape as the reference's
    BinMapper sync (dataset_loader.cpp:615-640)."""

    def allgather(self, obj):
        import pickle
        import numpy as _np
        from jax.experimental import multihost_utils
        blob = _np.frombuffer(pickle.dumps(obj), dtype=_np.uint8)
        lengths = multihost_utils.process_allgather(
            _np.asarray([blob.size], _np.int64))
        lengths = _np.asarray(lengths).reshape(-1)
        maxlen = int(lengths.max())
        padded = _np.zeros(maxlen, _np.uint8)
        padded[:blob.size] = blob
        stacked = _np.asarray(multihost_utils.process_allgather(padded))
        stacked = stacked.reshape(len(lengths), maxlen)
        return [pickle.loads(stacked[i, :int(lengths[i])].tobytes())
                for i in range(len(lengths))]


class LoopbackComm(HostComm):
    """Test double: K simulated hosts as K threads in one process, with a
    barrier-synchronized allgather — the collective semantics are real
    (rank-ordered, lockstep) without any cluster."""

    def __init__(self, shared: dict, my_rank: int):
        self._shared = shared
        self._rank = my_rank

    @staticmethod
    def group(k: int) -> List["LoopbackComm"]:
        import threading
        shared = {"slots": [None] * k, "barrier": threading.Barrier(k)}
        return [LoopbackComm(shared, r) for r in range(k)]

    def allgather(self, obj):
        self._shared["slots"][self._rank] = obj
        self._shared["barrier"].wait()
        out = list(self._shared["slots"])
        self._shared["barrier"].wait()   # don't overwrite until all read
        return out
