"""Distributed topology bootstrap.

The reference's Network/Linkers stack (src/network/: TCP mesh construction,
Bruck allgather, recursive-halving reduce-scatter — network.cpp:64-298) is
replaced wholesale by XLA collectives over the device mesh: psum/all_gather/
reduce_scatter compiled into the training step (see parallel.learners).
What remains host-side is multi-process bootstrap: the analog of
Network::Init (application.cpp:169) is ``jax.distributed.initialize``.

``init`` accepts the reference's ``machines`` ip:port list for API compat
(basic.py:1734 set_network) and maps it onto jax.distributed's
coordinator/process model.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

from ..log import Log, LightGBMError

_initialized = False
_num_machines = 1
_rank = 0


def init(machines: str = "", local_listen_port: int = 12400,
         time_out: int = 120, num_machines: int = 1) -> None:
    """Network::Init analog. With num_machines == 1 this is a no-op; with
    more, the caller must run one process per host and the machine list's
    first entry is used as the jax.distributed coordinator."""
    global _initialized, _num_machines, _rank
    if num_machines <= 1:
        _initialized = True
        return
    import jax
    # Compiled collectives on the CPU backend need a cross-process
    # implementation: jax's default leaves psum/all_gather unable to cross
    # process boundaries, which would break every learner schedule in
    # parallel/learners.py the moment the mesh spans hosts. Gloo rides the
    # same TCP fabric the coordinator already uses; TPU/GPU backends ignore
    # the flag. Must be set before the first backend client is created —
    # if the caller already touched jax.devices(), leave their choice alone.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jaxlib without gloo, or backend already up
        pass
    hosts: List[str] = [m.strip() for m in machines.split(",") if m.strip()]
    if len(hosts) != num_machines:
        raise LightGBMError(
            "machines list has %d entries but num_machines=%d"
            % (len(hosts), num_machines))
    coordinator = hosts[0]
    process_id = int(os.environ.get("LIGHTGBM_TPU_RANK",
                                    os.environ.get("JAX_PROCESS_ID", "0")))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_machines,
                               process_id=process_id,
                               initialization_timeout=time_out)
    _initialized = True
    _num_machines = num_machines
    _rank = process_id
    Log.info("Distributed init: rank %d / %d (coordinator %s)",
             _rank, _num_machines, coordinator)


def free() -> None:
    global _initialized, _num_machines, _rank, _external_comm
    _initialized = False
    _num_machines = 1
    _rank = 0
    # drop any injected transport: the host may free its callback code
    # right after LGBM_NetworkFree
    _external_comm = None


def num_machines() -> int:
    return _num_machines


def rank() -> int:
    return _rank


class HostComm:
    """Host-side allgather seam for distributed ingest (the pluggable
    collectives idea of LGBM_NetworkInitWithFunctions, network.h:96 /
    c_api.h:958 — kept so tests can run the identical code path without a
    cluster).

    ``allgather(obj) -> list[obj]`` returns every host's object in rank
    order. The jax implementation rides jax.experimental.multihost_utils;
    LoopbackComm simulates K hosts in one process for tests.
    """

    def allgather(self, obj):
        raise NotImplementedError


class JaxHostComm(HostComm):
    """Cross-host allgather via jax.distributed (host metadata only — the
    heavy per-iteration collectives are XLA ops inside the training step).

    Arbitrary picklable objects (ragged arrays included) are supported by
    gathering pickled bytes: lengths first (fixed shape), then the padded
    byte arrays — the same serialize-then-Allgather shape as the reference's
    BinMapper sync (dataset_loader.cpp:615-640)."""

    def allgather(self, obj):
        import pickle
        import numpy as _np
        from jax.experimental import multihost_utils
        blob = _np.frombuffer(pickle.dumps(obj), dtype=_np.uint8)
        lengths = multihost_utils.process_allgather(
            _np.asarray([blob.size], _np.int64))
        lengths = _np.asarray(lengths).reshape(-1)
        maxlen = int(lengths.max())
        padded = _np.zeros(maxlen, _np.uint8)
        padded[:blob.size] = blob
        stacked = _np.asarray(multihost_utils.process_allgather(padded))
        stacked = stacked.reshape(len(lengths), maxlen)
        return [pickle.loads(stacked[i, :int(lengths[i])].tobytes())
                for i in range(len(lengths))]


class KvHostComm(HostComm):
    """Host allgather over the jax.distributed coordination-service
    key-value store — no compiled computation at all, which matters
    because the CPU backend cannot run cross-process computations
    (``process_allgather`` raises "Multiprocess computations aren't
    implemented on the CPU backend"), yet the coordination service is up
    on every backend the moment ``jax.distributed.initialize`` returns.

    Protocol: each rank sets ``<ns>/r<round>/p<rank>`` to its
    base64-pickled payload, then blocking-gets every rank's key (the
    blocking get IS the synchronization — no separate barrier).  The
    round counter namespaces keys so consecutive allgathers never read a
    stale value; calls must therefore be SPMD-lockstep across processes
    (same construction order, same call count), which is exactly how the
    distributed-obs per-block cadence drives it.  Keys from two rounds
    back are best-effort deleted to keep the coordinator's store bounded.
    """

    def __init__(self, namespace: str = "lgbm_hostcomm",
                 timeout_ms: int = 60000, retries: int = 3,
                 retry_backoff_s: float = 0.25, peer_guard=None,
                 client=None, num_processes: Optional[int] = None,
                 rank: Optional[int] = None):
        self._ns = str(namespace)
        self._timeout_ms = int(timeout_ms)
        self._retries = max(int(retries), 0)
        self._retry_backoff_s = max(float(retry_backoff_s), 0.0)
        # peer_guard() -> list of dead peer ranks (KvHeartbeat.dead_peers);
        # checked between poll slices so a dead rank fails in seconds, not
        # after the full blocking-get timeout
        self._peer_guard = peer_guard
        self._client = client              # tests inject a dict-backed stub
        self._n = num_processes
        self._rank = rank
        self._round = 0

    def _resolve(self):
        if self._client is None:
            from jax._src import distributed as _jdist
            self._client = getattr(_jdist.global_state, "client", None)
            if self._client is None:
                raise LightGBMError(
                    "KvHostComm needs jax.distributed to be initialized")
        if self._n is None or self._rank is None:
            import jax
            self._n = int(jax.process_count())
            self._rank = int(jax.process_index())
        return self._client

    @staticmethod
    def _transient(err: Exception) -> bool:
        """Coordination-service failures worth retrying; a timeout is NOT
        transient — the peer is late or dead, retrying just re-waits."""
        return "DEADLINE_EXCEEDED" not in str(err)

    def _kv_set(self, key: str, value: str, r: int) -> None:
        from ..resilience import faults
        client = self._client
        last: Optional[Exception] = None
        for attempt in range(self._retries + 1):
            try:
                faults.inject("kv_set", round=r, rank=self._rank, key=key)
                client.key_value_set(key, value)
                return
            except Exception as e:  # noqa: BLE001 - classify + retry below
                if isinstance(e, LightGBMError):
                    raise
                last = e
                if not self._transient(e) or attempt == self._retries:
                    break
                Log.warning("KvHostComm set %s failed (%s); retry %d/%d",
                            key, e, attempt + 1, self._retries)
                time.sleep(self._retry_backoff_s * (2 ** attempt))
        raise LightGBMError(
            "KvHostComm set failed: namespace=%s round=%d rank=%d key=%s "
            "after %d attempt(s): %s"
            % (self._ns, r, self._rank, key, self._retries + 1, last))

    def _kv_get(self, key: str, r: int, peer: int) -> str:
        from ..resilience import faults
        client = self._client
        deadline = time.monotonic() + self._timeout_ms / 1000.0
        start = time.monotonic()
        attempts = 0
        last: Optional[Exception] = None
        while True:
            # short poll slices so the peer guard runs every ~2s even
            # while the value is simply not there yet
            slice_ms = min(max(int((deadline - time.monotonic()) * 1000), 1),
                           2000)
            attempts += 1
            try:
                faults.inject("kv_get", round=r, rank=self._rank,
                              peer=peer, key=key)
                return client.blocking_key_value_get(key, slice_ms)
            except Exception as e:  # noqa: BLE001 - classify + retry below
                if isinstance(e, LightGBMError):
                    raise
                last = e
                elapsed_ms = (time.monotonic() - start) * 1000.0
                if self._peer_guard is not None:
                    try:
                        dead = list(self._peer_guard())
                    except Exception:
                        dead = []
                    if peer in dead:
                        raise LightGBMError(
                            "KvHostComm allgather: peer rank %d is DEAD "
                            "(heartbeat lease expired) — namespace=%s "
                            "round=%d rank=%d key=%s elapsed=%.0fms"
                            % (peer, self._ns, r, self._rank, key,
                               elapsed_ms)) from e
                timed_out = time.monotonic() >= deadline
                if not timed_out and self._transient(e) and \
                        attempts <= self._retries:
                    Log.warning("KvHostComm get %s failed (%s); retry "
                                "%d/%d", key, e, attempts, self._retries)
                    time.sleep(self._retry_backoff_s * (2 ** (attempts - 1)))
                    continue
                if not timed_out and "DEADLINE_EXCEEDED" in str(e):
                    continue     # poll slice expired; keep waiting
                raise LightGBMError(
                    "KvHostComm allgather %s: namespace=%s round=%d "
                    "rank=%d peer=%d key=%s elapsed=%.0fms attempts=%d: %s"
                    % ("timed out" if timed_out else "failed",
                       self._ns, r, self._rank, peer, key,
                       elapsed_ms, attempts, last)) from e

    def allgather(self, obj):
        import base64
        import pickle
        self._resolve()
        n, me = self._n, self._rank
        r = self._round
        self._round += 1
        keyfmt = "%s/r%d/p%%d" % (self._ns, r)
        blob = base64.b64encode(pickle.dumps(obj)).decode("ascii")
        self._kv_set(keyfmt % me, blob, r)
        out = []
        for p in range(n):
            raw = self._kv_get(keyfmt % p, r, p)
            out.append(pickle.loads(base64.b64decode(raw)))
        if r >= 2:   # GC our own key from two rounds back
            try:
                self._client.key_value_delete(
                    "%s/r%d/p%d" % (self._ns, r - 2, me))
            except Exception:
                pass
        return out


def check_model_agreement(digest: str, comm: Optional["HostComm"] = None,
                          namespace: str = "lgbm_model_agree") -> List[str]:
    """Cross-process model-agreement check: allgather each rank's model
    digest and fail loudly if any pair differs.

    Data-parallel training is replicated-by-construction — every rank
    commits the tree built from the globally reduced histograms — so a
    digest mismatch always means real divergence (non-deterministic input
    order, a rank reading different data, a collective silently local).
    Returns the rank-ordered digest list; raises LightGBMError naming the
    disagreeing ranks. Single-process runs return ``[digest]`` untouched.
    """
    if comm is None:
        comm = default_host_comm(namespace=namespace)
    if comm is None:
        return [str(digest)]
    digests = [str(d) for d in comm.allgather(str(digest))]
    if len(set(digests)) > 1:
        raise LightGBMError(
            "model disagreement across processes: "
            + ", ".join("rank %d=%s" % (i, d[:16])
                        for i, d in enumerate(digests)))
    return digests


# one KV comm per namespace, process-wide: the round counter lives on
# the instance, so handing out a FRESH KvHostComm for a namespace that
# already ran an allgather would reuse round-0 keys and fail with
# ALREADY_EXISTS. Every process acquires namespaces in lockstep (the
# callers are collective), so the cached counters stay aligned.
_KV_COMMS: dict = {}


def default_host_comm(namespace: str = "lgbm_hostcomm",
                      timeout_ms: int = 60000) -> Optional[HostComm]:
    """The right host-metadata allgather for the current topology: None
    single-process, the coordination-service KV comm on the CPU backend
    (which cannot run multiprocess computations), ``process_allgather``
    everywhere else (TPU/GPU meshes). KV comms are cached per namespace
    (first call's ``timeout_ms`` wins) so repeated acquisitions continue
    one round sequence instead of colliding on reused keys."""
    import jax
    if jax.process_count() <= 1:
        return None
    if jax.default_backend() == "cpu":
        comm = _KV_COMMS.get(namespace)
        if comm is None:
            comm = KvHostComm(namespace=namespace, timeout_ms=timeout_ms)
            _KV_COMMS[namespace] = comm
        return comm
    return JaxHostComm()


class LoopbackComm(HostComm):
    """Test double: K simulated hosts as K threads in one process, with a
    barrier-synchronized allgather — the collective semantics are real
    (rank-ordered, lockstep) without any cluster.

    A simulated host that dies between the two waits used to hang every
    other thread forever; ``abort()`` (call it from the dying rank's
    except/finally) breaks the barrier so peers get a clean LightGBMError
    instead, and ``timeout_s`` bounds the wait as a backstop."""

    def __init__(self, shared: dict, my_rank: int):
        self._shared = shared
        self._rank = my_rank

    @staticmethod
    def group(k: int, timeout_s: Optional[float] = None) -> List["LoopbackComm"]:
        import threading
        shared = {"slots": [None] * k, "barrier": threading.Barrier(k),
                  "timeout_s": timeout_s, "aborted_by": None}
        return [LoopbackComm(shared, r) for r in range(k)]

    def abort(self) -> None:
        """Mark this rank dead and break the barrier, unblocking peers."""
        if self._shared.get("aborted_by") is None:
            self._shared["aborted_by"] = self._rank
        self._shared["barrier"].abort()

    def _wait(self, phase: str) -> None:
        import threading
        try:
            self._shared["barrier"].wait(self._shared.get("timeout_s"))
        except threading.BrokenBarrierError:
            culprit = self._shared.get("aborted_by")
            raise LightGBMError(
                "LoopbackComm allgather aborted at %s barrier on rank %d%s"
                % (phase, self._rank,
                   ": rank %d crashed" % culprit if culprit is not None
                   else " (barrier broken or timed out)")) from None

    def allgather(self, obj):
        try:
            self._shared["slots"][self._rank] = obj
            self._wait("publish")
            out = list(self._shared["slots"])
            self._wait("drain")   # don't overwrite until all read
            return out
        except LightGBMError:
            raise
        except BaseException:
            # dying between the waits must not wedge the peers
            self.abort()
            raise


class ExternalComm(HostComm):
    """Injectable collectives — the LGBM_NetworkInitWithFunctions seam
    (reference c_api.h:958, network.h:96, meta.h:51-57). The caller hands
    the ABI two C function pointers:

      allgather(input, input_size, block_start, block_len, num_block,
                output, output_size)
      reduce_scatter(input, input_size, type_size, block_start, block_len,
                     num_block, output, output_size, &reducer)

    and every host-side collective (sharded ingest's bin-sample merge,
    HostComm.allgather users) dispatches through them instead of
    jax.distributed — which is exactly what makes the distributed code
    path drivable from a test without a cluster. Ragged payloads ride the
    same two-phase shape as the reference's BinMapper sync: one fixed
    8-byte length round, then the data round.
    """

    def __init__(self, num_machines: int, my_rank: int,
                 reduce_scatter_ptr: int, allgather_ptr: int):
        import ctypes
        self._k = int(num_machines)
        self._rank = int(my_rank)
        c = ctypes
        self._AGT = c.CFUNCTYPE(
            None, c.c_char_p, c.c_int32, c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.c_int, c.c_char_p, c.c_int32)
        # last arg: const ReduceFunction& == pointer to the function pointer
        self._RST = c.CFUNCTYPE(
            None, c.c_char_p, c.c_int32, c.c_int, c.POINTER(c.c_int32),
            c.POINTER(c.c_int32), c.c_int, c.c_char_p, c.c_int32,
            c.POINTER(c.c_void_p))
        # void* not char*: ctypes converts incoming c_char_p callback
        # args to NUL-truncated bytes, corrupting binary payloads
        self._REDT = c.CFUNCTYPE(None, c.c_void_p, c.c_void_p, c.c_int,
                                 c.c_int32)
        self._ag = self._AGT(allgather_ptr) if allgather_ptr else None
        self._rs = self._RST(reduce_scatter_ptr) if reduce_scatter_ptr else None

    def _allgather_raw(self, blob: bytes, block_lens) -> bytes:
        import ctypes as c
        k = self._k
        starts = [0] * k
        for i in range(1, k):
            starts[i] = starts[i - 1] + int(block_lens[i - 1])
        total = starts[-1] + int(block_lens[-1])
        out = c.create_string_buffer(total)
        inp = c.create_string_buffer(bytes(blob), len(blob))
        self._ag(c.cast(inp, c.c_char_p), c.c_int32(len(blob)),
                 (c.c_int32 * k)(*starts), (c.c_int32 * k)(
                     *[int(b) for b in block_lens]),
                 c.c_int(k), c.cast(out, c.c_char_p), c.c_int32(total))
        return out.raw

    def allgather(self, obj):
        import pickle
        import struct
        if self._ag is None:
            raise LightGBMError("external allgather function not provided")
        blob = pickle.dumps(obj)
        lens_raw = self._allgather_raw(struct.pack("<q", len(blob)),
                                       [8] * self._k)
        lens = [struct.unpack_from("<q", lens_raw, 8 * i)[0]
                for i in range(self._k)]
        data = self._allgather_raw(blob, lens)
        out, off = [], 0
        for ln in lens:
            out.append(pickle.loads(data[off:off + ln]))
            off += ln
        return out

    def reduce_scatter_sum(self, arr):
        """Reference Network::ReduceScatter shape: each rank contributes a
        float64 array of K equal blocks; rank r receives the element-wise
        sum of every rank's block r. The sum reducer crosses the ABI as a
        ReduceFunction pointer (meta.h:51)."""
        import ctypes as c
        import numpy as np
        if self._rs is None:
            raise LightGBMError("external reduce_scatter function "
                                "not provided")
        a = np.ascontiguousarray(arr, np.float64)
        k = self._k
        if a.size % k:
            raise LightGBMError("reduce_scatter payload not divisible "
                                "into %d blocks" % k)
        blk = a.size // k
        blk_bytes = blk * 8

        def _sum(src, dst, type_size, nbytes):
            n = nbytes // 8
            s = np.frombuffer(c.string_at(src, nbytes), np.float64, n)
            buf = (c.c_double * n).from_address(dst)
            np.asarray(buf)[:] += s
        reducer = self._REDT(_sum)
        reducer_ptr = c.c_void_p(c.cast(reducer, c.c_void_p).value)
        starts = (c.c_int32 * k)(*[i * blk_bytes for i in range(k)])
        lens = (c.c_int32 * k)(*([blk_bytes] * k))
        out = c.create_string_buffer(blk_bytes)
        inp = a.tobytes()
        inbuf = c.create_string_buffer(inp, len(inp))
        self._rs(c.cast(inbuf, c.c_char_p), c.c_int32(len(inp)),
                 c.c_int(8), starts, lens, c.c_int(k),
                 c.cast(out, c.c_char_p), c.c_int32(blk_bytes),
                 c.byref(reducer_ptr))
        return np.frombuffer(out.raw, np.float64, blk).copy()


_external_comm: Optional[ExternalComm] = None


def init_with_functions(num_machines: int, rank: int,
                        reduce_scatter_ptr: int, allgather_ptr: int) -> None:
    """LGBM_NetworkInitWithFunctions analog: injectable collectives for
    hosts that bring their own transport (or tests that bring none)."""
    global _initialized, _num_machines, _rank, _external_comm
    _external_comm = ExternalComm(num_machines, rank,
                                  reduce_scatter_ptr, allgather_ptr)
    _initialized = True
    _num_machines = int(num_machines)
    _rank = int(rank)
    Log.info("Network init with external functions: rank %d / %d",
             _rank, _num_machines)


def active_comm() -> Optional[HostComm]:
    """The registered external transport, if any — HostComm consumers
    (e.g. BinnedDataset.from_sharded) use it when no comm is passed."""
    return _external_comm
