"""Frontier-wave parallel tree learners as compiled collective schedules.

The frontier grower (core/grow_frontier.py) has exactly three collective
seams per tree: the root reduction, the once-per-wave reduction of the
``[K, C, B, 3]`` smaller-child histogram tensor, and the per-wave best-split
search over the 2K children. This module packages the reference's parallel
learners (parallel_tree_learner.h) as interchangeable implementations of
those seams, selected by ``tree_learner``:

- **serial** (:class:`PsumLearner`): the PR 2 schedule — one ``psum`` of the
  full histogram tensor per wave, every device searches all features. Emits
  byte-for-byte the ops the grower always emitted, so the serial-path jaxpr
  fingerprints in ANALYSIS_BASELINE.json are unchanged.
- **data** (:class:`DataRSLearner`, data_parallel_tree_learner.cpp:146-161):
  ``psum_scatter`` (tiled reduce-scatter) over the feature axis replaces the
  wave psum — device ``d`` receives the fully-reduced histograms of feature
  block ``[d*fs, (d+1)*fs)`` only, scans best splits for just that shard,
  and ONE small all_gather of packed per-slot best-split records elects the
  global winners (SyncUpGlobalBestSplit, parallel_tree_learner.h:186-230).
  Per-wave comm drops from ``K*F*B*3`` psum'd floats to ``K*F*B*3/P``
  scattered + ``P*K*R`` gathered record floats (R ~ 21), and the sibling-
  subtraction hist pool shrinks to its feature shard (~1/P memory).
- **voting** (:class:`VotingLearner`, PV-Tree,
  voting_parallel_tree_learner.cpp:166-360): histograms stay LOCAL. Each
  device nominates its local top-k features per slot from local-histogram
  gains, two tiny int32 all_gathers elect <=2k global candidates by vote,
  and one psum exchanges ONLY the elected columns — ``K*2k*B*3`` floats per
  wave, independent of the total feature count. The split search then runs
  on the candidate histograms with GLOBAL leaf totals, so elected gains are
  exact; the approximation is only in which candidates stand (PAPER.md /
  arXiv:1706.08359 analysis). With ``top_k >= F`` every feature is elected
  and the learner degenerates to the exact data-parallel search.

Tie-break contract: find_best_split's argmax takes the FIRST maximum
(lowest feature index). DataRSLearner preserves it exactly because feature
blocks are contiguous in rank order: the cross-device argmax takes the
lowest rank among gain-maximal records, whose local search already took the
lowest local index — composing to the lowest global feature index.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.split import BestSplit, find_best_split, per_feature_split_merged

# f32 lanes in a packed BestSplit record: gain, feature, threshold,
# default_left, 6 child sums, 2 child outputs, is_categorical, 8 bitset words
RECORD_LANES = 21


def pack_best_record(bs: BestSplit) -> jnp.ndarray:
    """Flatten a batched BestSplit (fields ``[K]``/``[K, 8]``) into one
    ``[K, RECORD_LANES]`` f32 tensor so the election needs a single
    all_gather. Lane 0 is the gain (the argmax key); int/uint fields are
    BITCAST to f32 (lossless round-trip), bools value-cast (0.0/1.0)."""

    def lanes(v):
        v2 = v.reshape(v.shape[0], -1)
        if v2.dtype == jnp.bool_:
            return v2.astype(jnp.float32)
        if v2.dtype in (jnp.int32, jnp.uint32):
            return lax.bitcast_convert_type(v2, jnp.float32)
        return v2.astype(jnp.float32)

    rec = jnp.concatenate([lanes(v) for v in bs], axis=1)
    assert rec.shape[1] == RECORD_LANES, rec.shape
    return rec


def unpack_best_record(rec: jnp.ndarray) -> BestSplit:
    """Inverse of :func:`pack_best_record` (``[K, RECORD_LANES]`` f32)."""
    i32 = lambda c: lax.bitcast_convert_type(rec[:, c], jnp.int32)
    return BestSplit(
        gain=rec[:, 0],
        feature=i32(1),
        threshold=i32(2),
        default_left=rec[:, 3] > 0.5,
        left_sum_grad=rec[:, 4],
        left_sum_hess=rec[:, 5],
        left_count=rec[:, 6],
        right_sum_grad=rec[:, 7],
        right_sum_hess=rec[:, 8],
        right_count=rec[:, 9],
        left_output=rec[:, 10],
        right_output=rec[:, 11],
        is_categorical=rec[:, 12] > 0.5,
        cat_bitset=lax.bitcast_convert_type(rec[:, 13:21], jnp.uint32))


def elect_best_records(bs: BestSplit, axis_name: str) -> BestSplit:
    """Per-slot global best-split election: one all_gather of the packed
    ``[K, R]`` records, then a per-slot argmax on the gain lane. The first
    maximum wins, i.e. the lowest rank — see the module tie-break note."""
    rec = pack_best_record(bs)                         # [K, R]
    allrec = lax.all_gather(rec, axis_name)            # [D, K, R]
    winner = jnp.argmax(allrec[:, :, 0], axis=0)       # [K] lowest-rank max
    sel = jnp.take_along_axis(allrec, winner[None, :, None], axis=0)[0]
    return unpack_best_record(sel)


class PsumLearner:
    """The serial / one-psum-per-wave schedule (identical ops to the
    pre-learner grower; also the single-device no-op when axis_name=None)."""
    kind = "serial"
    varying_pool = False

    def __init__(self, psum: Callable, child_best: Callable):
        self._psum = psum
        self._child_best = child_best

    def reduce(self, hist):
        return self._psum(hist)

    def best_root(self, hist, sum_g, sum_h, cnt):
        return self._child_best(hist, sum_g, sum_h, cnt, -jnp.inf, jnp.inf)

    def best_children(self, ch_hist, sg, sh, cnt, mn, mx):
        return jax.vmap(self._child_best)(ch_hist, sg, sh, cnt, mn, mx)


class DataRSLearner:
    """Data-parallel with reduce-scattered wave histograms + packed
    best-record election. Requires C % P == 0 (gbdt pads features)."""
    kind = "data_rs"
    varying_pool = True

    def __init__(self, params, axis_name, meta, feature_mask):
        assert not params.with_efb, \
            "reduce-scatter learner is incompatible with EFB bundles"
        self.axis_name = axis_name
        self.params = params
        self.meta = meta
        self.feature_mask = feature_mask

    def reduce(self, hist):
        # tiled reduce-scatter over the feature axis: device d receives the
        # fully-summed block d (rank-ordered contiguous feature blocks)
        return lax.psum_scatter(hist, self.axis_name,
                                scatter_dimension=hist.ndim - 3, tiled=True)

    def _local(self, fs):
        """Slice meta/mask to this device's [base, base+fs) feature block."""
        base = lax.axis_index(self.axis_name).astype(jnp.int32) * fs
        sl = lambda a: (None if a is None
                        else lax.dynamic_slice_in_dim(a, base, fs, axis=0))
        return base, jax.tree.map(sl, self.meta), sl(self.feature_mask)

    def _search(self, hist_local, sum_g, sum_h, cnt, mn, mx,
                base, meta_l, fmask_l):
        p = self.params
        bs = find_best_split(hist_local, meta_l, p.split, sum_g, sum_h, cnt,
                             fmask_l, min_constraint=mn, max_constraint=mx,
                             with_categorical=p.with_categorical)
        return bs._replace(feature=base + bs.feature)

    def best_root(self, hist, sum_g, sum_h, cnt):
        base, meta_l, fmask_l = self._local(hist.shape[0])
        bs = self._search(hist, sum_g, sum_h, cnt, -jnp.inf, jnp.inf,
                          base, meta_l, fmask_l)
        bs1 = jax.tree.map(lambda a: a[None], bs)
        return jax.tree.map(lambda a: a[0],
                            elect_best_records(bs1, self.axis_name))

    def best_children(self, ch_hist, sg, sh, cnt, mn, mx):
        base, meta_l, fmask_l = self._local(ch_hist.shape[1])
        bs = jax.vmap(self._search, in_axes=(0,) * 6 + (None,) * 3)(
            ch_hist, sg, sh, cnt, mn, mx, base, meta_l, fmask_l)
        return elect_best_records(bs, self.axis_name)


class VotingLearner:
    """PV-Tree: local histograms, top-k vote election, exchange only the
    elected columns (the frontier-wave port of grow.py's voting_best)."""
    kind = "voting"
    varying_pool = True

    def __init__(self, params, axis_name, meta, feature_mask):
        assert not params.with_efb, \
            "voting learner is incompatible with EFB bundles"
        self.axis_name = axis_name
        self.params = params
        self.meta = meta
        self.feature_mask = feature_mask
        f = int(feature_mask.shape[0])
        self.k = min(params.voting_top_k, f)
        self.k2 = min(2 * params.voting_top_k, f)

    def reduce(self, hist):
        return hist      # histograms stay device-local; election reduces

    def _vote(self, ch_hist, sg, sh, cnt, mn, mx):
        """Batched election + exact search over [K, F, B, 3] LOCAL hists
        with GLOBAL totals sg/sh/cnt (fields [K])."""
        p, ax = self.params, self.axis_name
        f = self.feature_mask.shape[0]
        bdim = ch_hist.shape[2]
        # local leaf totals from the local histogram itself: every local
        # row lands in exactly one bin of feature 0
        lsg = jnp.sum(ch_hist[:, 0, :, 0], axis=1)
        lsh = jnp.sum(ch_hist[:, 0, :, 1], axis=1)
        lsc = jnp.sum(ch_hist[:, 0, :, 2], axis=1)

        def local_gains(h, g, hh, c):
            pf, _ = per_feature_split_merged(
                h, self.meta, p.split, g, hh, c, self.feature_mask,
                with_categorical=p.with_categorical)
            return pf.gain

        gains = jax.vmap(local_gains)(ch_hist, lsg, lsh, lsc)     # [K, F]
        top_gain, top_idx = lax.top_k(gains, self.k)              # [K, k]
        w = jnp.isfinite(top_gain).astype(jnp.int32)  # real proposals only
        all_idx = jnp.moveaxis(lax.all_gather(top_idx, ax), 0, 1)
        all_w = jnp.moveaxis(lax.all_gather(w, ax), 0, 1)         # [K, D, k]
        kk = all_idx.shape[0]
        votes = jax.vmap(
            lambda i, v: jnp.zeros((f,), jnp.int32).at[i].add(v))(
                all_idx.reshape(kk, -1), all_w.reshape(kk, -1))   # [K, F]
        elected = lax.top_k(votes, self.k2)[1]                    # [K, k2]
        # THE wave exchange: only the elected columns cross the mesh
        cand = lax.psum(jnp.take_along_axis(
            ch_hist, elected[:, :, None, None], axis=1), ax)  # [K, k2, B, 3]
        gh = jax.vmap(lambda e, c: jnp.zeros(
            (f, bdim, 3), jnp.float32).at[e].set(c))(elected, cand)
        cand_mask = jax.vmap(
            lambda e: jnp.zeros((f,), bool).at[e].set(True))(elected)

        def search(h, m, g, hh, c, lo, hi):
            return find_best_split(h, self.meta, p.split, g, hh, c,
                                   self.feature_mask & m, min_constraint=lo,
                                   max_constraint=hi,
                                   with_categorical=p.with_categorical)

        # elected/votes are all_gather-derived (replicated), cand is psum'd
        # and the totals are global, so the result is replicated — no
        # sync_best_split needed
        return jax.vmap(search)(gh, cand_mask, sg, sh, cnt, mn, mx)

    def best_root(self, hist, sum_g, sum_h, cnt):
        one = lambda v: jnp.asarray(v)[None]
        bs = self._vote(hist[None], one(sum_g), one(sum_h), one(cnt),
                        one(-jnp.inf), one(jnp.inf))
        return jax.tree.map(lambda a: a[0], bs)

    def best_children(self, ch_hist, sg, sh, cnt, mn, mx):
        return self._vote(ch_hist, sg, sh, cnt, mn, mx)


def make_frontier_learner(params, axis_name: Optional[str], meta,
                          feature_mask, psum: Callable,
                          child_best: Callable):
    """Select the wave-collective schedule for grow_tree_frontier.

    ``psum``/``child_best`` are the grower's own closures; PsumLearner uses
    them verbatim so the serial path's compiled program never changes."""
    if axis_name is not None and params.voting_top_k > 0:
        return VotingLearner(params, axis_name, meta, feature_mask)
    if axis_name is not None and params.frontier_rs:
        return DataRSLearner(params, axis_name, meta, feature_mask)
    return PsumLearner(psum, child_best)
