"""Replicated serving: KV-announced generations and rolling hot-rolls.

A serving fleet is N independent ``task=serve`` processes watching the
SAME checkpoint directory (serving/registry.py CheckpointWatcher).  Left
alone they would all stage-and-prewarm a new snapshot at once — every
replica compiling simultaneously is a fleet-wide latency cliff, and a bad
snapshot would hit every replica's canary in parallel.  This module adds
the coordination layer on the PR 9/10 KV seam (parallel/network.py
``KvHostComm`` client contract):

- :class:`FileKvClient` — an atomic-file key/value store satisfying the
  exact client interface ``KvHostComm`` takes (``key_value_set`` /
  ``blocking_key_value_get`` / ``key_value_delete``; timeouts raise with
  ``DEADLINE_EXCEEDED`` in the message, the transient-vs-fatal marker
  ``KvHostComm._transient`` keys on).  It lets plain OS processes share a
  namespace through any common directory — no ``jax.distributed`` needed
  for a single-host fleet, and the same announcer code runs unchanged
  over the real coordination-service client on a TPU pod.
- :class:`ReplicaAnnouncer` — each replica periodically publishes one
  JSON document (generation per model, last hot-rolled snapshot id,
  rejected snapshot ids, a metrics digest, drift status) under
  ``fleet/<replica>``.  Announcements carry a wall-clock stamp; readers
  treat documents older than the lease as a dead replica.
- :class:`RollingDeployCoordinator` — turn-taking WITHOUT a lock
  service: replicas roll a new snapshot in sorted-name order, each
  waiting until every alphabetically-earlier LIVE replica announces the
  target snapshot (or rejects it).  The first replica is the fleet's
  canary — its ``stage_and_prewarm`` refusal (docs/Resilience.md) is
  announced as a rejection and every successor then SKIPS the snapshot,
  so one guarded refusal protects the whole fleet.  Dead predecessors
  age out of the wait via the lease; a stuck-but-alive one is bounded by
  ``predecessor_timeout_s`` (availability beats strict ordering).
- :class:`FleetClusterProvider` — merges the announced documents into
  the ``/metrics/cluster`` + ``/stats/cluster`` federation surface
  (obs/server.py ``StatsServer.set_cluster`` contract), also served by
  the serving HTTP front-end when a fleet KV directory is configured.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional
from urllib.parse import quote, unquote

from ..log import Log, check


class FileKvClient:
    """Directory-backed KV satisfying the ``KvHostComm`` client seam.

    One key is one file (name = URL-quoted key) written atomically via a
    same-directory temp file + ``os.replace`` — readers see either the
    old value or the new one, never a torn write.  ``blocking_key_value_get``
    polls; on deadline it raises with ``DEADLINE_EXCEEDED`` in the
    message so ``KvHostComm`` treats it exactly like the real
    coordination-service timeout (a poll-slice expiry, not a fatality).
    """

    def __init__(self, directory: str, poll_interval_s: float = 0.02):
        check(bool(directory), "FileKvClient needs a directory")
        self.directory = directory
        self.poll_interval_s = float(poll_interval_s)
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, quote(key, safe=""))

    # ------------------------------------------------ KvHostComm contract
    def key_value_set(self, key: str, value: str) -> None:
        path = self._path(key)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as fh:
            fh.write(value)
        os.replace(tmp, path)

    def blocking_key_value_get(self, key: str, timeout_ms: int) -> str:
        deadline = time.monotonic() + max(int(timeout_ms), 0) / 1000.0
        path = self._path(key)
        while True:
            val = self.try_get(key)
            if val is not None:
                return val
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "DEADLINE_EXCEEDED: key %r not set within %d ms (%s)"
                    % (key, timeout_ms, path))
            time.sleep(self.poll_interval_s)

    def key_value_delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    # ------------------------------------------------ fleet extras
    def try_get(self, key: str) -> Optional[str]:
        """Non-blocking read; None when unset (or mid-replace)."""
        try:
            with open(self._path(key), "r") as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def keys(self, prefix: str = "") -> List[str]:
        """Every stored key starting with ``prefix`` (sorted)."""
        out = []
        for name in os.listdir(self.directory):
            if name.endswith((".tmp", ".lock")) or ".tmp." in name:
                continue
            key = unquote(name)
            if key.startswith(prefix):
                out.append(key)
        return sorted(out)


def _fleet_key(replica: str) -> str:
    return "fleet/" + replica


class ReplicaAnnouncer:
    """Publish one replica's serving state into the fleet KV namespace.

    The document is the fleet's ONLY coordination currency — generations
    per model, the last hot-rolled snapshot id, rejected snapshot ids,
    and a metrics digest — stamped with wall-clock time so readers can
    lease out dead replicas (``lease_s``).  ``announce_once`` is cheap
    (one metrics snapshot + one atomic file write); the daemon loop runs
    it every ``period_s``.
    """

    def __init__(self, client, replica: str, engine=None, watcher=None,
                 period_s: float = 1.0, lease_s: float = 10.0):
        check(bool(replica), "ReplicaAnnouncer needs a replica name")
        self.client = client
        self.replica = replica
        self.engine = engine
        self.watcher = watcher
        self.period_s = float(period_s)
        self.lease_s = float(lease_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ publish
    def state(self) -> Dict:
        doc: Dict = {"replica": self.replica, "pid": os.getpid(),
                     "time": round(time.time(), 3)}
        if self.engine is not None:
            reg = self.engine.registry
            doc["generations"] = {mid: reg.generation(mid)
                                  for mid in reg.ids()}
            m = self.engine.metrics.snapshot()
            doc["metrics"] = {k: m.get(k) for k in (
                "requests", "rows", "errors", "shed",
                "recompiles_after_warmup", "rollbacks")}
            doc["p99_ms"] = m.get("latency_ms", {}).get("p99_ms")
            doc["drift"] = self.engine.drift_status().get("status")
        if self.watcher is not None:
            doc["snap_id"] = int(self.watcher._last_id)
            doc["rejected"] = sorted(int(i)
                                     for i in self.watcher._rejected_ids)
        return doc

    def announce_once(self) -> Dict:
        doc = self.state()
        self.client.key_value_set(_fleet_key(self.replica),
                                  json.dumps(doc, sort_keys=True))
        return doc

    def retract(self) -> None:
        """Remove this replica's document (clean shutdown — readers stop
        counting it immediately instead of waiting out the lease)."""
        self.client.key_value_delete(_fleet_key(self.replica))

    # ------------------------------------------------------------ read side
    @staticmethod
    def read_fleet(client, lease_s: float = 10.0) -> Dict[str, Dict]:
        """Every announced replica document, keyed by replica name, each
        annotated ``"live"`` by the lease test.  Unparseable documents
        (a reader racing a writer on a non-atomic store) are skipped."""
        fleet: Dict[str, Dict] = {}
        now = time.time()
        for key in client.keys("fleet/"):
            raw = client.try_get(key)
            if raw is None:
                continue
            try:
                doc = json.loads(raw)
            except ValueError:
                continue
            name = doc.get("replica") or key[len("fleet/"):]
            doc["live"] = bool(now - float(doc.get("time", 0)) <= lease_s)
            fleet[name] = doc
        return fleet

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaAnnouncer":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.announce_once()

        def loop():
            while not self._stop.wait(self.period_s):
                try:
                    self.announce_once()
                except Exception as e:  # noqa: BLE001 - announcer must not die
                    Log.warning("fleet announcer %r: %s", self.replica, e)

        self._thread = threading.Thread(
            target=loop, name="lgbm-fleet-announce-%s" % self.replica,
            daemon=True)
        self._thread.start()
        return self

    def stop(self, retract: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if retract:
            try:
                self.retract()
            except Exception:  # noqa: BLE001 - shutdown best-effort
                pass


class RollingDeployCoordinator:
    """One-replica-at-a-time hot-rolls, ordered by replica name.

    ``step()`` is one coordination decision: if the watched checkpoint
    directory holds a snapshot newer than what this replica serves, wait
    until every alphabetically-earlier live replica has either rolled to
    it (announced ``snap_id >= target``) or rejected it, then run the
    normal canary-guarded ``CheckpointWatcher.poll``.  A predecessor's
    announced rejection short-circuits the whole fleet: the snapshot is
    added to the local watcher's rejected set without ever being staged —
    the first replica's canary ate the bad snapshot for everyone.
    """

    def __init__(self, client, announcer: ReplicaAnnouncer, watcher,
                 poll_interval_s: float = 0.5,
                 predecessor_timeout_s: float = 30.0):
        self.client = client
        self.announcer = announcer
        self.watcher = watcher
        self.replica = announcer.replica
        self.poll_interval_s = float(poll_interval_s)
        self.predecessor_timeout_s = float(predecessor_timeout_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ decisions
    def _pending_snapshot(self):
        """(snap_id, path) newer than what we serve, or None."""
        from ..checkpoint.manager import CheckpointManager
        latest = CheckpointManager(self.watcher.checkpoint_dir).latest_model()
        if latest is None:
            return None
        snap_id, path = latest
        if snap_id <= self.watcher._last_id \
                or snap_id in self.watcher._rejected_ids:
            return None
        return snap_id, path

    def _predecessors_ready(self, snap_id: int):
        """(ready, rejected_by): ready when every live replica sorting
        before us has announced ``snap_id >= target`` or rejected it;
        ``rejected_by`` names a predecessor whose canary refused it."""
        fleet = ReplicaAnnouncer.read_fleet(self.client,
                                            self.announcer.lease_s)
        for name in sorted(fleet):
            if name >= self.replica:
                break
            doc = fleet[name]
            if not doc.get("live", False):
                continue                      # leased out: dead can't block
            if snap_id in doc.get("rejected", []):
                return False, name
            if int(doc.get("snap_id", -1)) < snap_id:
                return False, None
        return True, None

    def step(self) -> bool:
        """Returns True when this call hot-rolled a new snapshot."""
        pending = self._pending_snapshot()
        if pending is None:
            return False
        snap_id, _ = pending
        deadline = time.monotonic() + self.predecessor_timeout_s
        while not self._stop.is_set():
            ready, rejected_by = self._predecessors_ready(snap_id)
            if rejected_by is not None:
                # fleet-wide canary: the first replica's guarded roll
                # refused this snapshot — never stage it here
                self.watcher._rejected_ids.add(snap_id)
                Log.warning("fleet %r: snapshot %d rejected by canary "
                            "replica %r; skipping fleet-wide",
                            self.replica, snap_id, rejected_by)
                self.announcer.announce_once()
                return False
            if ready:
                break
            if time.monotonic() >= deadline:
                Log.warning("fleet %r: predecessors silent on snapshot %d "
                            "for %.0fs; rolling anyway", self.replica,
                            snap_id, self.predecessor_timeout_s)
                break
            self._stop.wait(self.poll_interval_s)
        rolled = bool(self.watcher.poll())
        # announce immediately either way: a successful roll unblocks the
        # next replica's wait, a rejection warns it off
        self.announcer.announce_once()
        return rolled

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RollingDeployCoordinator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 - keep serving alive
                    Log.warning("fleet coordinator %r: %s", self.replica, e)

        self._thread = threading.Thread(
            target=loop, name="lgbm-fleet-roll-%s" % self.replica,
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class FleetClusterProvider:
    """Fleet-wide state for ``/metrics/cluster`` + ``/stats/cluster``.

    Satisfies the ``StatsServer.set_cluster`` provider contract
    (obs/server.py): ``cluster_stats()`` returns the merged replica
    documents plus a fleet summary (replica/live counts, snapshot id
    spread — a non-zero spread is a rolling deploy in flight), and
    ``cluster_prometheus()`` renders them as per-replica labeled gauges
    federation-style scrapers can aggregate."""

    def __init__(self, client, lease_s: float = 10.0):
        self.client = client
        self.lease_s = float(lease_s)

    def cluster_stats(self) -> Dict:
        fleet = ReplicaAnnouncer.read_fleet(self.client, self.lease_s)
        live = [d for d in fleet.values() if d.get("live")]
        snaps = [int(d["snap_id"]) for d in live if "snap_id" in d]
        summary = {
            "replicas": len(fleet),
            "live": len(live),
            "requests": sum(int(d.get("metrics", {}).get("requests") or 0)
                            for d in live),
            "shed": sum(int(d.get("metrics", {}).get("shed") or 0)
                        for d in live),
            "snap_id_min": min(snaps) if snaps else -1,
            "snap_id_max": max(snaps) if snaps else -1,
            "rolling": bool(snaps) and min(snaps) != max(snaps),
        }
        return {"fleet": summary, "replicas": fleet}

    def cluster_prometheus(self) -> str:
        # replica names come from config/CLI, so label VALUES must be
        # escaped per exposition 0.0.4 (backslash, quote, newline) — a
        # replica named `a"b` used to emit an unparseable line here
        from ..obs.registry import escape_label_value
        snap = self.cluster_stats()
        lines = [
            "# HELP lgbm_fleet_replica_up Replica announced within lease.",
            "# TYPE lgbm_fleet_replica_up gauge",
        ]
        gauges = [
            ("lgbm_fleet_replica_snap_id", "snap_id",
             "Last hot-rolled snapshot id."),
            ("lgbm_fleet_replica_requests_total", ("metrics", "requests"),
             "Requests served."),
            ("lgbm_fleet_replica_shed_total", ("metrics", "shed"),
             "Requests shed."),
            ("lgbm_fleet_replica_recompiles_after_warmup",
             ("metrics", "recompiles_after_warmup"),
             "Serving recompiles past the warmup floor."),
        ]
        for name in sorted(snap["replicas"]):
            doc = snap["replicas"][name]
            lines.append('lgbm_fleet_replica_up{replica="%s"} %d'
                         % (escape_label_value(name),
                            1 if doc.get("live") else 0))
        for metric, path, help_text in gauges:
            lines.append("# HELP %s %s" % (metric, help_text))
            lines.append("# TYPE %s gauge" % metric)
            for name in sorted(snap["replicas"]):
                doc = snap["replicas"][name]
                val = (doc.get(path) if isinstance(path, str)
                       else doc.get(path[0], {}).get(path[1]))
                if val is None:
                    continue
                lines.append('%s{replica="%s"} %s'
                             % (metric, escape_label_value(name), val))
        s = snap["fleet"]
        lines += [
            "# HELP lgbm_fleet_live_replicas Live replicas in the fleet.",
            "# TYPE lgbm_fleet_live_replicas gauge",
            "lgbm_fleet_live_replicas %d" % s["live"],
            "# HELP lgbm_fleet_rolling A rolling deploy is in flight.",
            "# TYPE lgbm_fleet_rolling gauge",
            "lgbm_fleet_rolling %d" % (1 if s["rolling"] else 0),
        ]
        return "\n".join(lines) + "\n"
