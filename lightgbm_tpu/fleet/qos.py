"""Multi-model QoS: admission quotas, weighted-fair scheduling, and
closed-loop cascade-margin autotuning.

When several models share one ServingEngine the micro-batch queue's
head-of-line pick lets a chatty model starve the rest, and the single
engine-wide row bound sheds EVERY model once any one of them floods the
queue. :class:`QosPolicy` fixes both:

- **per-model admission**: each model gets a queued-row quota; a request
  that would exceed its own model's quota is shed with a per-model
  503-with-Retry-After while other models keep being admitted (the
  engine-wide ``serve_max_queue_rows`` bound still backstops the total);
- **weighted-fair scheduling**: dispatch picks the queued model with the
  smallest ``rows_served / weight`` virtual time (classic weighted fair
  queueing over row counts), so a weight-4 model gets ~4x the device
  rows of a weight-1 model under saturation — instead of whatever
  arrival order happened to produce.

:class:`CascadeAutotuner` closes the latency loop: it watches the
per-bucket latency histograms (serving/metrics.py) and walks the
early-exit cascade margin (serving/traversal.py) down when the observed
p99 overshoots ``serve_latency_budget_ms`` (more rows exit after the
first ``cascade_trees`` iterations -> cheaper tail) and back up toward
full-model exactness when there is headroom. Margin changes go through
``ServingEngine.set_cascade_margin``, which re-warms the affected
predictors OFF the request path inside a warmup-credit window — the
zero-recompiles-after-warmup serving invariant survives every retune.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..log import Log, check


class QosPolicy:
    """Per-model admission quotas + weighted-fair virtual time.

    ``weights`` maps model_id -> scheduling weight (default 1.0);
    ``quota_rows`` maps model_id -> max queued rows for that model
    (``default_quota_rows`` for unlisted models; 0 = no per-model bound).
    Thread-safety: all mutation happens under the owning queue's lock.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 quota_rows: Optional[Dict[str, int]] = None,
                 default_weight: float = 1.0,
                 default_quota_rows: int = 0):
        check(default_weight > 0, "QoS default_weight must be > 0")
        self.weights = dict(weights or {})
        for mid, w in self.weights.items():
            check(w > 0, "QoS weight for %r must be > 0" % mid)
        self.quota_rows = {m: int(q) for m, q in (quota_rows or {}).items()}
        self.default_weight = float(default_weight)
        self.default_quota_rows = max(int(default_quota_rows), 0)
        self._served_rows: Dict[str, float] = {}
        self._shed: Dict[str, int] = {}

    @classmethod
    def from_spec(cls, weights_spec: str = "", quota_rows: int = 0
                  ) -> "QosPolicy":
        """Build from the config-string surface: ``serve_qos_weights`` is
        ``"modelA=4,modelB=1"`` (empty = every model weight 1) and
        ``serve_qos_quota_rows`` is the default per-model quota."""
        weights: Dict[str, float] = {}
        for part in (weights_spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            check("=" in part,
                  "serve_qos_weights entries must look like model=weight, "
                  "got %r" % part)
            mid, w = part.split("=", 1)
            weights[mid.strip()] = float(w)
        return cls(weights=weights, default_quota_rows=quota_rows)

    # ------------------------------------------------------------ admission
    def weight(self, model_id: str) -> float:
        return self.weights.get(model_id, self.default_weight)

    def quota(self, model_id: str) -> int:
        return self.quota_rows.get(model_id, self.default_quota_rows)

    def admit(self, model_id: str, queued_model_rows: int,
              nrows: int) -> bool:
        """True when ``nrows`` more rows fit under the model's quota."""
        q = self.quota(model_id)
        if q and queued_model_rows + nrows > q:
            self._shed[model_id] = self._shed.get(model_id, 0) + 1
            return False
        return True

    # ------------------------------------------------------------ fairness
    def _floor_vt(self) -> float:
        """The fleet's minimum VIRTUAL time (``served_rows / weight``) —
        the start point for models seen for the first time, so a
        newcomer neither starves the incumbents nor gets an unbounded
        catch-up burst. The floor must be in virtual-time units, not raw
        rows: seeding a weight-1 newcomer with a weight-4 incumbent's
        ROW count would hand it a 4x-inflated virtual time and starve
        it indefinitely."""
        return min((self._served_rows[m] / self.weight(m)
                    for m in self._served_rows), default=0.0)

    def pick(self, queued_rows_by_model: Dict[str, int]) -> str:
        """The model to dispatch next: smallest virtual time
        ``served_rows / weight`` among models with queued work. An
        unseen model sits AT the floor, which follows the incumbents'
        virtual time — so ties must break toward the newcomer or it
        never receives the first service that enters it into the
        rotation."""
        floor = self._floor_vt()
        best, best_key = None, None
        for mid in sorted(queued_rows_by_model):
            seen = mid in self._served_rows
            vt = self._served_rows[mid] / self.weight(mid) if seen else floor
            key = (vt, seen)               # False < True: unseen wins ties
            if best_key is None or key < best_key:
                best, best_key = mid, key
        return best

    def account(self, model_id: str, rows: int) -> None:
        if model_id not in self._served_rows:
            self._served_rows[model_id] = \
                self._floor_vt() * self.weight(model_id)
        self._served_rows[model_id] += float(rows)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-model QoS state for ``/stats`` (docs/Fleet.md schema)."""
        models = set(self._served_rows) | set(self._shed) \
            | set(self.weights) | set(self.quota_rows)
        return {mid: {
            "weight": self.weight(mid),
            "quota_rows": self.quota(mid),
            "served_rows": self._served_rows.get(mid, 0.0),
            "shed": self._shed.get(mid, 0),
        } for mid in sorted(models)}


class CascadeAutotuner:
    """Walk the engine's cascade margin along a static ladder to hold the
    observed per-bucket p99 under ``budget_ms``.

    The ladder is geometric from near-exact (the engine's configured
    margin — largest, fewest early exits) down to ``margin / 2**(n-1)``.
    Each step only ever moves ONE rung and re-warms through
    ``set_cascade_margin`` (off-path, warmup-credited), so a noisy p99
    cannot thrash the compiled-entry cache. ``headroom`` (default 0.6):
    only retune UP toward exactness when p99 < headroom * budget —
    hysteresis against oscillation at the boundary."""

    def __init__(self, engine, budget_ms: float, rungs: int = 4,
                 interval_s: float = 2.0, headroom: float = 0.6,
                 min_samples: int = 20):
        check(budget_ms > 0, "serve_latency_budget_ms must be > 0 to tune")
        check(engine.cascade_trees > 0,
              "cascade autotuning needs serving_cascade_trees > 0 "
              "(no early-exit stage to widen)")
        self.engine = engine
        self.budget_ms = float(budget_ms)
        top = float(engine.cascade_margin)
        self.ladder: List[float] = [top / (2.0 ** i) for i in range(rungs)]
        self.interval_s = float(interval_s)
        self.headroom = float(headroom)
        self.min_samples = int(min_samples)
        self._idx = 0                      # current rung (0 = widest margin)
        self._seen: Dict[int, int] = {}    # bucket -> samples already judged
        self.retunes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def observed_p99_ms(self) -> Optional[float]:
        """Worst p99 across buckets with NEW samples since the last step
        (stale histograms must not re-trigger a retune forever)."""
        worst = None
        for bucket, st in self.engine.metrics.bucket_latency().items():
            fresh = int(st["count"]) - self._seen.get(int(bucket), 0)
            if fresh < self.min_samples:
                continue
            if worst is None or st["p99_ms"] > worst:
                worst = float(st["p99_ms"])
        return worst

    def step(self) -> Optional[float]:
        """One control decision; returns the newly applied margin or None
        when nothing changed."""
        p99 = self.observed_p99_ms()
        if p99 is None:
            return None
        target = self._idx
        if p99 > self.budget_ms and self._idx < len(self.ladder) - 1:
            target = self._idx + 1         # tighter margin, more early exit
        elif p99 < self.headroom * self.budget_ms and self._idx > 0:
            target = self._idx - 1         # headroom: move toward exactness
        for bucket, st in self.engine.metrics.bucket_latency().items():
            self._seen[int(bucket)] = int(st["count"])
        if target == self._idx:
            return None
        self._idx = target
        margin = self.ladder[target]
        self.engine.set_cascade_margin(margin)
        self.retunes += 1
        Log.info("cascade autotune: p99 %.1f ms vs budget %.1f ms -> "
                 "margin %.4g (rung %d/%d)", p99, self.budget_ms, margin,
                 target + 1, len(self.ladder))
        return margin

    def snapshot(self) -> Dict[str, float]:
        return {"budget_ms": self.budget_ms,
                "margin": self.ladder[self._idx],
                "rung": self._idx, "rungs": len(self.ladder),
                "retunes": self.retunes}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "CascadeAutotuner":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="lgbm-cascade-tuner",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 - tuner must not die
                Log.warning("cascade autotune step failed: %s", e)
