"""lightgbm_tpu.fleet — the continuous-training serving fleet.

The fourth runtime pillar next to train / serve / stream: everything
needed to keep a SERVING model current without a full retrain, at fleet
scale. Four layers (docs/Fleet.md):

- **refit** (refit.py): structure-preserving leaf re-estimation on fresh
  data — the reference's ``GBDT::RefitTree`` semantics executed as ONE
  device pass (flat SoA leaf-id traversal + per-leaf segment sums inside
  a ``lax.scan`` over boosting iterations), published as a checkpoint
  snapshot so the result rides the existing hot-roll path.
- **QoS** (qos.py): per-model admission quotas + weighted-fair
  scheduling when several models share one engine, and closed-loop
  cascade-margin autotuning against a latency budget.
- **replicas** (replica.py): N serving processes kept converged through
  a shared checkpoint dir + KV generation announcements, rolled one at a
  time behind the canary-guarded ``stage_and_prewarm`` refusal path,
  with fleet-wide state federated on ``/metrics/cluster``.
- **the loop**: drift warn -> refit window -> snapshot -> rolling
  hot-roll, exercised end-to-end by ``tools/fleet_smoke.py``.
"""
from .refit import Refitter, refit_booster
from .qos import CascadeAutotuner, QosPolicy
from .replica import (FileKvClient, FleetClusterProvider, ReplicaAnnouncer,
                      RollingDeployCoordinator)

__all__ = [
    "Refitter", "refit_booster", "QosPolicy", "CascadeAutotuner",
    "FileKvClient", "ReplicaAnnouncer", "RollingDeployCoordinator",
    "FleetClusterProvider",
]
