"""Structure-preserving refit: re-estimate leaf outputs on fresh data.

The reference's continuous-training primitive (``GBDT::RefitTree``,
gbdt.cpp:263-286 + ``FitByExistingTree``): every split of every tree is
kept, only the leaf OUTPUTS are recomputed from the new data's gradients
— orders of magnitude cheaper than retraining, and the serving side can
hot-roll the result with zero structural churn (same traversal depth,
same node tables, new leaf values).

Device execution shape: the packed ``FlatForest`` (serving/traversal.py)
routes ALL rows through ALL trees in one ``depth``-step traversal
(``forest_leaf_ids`` — [N, T] leaf ids), then ONE jitted ``lax.scan``
over boosting iterations refreshes gradients from the running scores and
segment-sums grad/hess per leaf:

    out  = -sign(G) * max(|G| - l1, 0) / (H + l2 + eps)    (per leaf)
    leaf = decay * old + (1 - decay) * out * tree_shrinkage

(CalculateSplittedLeafOutput, feature_histogram.hpp:454-462, then the
RefitTree decay blend.) Per-tree shrinkage — including DART's per-tree
weights — is preserved, and padded leaf slots keep their old values so a
packed table never leaks refit math into rows that can't reach it.

The compiled-program set is BOUNDED and tree-count-independent: one
leaf-id traversal program + one scan program per (row-count, objective)
signature, reused across refit cycles — the perf gate pins both the
per-cycle program count and that a second cycle at the same shapes
compiles NOTHING (obs/perfgate.py ``refit_*`` counters). Final stored
leaf values are blended on host in float64 against the original doubles,
so ``decay_rate=1.0`` is byte-stable (the tier-1 refit tests pin this).

Host fallback: ``Booster.refit`` keeps the numpy path for sparse inputs;
it is also the golden reference the device path is tested against.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..io.dataset import Metadata
from ..log import check
from ..serving.traversal import forest_leaf_ids, pack_flat_forest

_EPS = 1e-15


def _objective_arrays(obj) -> Dict[str, jnp.ndarray]:
    """Every device-array attribute of an initialized objective — the
    data-dependent state its ``get_gradients`` closes over (label,
    weights, transformed labels, lambdarank's padded query tensors, ...).
    Passing these as ARGUMENTS to the jitted refit core — re-bound onto
    the objective inside the trace — keeps the compiled program reusable
    across refit windows: fresh data of the same shapes hits the jit
    cache instead of retracing."""
    return {name: val for name, val in vars(obj).items()
            if isinstance(val, jnp.ndarray)}


class Refitter:
    """Reusable device refitter bound to one model's structure.

    Packs the forest once; each :meth:`refit` call routes a fresh data
    window through it and returns a new ``Booster`` with identical tree
    structures and re-estimated leaf values. Hold the instance across
    cycles (the fleet refit worker does) to reuse the compiled programs.
    """

    def __init__(self, booster):
        impl = booster._impl
        check(impl is not None and impl.models,
              "Cannot refit: no trained model")
        check(booster._objective is not None,
              "Cannot refit a model trained with a custom objective")
        self._model_str = booster.model_to_string()
        self._models = list(impl.models)
        self.k = max(int(impl.num_tree_per_iteration), 1)
        self.iterations = len(self._models) // self.k
        forest, depth = pack_flat_forest(self._models)
        self.depth = depth
        self._forest = jax.tree.map(jnp.asarray, forest)
        nleaves = forest.leaf_value.shape[1]
        self._nl = np.array(
            [int(getattr(t, "num_leaves_actual", t.num_leaves))
             for t in self._models], np.int32)
        # pre-pack the per-tree refit constants iteration-major [I, k, ...]
        self._old64 = [np.asarray(t.leaf_value, np.float64)
                       for t in self._models]
        self._old_leaf = jnp.asarray(
            forest.leaf_value.reshape(self.iterations, self.k, nleaves))
        self._shrink = jnp.asarray(np.array(
            [float(getattr(t, "shrinkage", 1.0)) for t in self._models],
            np.float32).reshape(self.iterations, self.k))
        self._mask = jnp.asarray(
            (np.arange(nleaves)[None, :] < self._nl[:, None])
            .reshape(self.iterations, self.k, nleaves))
        cfg = booster.config
        self._decay_default = float(cfg.refit_decay_rate)
        self._l1 = float(cfg.lambda_l1)
        self._l2 = float(cfg.lambda_l2)
        self._mds = float(cfg.max_delta_step)
        self._obj = copy.deepcopy(booster._objective)
        self._core = None
        # jitted once: an EAGER fori_loop re-traces per call (its body
        # closure is a fresh function object each time), which would leak
        # one compile per cycle; under jit the traversal is one cached
        # program keyed on (forest pytree, rows, depth)
        self._route = jax.jit(forest_leaf_ids, static_argnames="depth")

    # ------------------------------------------------------------ core
    def _raw_core(self):
        """The un-jitted scan-over-iterations refit program; one gradient
        refresh per boosting iteration from the running scores — the
        identical refresh schedule as the host path (c == i % k == 0)."""
        obj, k = self._obj, self.k
        l1, l2, mds = self._l1, self._l2, self._mds

        def core(leaves, old_leaf, shrink, mask, decay, attrs):
            for name, val in attrs.items():
                setattr(obj, name, val)
            n = leaves.shape[-1]
            nleaves = old_leaf.shape[-1]

            def seg(lf, v):
                return jnp.zeros((nleaves,), jnp.float32).at[lf].add(v)

            def body(scores, xs):
                lv, old, shr, msk = xs           # [k,N] [k,L] [k] [k,L]
                if k == 1:
                    g, h = obj.get_gradients(scores[:, 0])
                    g, h = g.reshape(1, -1), h.reshape(1, -1)
                else:
                    g, h = obj.get_gradients(scores)
                    g, h = g.T, h.T
                sg = jax.vmap(seg)(lv, g)        # [k, L]
                sh = jax.vmap(seg)(lv, h)
                out = -jnp.sign(sg) * jnp.maximum(jnp.abs(sg) - l1, 0.0) \
                    / (sh + l2 + _EPS)
                if mds > 0:
                    out = jnp.clip(out, -mds, mds)
                out = out * shr[:, None]
                new = jnp.where(msk, decay * old + (1.0 - decay) * out, old)
                upd = jax.vmap(lambda nw, lf: nw[lf])(new, lv)   # [k, N]
                return scores + upd.T, out

            scores0 = jnp.zeros((n, k), jnp.float32)
            _, outs = lax.scan(
                body, scores0, (leaves, old_leaf, shrink, mask))
            return outs                          # [I, k, L] pre-blend

        return core

    # ------------------------------------------------------------ refit
    def refit(self, data, label, decay_rate: Optional[float] = None,
              weight=None, group=None):
        """One refit cycle: returns a NEW Booster, structure-identical to
        the bound model, with leaf values re-estimated on ``data``."""
        from ..basic import Booster, _to_1d, _to_2d_float

        X = _to_2d_float(data)
        n = X.shape[0]
        decay = self._decay_default if decay_rate is None \
            else float(decay_rate)
        md = Metadata(n)
        md.set_label(_to_1d(label))
        if weight is not None:
            md.set_weight(_to_1d(weight))
        if group is not None:
            md.set_query(np.asarray(group, np.int64))
        self._obj.init(md, n)
        attrs = _objective_arrays(self._obj)

        leaves = self._route(self._forest, jnp.asarray(X, jnp.float32),
                             depth=self.depth)                  # [N, T]
        leaves = jnp.transpose(leaves).reshape(self.iterations, self.k, n)
        if self._core is None:
            self._core = jax.jit(self._raw_core())
        outs = np.asarray(self._core(
            leaves, self._old_leaf, self._shrink, self._mask,
            jnp.float32(decay), attrs))

        # stored values blend on HOST in f64 against the original doubles
        # (the scan's f32 blend only feeds the in-flight score refresh):
        # decay=1.0 reproduces the old leaf tables byte-for-byte
        new_trees = []
        for i, ht in enumerate(self._models):
            it, c = divmod(i, self.k)
            nl = self._nl[i]
            nh = copy.deepcopy(ht)
            nh.leaf_value = ht.leaf_value.copy()
            nh.leaf_value[:nl] = decay * self._old64[i][:nl] \
                + (1.0 - decay) * outs[it, c, :nl].astype(np.float64)
            new_trees.append(nh)
        refitted = Booster(model_str=self._model_str)
        refitted._impl.models = new_trees
        return refitted


def refit_booster(booster, data, label, decay_rate: Optional[float] = None,
                  weight=None, group=None):
    """One-shot device refit (``Booster.refit`` dispatches here for dense
    inputs); build a :class:`Refitter` directly to amortize packing and
    compilation across repeated cycles."""
    return Refitter(booster).refit(data, label, decay_rate=decay_rate,
                                   weight=weight, group=group)


def refit_audit_entry(booster, rows: int = 256
                      ) -> Tuple[Any, Tuple[Any, ...]]:
    """(fn, args) for the static-analysis gate: the refit core with
    ShapeDtypeStruct arguments at ``rows`` synthetic rows, traceable by
    ``jax.make_jaxpr`` without touching a device. Pins the program's
    structural fingerprint — zero f64 primitives, zero collectives, zero
    host callbacks — exactly like the serving predict entries."""
    r = Refitter(booster)
    md = Metadata(rows)
    md.set_label(np.zeros(rows, np.float32))
    r._obj.init(md, rows)
    sds = jax.ShapeDtypeStruct
    attrs = jax.tree_util.tree_map(
        lambda a: sds(a.shape, a.dtype), _objective_arrays(r._obj))
    nleaves = r._old_leaf.shape[-1]
    args = (sds((r.iterations, r.k, rows), jnp.int32),
            sds((r.iterations, r.k, nleaves), jnp.float32),
            sds((r.iterations, r.k), jnp.float32),
            sds((r.iterations, r.k, nleaves), jnp.bool_),
            sds((), jnp.float32), attrs)
    return r._raw_core(), args
