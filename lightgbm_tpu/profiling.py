"""Phase timing probes — the TIMETAG analog (serial_tree_learner.cpp:15-43).

The boosting iteration is one fused jit program, so per-phase time cannot be
read from inside it; instead each phase's op is re-run standalone on the
booster's real shapes and timed. The taxonomy mirrors the reference's
(init/hist/find-split/split) plus the TPU-specific partition/gather phase.
``jax.profiler`` traces can be layered on via trace_dir for a full timeline.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _timed(fn, *args, reps=3, **kw) -> float:
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def latency_summary(samples_ms) -> Dict[str, float]:
    """Quantile summary of a latency sample window (milliseconds) — the
    serving-side SLO view (p50/p90/p99) shared by serving.metrics and any
    offline analysis of its JSON-lines output."""
    a = np.asarray(list(samples_ms), np.float64)
    if a.size == 0:
        return {"count": 0, "mean_ms": 0.0, "p50_ms": 0.0, "p90_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}
    p50, p90, p99 = np.percentile(a, [50.0, 90.0, 99.0])
    return {"count": int(a.size), "mean_ms": round(float(a.mean()), 4),
            "p50_ms": round(float(p50), 4), "p90_ms": round(float(p90), 4),
            "p99_ms": round(float(p99), 4),
            "max_ms": round(float(a.max()), 4)}


def phase_probe(booster, trace_dir: Optional[str] = None) -> Dict[str, float]:
    """Per-phase seconds for one boosting iteration's building blocks, using
    the booster's actual data/shapes. Keys: grad, hist_full,
    partition_hist_fused, hist_leaf_half, find_split, plus frontier_hist /
    frontier_waves / frontier_sweeps_per_tree when the booster grows in
    frontier mode (docs/Performance.md describes each)."""
    from .core.histogram import build_histogram
    from .core.partition import (frontier_slots_from_partition, hist_for_leaf,
                                 init_partition, make_row_gather,
                                 partition_and_hist,
                                 sort_placement_profitable, stack_vals)
    from .core.split import find_best_split

    xb = booster.xb
    n = booster.num_data
    params = booster.grow_params
    meta = booster.feature_meta
    out: Dict[str, float] = {}

    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    try:
        scores = booster.scores
        if booster.objective is not None:
            obj = booster.objective
            if booster.num_tree_per_iteration == 1:
                grad_fn = jax.jit(lambda s: obj.get_gradients(s[:, 0]))
            else:
                grad_fn = jax.jit(lambda s: obj.get_gradients(s))
            out["grad"] = _timed(grad_fn, scores)
            g, h = grad_fn(scores)
            if g.ndim == 2:           # multiclass: probe class 0's tree
                g, h = g[:, 0], h[:, 0]
        else:
            g = jnp.zeros((n,), jnp.float32)
            h = jnp.ones((n,), jnp.float32)
        mask = jnp.ones((n,), jnp.float32)

        out["hist_full"] = _timed(
            build_histogram, xb, g, h, mask, num_bins=params.num_bins,
            row_chunk=params.row_chunk, impl=params.hist_impl)
        hist = build_histogram(xb, g, h, mask, num_bins=params.num_bins,
                               row_chunk=params.row_chunk,
                               impl=params.hist_impl)

        part = init_partition(n, params.num_leaves, params.row_chunk)
        # sized to the partition TILE, not n: the decision closure below
        # is sliced per row tile, which is row_chunk wide even when the
        # dataset is smaller
        half = jnp.asarray(
            np.arange(max(n, params.row_chunk), dtype=np.int64) % 2 == 0)
        # probe in f32 regardless of ambient x64: the gather closure owns
        # the packed bins/values boundary, so dtypes must be consistent
        gr = make_row_gather(
            xb, stack_vals(g.astype(jnp.float32), h.astype(jnp.float32),
                           mask.astype(jnp.float32)))
        ncols = xb.shape[1]
        # the real growth path: one fused pass that partitions the root and
        # prices both children — same placement selection as grow_tree
        # (sort path on device / pallas_interpret, scatter loop on CPU)
        use_sort = sort_placement_profitable(params.hist_impl,
                                             params.vmapped_classes)
        fused = jax.jit(lambda p: partition_and_hist(
            p, jnp.zeros((n,), jnp.int32), jnp.int32(0), jnp.int32(1),
            lambda rows: half[:rows.shape[0]],
            jnp.asarray(True), params.row_chunk, gr, ncols,
            params.num_bins, params.hist_impl, use_sort=use_sort))
        out["partition_hist_fused"] = _timed(lambda p: fused(p)[0], part)
        part2 = fused(part)[0]
        out["hist_leaf_half"] = _timed(
            jax.jit(lambda p: hist_for_leaf(
                p, jnp.int32(0), gr, n, ncols, params.num_bins,
                params.row_chunk, impl=params.hist_impl)), part2)

        if getattr(params, "frontier_mode", False):
            from .core.histogram import build_histogram_frontier
            # the frontier wave cost: the partition hands the builder the
            # wave's LEAF IDS and one leaf-indexed sweep prices them all —
            # probed at full wave width (every leaf can split)
            n_slots = max(params.num_leaves - 1, 1)
            slots = frontier_slots_from_partition(
                part2, jnp.arange(n_slots, dtype=jnp.int32), n)
            out["frontier_hist"] = _timed(
                build_histogram_frontier, xb, slots, g, h, mask,
                num_bins=params.num_bins, num_slots=n_slots,
                row_chunk=params.row_chunk, impl=params.hist_impl)
            # dataset sweeps per tree scale with DEPTH, not leaf count:
            # wave w splits the leaves created in wave w-1, so waves = max
            # leaf depth of the grown tree, sweeps = waves + 1 (the root)
            if booster.models:
                t0 = booster.models[0]
                waves = 0
                stack = [(0, 1)] if t0.num_leaves > 1 else []
                while stack:
                    nd, d = stack.pop()
                    for ch in (int(t0.left_child[nd]),
                               int(t0.right_child[nd])):
                        if ch < 0:       # ~leaf encoding: negative = leaf
                            waves = max(waves, d)
                        else:
                            stack.append((ch, d + 1))
                out["frontier_waves"] = float(waves)
                out["frontier_sweeps_per_tree"] = float(waves + 1)

        sum_g = jnp.sum(g)
        sum_h = jnp.sum(h)
        cnt = jnp.asarray(float(n), jnp.float32)
        fmask = jnp.ones((meta.num_bin.shape[0],), bool)
        split_fn = jax.jit(lambda hh: find_best_split(
            hh, meta, params.split, sum_g, sum_h, cnt, fmask,
            with_categorical=params.with_categorical))
        # find_split works on per-feature views; without EFB hist == view
        if not params.with_efb:
            out["find_split"] = _timed(split_fn, hist)

        # checkpoint overhead (lightgbm_tpu.checkpoint): one full-state
        # snapshot save + restore on the booster's real model/shapes, so
        # the per-period cost shows up next to the phases it competes with
        out.update(_checkpoint_probe(booster))
    finally:
        if trace_dir:
            jax.profiler.stop_trace()
    return {k: round(v, 5) for k, v in out.items()}


def _checkpoint_probe(booster) -> Dict[str, float]:
    """checkpoint_save_s / checkpoint_restore_s: wall time of one snapshot
    write (state npz + manifest + model text) and one verified load back
    into the same driver. Restoring the state it just saved is a no-op for
    the booster. Empty dict when the booster has no trained trees yet."""
    import shutil
    import tempfile
    try:
        if not booster.models:
            return {}
        from .checkpoint.manager import CheckpointManager
        tmp = tempfile.mkdtemp(prefix="lgbm_tpu_ckpt_probe_")
        try:
            mgr = CheckpointManager(tmp, keep_last_n=1)
            t0 = time.perf_counter()
            mgr.save(booster)
            save_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            handle = mgr.load_latest()
            booster.load_training_state(handle.meta, handle.arrays)
            restore_s = time.perf_counter() - t0
            return {"checkpoint_save_s": save_s,
                    "checkpoint_restore_s": restore_s}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception:  # noqa: BLE001 - a probe must not kill the caller
        return {}
